"""Upstream v1.26 scheduler plugin semantics, re-implemented per-pod.

Each plugin is a set of pure functions over (CycleContext, PodView,
NodeInfo). The enumerated plugin set is pinned by the reference's golden
test (simulator/scheduler/plugin/plugins_test.go:852-884); the semantics are
re-derived from the upstream kube-scheduler v1.26 behavior the reference
vendors (SURVEY.md §2 #14). The TPU kernels in ops/ are property-tested
against these functions.

Filter functions return None on pass, or the failure reason string (the
message the reference shows in its filter-result annotation). Score
functions return raw scores; normalize functions apply each plugin's
NormalizeScore pass. DefaultNormalizeScore here mirrors the upstream helper
(max-scaling to [0,100], optionally reversed).
"""

from __future__ import annotations

import math
from typing import TYPE_CHECKING, Callable

from ..models.objects import (
    PodView,
    match_label_selector,
    match_node_selector_terms,
    pod_scoring_requests,
    tolerations_tolerate_taint,
)

if TYPE_CHECKING:  # pragma: no cover
    from .oracle import CycleContext, NodeInfo, Oracle
    from .results import PodSchedulingResult

from .config import MAX_NODE_SCORE
from .resources import to_int_resources


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

def default_normalize_score(
    raw: dict[str, int], reverse: bool = False, max_priority: int = MAX_NODE_SCORE
) -> dict[str, int]:
    """Upstream helper.DefaultNormalizeScore: scale by the max to
    [0, max_priority]; if reverse, flip (used by TaintToleration)."""
    max_count = max(raw.values(), default=0)
    if max_count == 0:
        if reverse:
            return {k: max_priority for k in raw}
        return dict(raw)
    out = {}
    for k, score in raw.items():
        s = max_priority * score // max_count
        if reverse:
            s = max_priority - s
        out[k] = s
    return out


def _pod_fit_resources(pod: PodView) -> dict[str, int]:
    return to_int_resources(pod.requests)


def _namespaces_for_term(term: dict, owner_ns: str, snapshot) -> "set[str] | None":
    """Resolve an affinity term's namespace set. None means "all namespaces"
    (a present-but-empty namespaceSelector). Defaults to the owner pod's
    namespace when neither namespaces nor namespaceSelector is given."""
    namespaces = set(term.get("namespaces") or [])
    ns_selector = term.get("namespaceSelector")
    if ns_selector is not None:
        if ns_selector == {} or (
            not ns_selector.get("matchLabels") and not ns_selector.get("matchExpressions")
        ):
            return None  # empty selector matches every namespace
        for ns_name, ns_obj in snapshot.namespaces.items():
            labels = (ns_obj.get("metadata", {}) or {}).get("labels") or {}
            if match_label_selector(ns_selector, labels):
                namespaces.add(ns_name)
    if not namespaces and ns_selector is None:
        namespaces = {owner_ns}
    return namespaces


def _term_matches_pod(term: dict, owner_ns: str, other: PodView, snapshot) -> bool:
    """Does an affinity term (owned by a pod in owner_ns) select `other`?"""
    ns = _namespaces_for_term(term, owner_ns, snapshot)
    if ns is not None and other.namespace not in ns:
        return False
    return match_label_selector(term.get("labelSelector"), other.labels)


# ---------------------------------------------------------------------------
# NodeResourcesFit
# ---------------------------------------------------------------------------

def fit_pre_filter(ctx: "CycleContext", pod: PodView) -> "str | None":
    ctx.state["fit.requests"] = _pod_fit_resources(pod)
    return None


def fit_filter(ctx: "CycleContext", pod: PodView, ni: "NodeInfo") -> "str | None":
    req = ctx.state.get("fit.requests")
    if req is None:
        req = _pod_fit_resources(pod)
    allowed_pods = ni.allocatable.get("pods", 0)
    if len(ni.pods) + 1 > allowed_pods:
        return "Too many pods"
    for name, v in req.items():
        if v == 0:
            continue
        free = ni.allocatable.get(name, 0) - ni.requested.get(name, 0)
        if v > free:
            return f"Insufficient {name}"
    return None


def _trunc_div(a: int, b: int) -> int:
    """Go's int64 division truncates toward zero; Python's // floors —
    they differ on negative dividends (downward shape slopes)."""
    q = abs(a) // b
    return -q if a < 0 else q


def rtcr_shape(strategy: dict) -> list[tuple[int, int]]:
    """The RequestedToCapacityRatio shape points, scaled the upstream way:
    user scores are 0..10 (MaxCustomPriorityScore) and are multiplied by
    MaxNodeScore/10 when the scorer is built; sorted by utilization."""
    pts = (strategy.get("requestedToCapacityRatio") or {}).get("shape") or [
        {"utilization": 0, "score": 0},
        {"utilization": 100, "score": 10},
    ]
    return sorted(
        (int(p.get("utilization", 0)), int(p.get("score", 0)) * (MAX_NODE_SCORE // 10))
        for p in pts
    )


def broken_linear(shape: list[tuple[int, int]], u: int) -> int:
    """Upstream helper.BuildBrokenLinearFunction: clamp outside the shape,
    integer linear interpolation (trunc division) between points."""
    if u < shape[0][0]:
        return shape[0][1]
    for (x1, y1), (x2, y2) in zip(shape, shape[1:]):
        if u < x2:
            return _trunc_div((u - x1) * (y2 - y1), max(x2 - x1, 1)) + y1
    return shape[-1][1]


def fit_score(ctx: "CycleContext", pod: PodView, ni: "NodeInfo") -> int:
    """ScoringStrategy LeastAllocated (the default) / MostAllocated /
    RequestedToCapacityRatio: per configured resource a 0..100 score,
    weight-averaged. Requested includes existing pods' non-zero requests
    plus this pod's. RequestedToCapacityRatio evaluates the broken-linear
    shape at utilization = requested*100/capacity (over-capacity and
    zero-capacity nodes evaluate the shape at 100, upstream
    resourceScoringFunction)."""
    args = ctx.args("NodeResourcesFit")
    strategy = (args.get("scoringStrategy") or {})
    resources = strategy.get("resources") or [
        {"name": "cpu", "weight": 1},
        {"name": "memory", "weight": 1},
    ]
    stype = strategy.get("type", "LeastAllocated")
    shape = rtcr_shape(strategy) if stype == "RequestedToCapacityRatio" else None
    pod_req = to_int_resources(pod_scoring_requests(pod.obj))
    score_sum = 0
    weight_sum = 0
    for spec in resources:
        rname, weight = spec["name"], int(spec.get("weight", 1))
        requested = ni.nonzero_requested.get(rname, 0) + pod_req.get(rname, 0)
        capacity = ni.allocatable.get(rname, 0)
        if stype == "RequestedToCapacityRatio":
            if capacity == 0 or requested > capacity:
                u = 100
            else:
                u = requested * 100 // capacity
            r_score = broken_linear(shape, u)
        elif capacity == 0 or requested > capacity:
            r_score = 0
        elif stype == "MostAllocated":
            r_score = requested * MAX_NODE_SCORE // capacity
        else:  # LeastAllocated
            r_score = (capacity - requested) * MAX_NODE_SCORE // capacity
        score_sum += r_score * weight
        weight_sum += weight
    return score_sum // weight_sum if weight_sum else 0


# ---------------------------------------------------------------------------
# NodeResourcesBalancedAllocation
# ---------------------------------------------------------------------------

# Usage fractions are quantized to 1/2^16 so the score is decided purely by
# integer arithmetic. Upstream computes float64 std (balancedResourceScorer);
# float division is not bit-portable across compilers (XLA lowers f64 divide
# to a non-IEEE reciprocal sequence), so this framework defines the score in
# exact integers instead: results can differ from upstream Go by at most 1
# point when a usage fraction straddles a 2^-16 quantum. Documented
# divergence, same class as the selectHost tie-break (see sched/oracle.py).
BALANCED_SCALE = 1 << 16


def balanced_allocation_score(ctx: "CycleContext", pod: PodView, ni: "NodeInfo") -> int:
    """score = floor((1 - std(fractions)) * 100), fractions capped at 1 and
    quantized to 1/BALANCED_SCALE; for two resources std = |f0 - f1| / 2
    (upstream balancedResourceScorer, in exact integer arithmetic)."""
    args = ctx.args("NodeResourcesBalancedAllocation")
    resources = args.get("resources") or [
        {"name": "cpu", "weight": 1},
        {"name": "memory", "weight": 1},
    ]
    pod_req = to_int_resources(pod_scoring_requests(pod.obj))
    S = BALANCED_SCALE
    q: list[int] = []
    for spec in resources:
        rname = spec["name"]
        capacity = ni.allocatable.get(rname, 0)
        if capacity == 0:
            continue
        requested = ni.nonzero_requested.get(rname, 0) + pod_req.get(rname, 0)
        q.append(min(requested * S // capacity, S))
    nf = len(q)
    if nf < 2:
        return MAX_NODE_SCORE
    if nf == 2:
        d = abs(q[0] - q[1])  # std = d / (2S)
        return (200 * S - 100 * d) // (2 * S)
    # std = sqrt(A) / (nf*S) with A = nf*Σq² - (Σq)²;
    # floor(100*(1-std)) = 100 - ceil(100*sqrt(A)/(nf*S)), computed exactly
    # via integer sqrt: ceil(sqrt(x)/D) == isqrt(x-1)//D + 1 for x > 0.
    A = nf * sum(x * x for x in q) - sum(q) ** 2
    x2 = 10000 * A
    if x2 == 0:
        return MAX_NODE_SCORE
    return MAX_NODE_SCORE - (math.isqrt(x2 - 1) // (nf * S) + 1)


# ---------------------------------------------------------------------------
# NodeName / NodeUnschedulable
# ---------------------------------------------------------------------------

def node_name_filter(ctx: "CycleContext", pod: PodView, ni: "NodeInfo") -> "str | None":
    if pod.node_name and pod.node_name != ni.node.name:
        return "node(s) didn't match the requested node name"
    return None


def node_unschedulable_filter(ctx, pod: PodView, ni: "NodeInfo") -> "str | None":
    if not ni.node.unschedulable:
        return None
    tolerated = tolerations_tolerate_taint(
        pod.tolerations,
        {"key": "node.kubernetes.io/unschedulable", "effect": "NoSchedule"},
    )
    if tolerated:
        return None
    return "node(s) were unschedulable"


# ---------------------------------------------------------------------------
# TaintToleration
# ---------------------------------------------------------------------------

def taint_toleration_filter(ctx, pod: PodView, ni: "NodeInfo") -> "str | None":
    for taint in ni.node.taints:
        if taint.get("effect") not in ("NoSchedule", "NoExecute"):
            continue
        if not tolerations_tolerate_taint(pod.tolerations, taint):
            return (
                "node(s) had untolerated taint "
                f"{{{taint.get('key', '')}: {taint.get('value', '')}}}"
            )
    return None


def taint_toleration_score(ctx, pod: PodView, ni: "NodeInfo") -> int:
    """Raw score = count of intolerable PreferNoSchedule taints."""
    count = 0
    for taint in ni.node.taints:
        if taint.get("effect") != "PreferNoSchedule":
            continue
        if not tolerations_tolerate_taint(pod.tolerations, taint):
            count += 1
    return count


def taint_toleration_normalize(ctx, pod: PodView, raw: dict[str, int]) -> dict[str, int]:
    return default_normalize_score(raw, reverse=True)


# ---------------------------------------------------------------------------
# NodeAffinity
# ---------------------------------------------------------------------------

def node_affinity_filter(ctx, pod: PodView, ni: "NodeInfo") -> "str | None":
    node = ni.node
    selector = pod.node_selector
    if selector:
        if any(node.labels.get(k) != v for k, v in selector.items()):
            return "node(s) didn't match Pod's node affinity/selector"
    required = (
        pod.node_affinity.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    )
    terms = required.get("nodeSelectorTerms") or []
    if terms and not match_node_selector_terms(terms, node):
        return "node(s) didn't match Pod's node affinity/selector"
    return None


def node_affinity_score(ctx, pod: PodView, ni: "NodeInfo") -> int:
    """Sum of weights of matching preferred terms."""
    total = 0
    preferred = (
        pod.node_affinity.get("preferredDuringSchedulingIgnoredDuringExecution") or []
    )
    for pref in preferred:
        term = pref.get("preference") or {}
        weight = int(pref.get("weight", 0))
        if match_node_selector_terms([term], ni.node):
            total += weight
    return total


def node_affinity_normalize(ctx, pod: PodView, raw: dict[str, int]) -> dict[str, int]:
    return default_normalize_score(raw, reverse=False)


# ---------------------------------------------------------------------------
# NodePorts
# ---------------------------------------------------------------------------

def _ports_conflict(a: tuple[str, str, int], b: tuple[str, str, int]) -> bool:
    proto_a, ip_a, port_a = a
    proto_b, ip_b, port_b = b
    if port_a != port_b or proto_a != proto_b:
        return False
    return ip_a == ip_b or ip_a == "0.0.0.0" or ip_b == "0.0.0.0"


def node_ports_pre_filter(ctx: "CycleContext", pod: PodView) -> "str | None":
    ctx.state["ports.want"] = pod.host_ports
    return None


def node_ports_filter(ctx, pod: PodView, ni: "NodeInfo") -> "str | None":
    want = ctx.state.get("ports.want")
    if want is None:
        want = pod.host_ports
    if not want:
        return None
    used = ni.used_host_ports()
    for w in want:
        if any(_ports_conflict(w, u) for u in used):
            return "node(s) didn't have free ports for the requested pod ports"
    return None


# ---------------------------------------------------------------------------
# PodTopologySpread
# ---------------------------------------------------------------------------

_SYSTEM_DEFAULT_CONSTRAINTS = [
    {"maxSkew": 3, "topologyKey": "topology.kubernetes.io/zone", "whenUnsatisfiable": "ScheduleAnyway"},
    {"maxSkew": 5, "topologyKey": "kubernetes.io/hostname", "whenUnsatisfiable": "ScheduleAnyway"},
]

# Spread score weights log(topoSize+2) are quantized to 1/2^12 fixed-point
# (computed host-side by this exact Python expression on both the oracle
# and engine paths) so the score is decided by integer arithmetic — same
# float-portability rationale as BALANCED_SCALE. Divergence from upstream's
# float64 math is bounded by the quantization (<0.1%% of a raw score).
SPREAD_SCALE = 1 << 12


def spread_log_weight(m: int) -> int:
    """floor(log(m+2) * 2^12) — the fixed-point topology weight."""
    return int(math.log(m + 2) * SPREAD_SCALE)


def round_half_even_div(x: int, d: int) -> int:
    """round(x/d) with banker's rounding, for x >= 0, d > 0 — the integer
    equivalent of Python round() on the quantized spread total."""
    q, r = divmod(x, d)
    if 2 * r > d:
        return q + 1
    if 2 * r == d:
        return q + (q & 1)
    return q


def resolve_spread_constraints(
    explicit: list[dict], args: dict
) -> tuple[list[dict], list[dict], bool]:
    """(hard, soft, is_explicit) — the constraint resolution shared by the
    oracle and the engine encoder.

    System defaulting (PodTopologySpreadArgs.defaultingType=System): two
    ScheduleAnyway constraints whose selector is derived from the pod's
    owning services/controllers. The simulator's store has no Service kind
    (same as the reference's 7 watched kinds), so the derived selector
    matches nothing — defaults contribute uniformly to scores."""
    if explicit:
        source = explicit
    elif args.get("defaultingType", "System") == "System":
        source = _SYSTEM_DEFAULT_CONSTRAINTS
    else:
        source = args.get("defaultConstraints") or []
    hard = [
        c for c in source
        if (c.get("whenUnsatisfiable") or "DoNotSchedule") == "DoNotSchedule"
    ]
    soft = [
        c for c in source
        if (c.get("whenUnsatisfiable") or "DoNotSchedule") == "ScheduleAnyway"
    ]
    return hard, soft, bool(explicit)


def _spread_constraints(ctx, pod: PodView, when: str) -> list[dict]:
    hard, soft, _ = resolve_spread_constraints(
        pod.topology_spread_constraints, ctx.args("PodTopologySpread")
    )
    return hard if when == "DoNotSchedule" else soft


def _node_eligible_for_spread(pod: PodView, ni: "NodeInfo") -> bool:
    """Nodes counted for min-match: must satisfy the pod's nodeSelector and
    required node affinity (upstream requiredNodeAffinity in PreFilter)."""
    return node_affinity_filter(None, pod, ni) is None


def _count_matching_pods(ni: "NodeInfo", selector: "dict | None", namespace: str, self_labels_match=None) -> int:
    if selector is None:
        return 0
    count = 0
    for p in ni.pods:
        if p.namespace != namespace:
            continue
        if p.obj.get("metadata", {}).get("deletionTimestamp"):
            continue
        if match_label_selector(selector, p.labels):
            count += 1
    return count


def spread_pre_filter(ctx: "CycleContext", pod: PodView) -> "str | None":
    constraints = _spread_constraints(ctx, pod, "DoNotSchedule")
    state: dict = {"constraints": constraints, "counts": {}, "mins": {}}
    ctx.state["spread.filter"] = state
    if not constraints:
        return None
    nodes = ctx.snapshot.node_list()
    for c in constraints:
        key = c["topologyKey"]
        sel = c.get("labelSelector")
        counts: dict[str, int] = {}
        for ni in nodes:
            if not _node_eligible_for_spread(pod, ni):
                continue
            if key not in ni.node.labels:
                continue
            # all constraint keys must be present for min-candidate nodes
            if any(c2["topologyKey"] not in ni.node.labels for c2 in constraints):
                continue
            val = ni.node.labels[key]
            counts[val] = counts.get(val, 0) + _count_matching_pods(ni, sel, pod.namespace)
        state["counts"][key] = counts
        state["mins"][key] = min(counts.values()) if counts else 0
    return None


def spread_filter(ctx, pod: PodView, ni: "NodeInfo") -> "str | None":
    state = ctx.state.get("spread.filter")
    if state is None:
        spread_pre_filter(ctx, pod)
        state = ctx.state["spread.filter"]
    constraints = state["constraints"]
    if not constraints:
        return None
    for c in constraints:
        key = c["topologyKey"]
        if key not in ni.node.labels:
            return "node(s) didn't match pod topology spread constraints (missing required label)"
        val = ni.node.labels[key]
        match_num = state["counts"][key].get(val, 0)
        self_match = 1 if match_label_selector(c.get("labelSelector"), pod.labels) else 0
        skew = match_num + self_match - state["mins"][key]
        if skew > int(c.get("maxSkew", 1)):
            return "node(s) didn't match pod topology spread constraints"
    return None


def spread_pre_score(ctx: "CycleContext", pod: PodView, feasible: list) -> "str | None":
    constraints = _spread_constraints(ctx, pod, "ScheduleAnyway")
    state: dict = {"constraints": constraints, "ignored": set(), "counts": {}, "weights": []}
    ctx.state["spread.score"] = state
    if not constraints:
        return None
    # requireAllTopologies: true when the pod carries explicit constraints
    require_all = bool(pod.topology_spread_constraints)
    topo_size = [0] * len(constraints)
    eligible_pairs: list[dict[str, int]] = [dict() for _ in constraints]
    for ni in feasible:
        if require_all and any(
            c["topologyKey"] not in ni.node.labels for c in constraints
        ):
            state["ignored"].add(ni.node.name)
            continue
        for i, c in enumerate(constraints):
            key = c["topologyKey"]
            if key == "kubernetes.io/hostname":
                continue
            val = ni.node.labels.get(key)
            if val is None:
                continue
            if val not in eligible_pairs[i]:
                eligible_pairs[i][val] = 0
                topo_size[i] += 1
    # count matching pods over ALL nodes that satisfy node affinity (+ keys)
    for ni in ctx.snapshot.node_list():
        if not _node_eligible_for_spread(pod, ni):
            continue
        if require_all and any(c["topologyKey"] not in ni.node.labels for c in constraints):
            continue
        for i, c in enumerate(constraints):
            key = c["topologyKey"]
            if key == "kubernetes.io/hostname":
                continue
            val = ni.node.labels.get(key)
            if val is None or val not in eligible_pairs[i]:
                continue
            eligible_pairs[i][val] += _count_matching_pods(ni, c.get("labelSelector"), pod.namespace)
    state["counts"] = eligible_pairs
    n_scored = len(feasible) - len(state["ignored"])
    state["weights"] = [
        spread_log_weight(
            n_scored if c["topologyKey"] == "kubernetes.io/hostname" else topo_size[i]
        )
        for i, c in enumerate(constraints)
    ]
    return None


def spread_score(ctx, pod: PodView, ni: "NodeInfo") -> int:
    state = ctx.state.get("spread.score")
    if state is None or not state["constraints"]:
        return 0
    if ni.node.name in state["ignored"]:
        return 0
    total_q = 0  # Σ cnt * w_q, in 1/SPREAD_SCALE units
    ms_sum = 0  # Σ (maxSkew - 1), exact integer part
    for i, c in enumerate(state["constraints"]):
        key = c["topologyKey"]
        val = ni.node.labels.get(key)
        if val is None:
            continue
        if key == "kubernetes.io/hostname":
            cnt = _count_matching_pods(ni, c.get("labelSelector"), pod.namespace)
        else:
            pair_counts = state["counts"][i]
            if val not in pair_counts:
                continue
            cnt = pair_counts[val]
        total_q += cnt * state["weights"][i]
        ms_sum += int(c.get("maxSkew", 1)) - 1
    # round(Σ cnt*w + Σ(ms-1)) == Σ(ms-1) + round(Σ cnt*w_q / SCALE)
    return ms_sum + round_half_even_div(total_q, SPREAD_SCALE)


def spread_normalize(ctx, pod: PodView, raw: dict[str, int]) -> dict[str, int]:
    state = ctx.state.get("spread.score") or {"constraints": [], "ignored": set()}
    if not state["constraints"]:
        return {k: 0 for k in raw}
    ignored = state["ignored"]
    live = [s for n, s in raw.items() if n not in ignored]
    if not live:
        return {k: 0 for k in raw}
    min_score, max_score = min(live), max(live)
    out = {}
    for node, s in raw.items():
        if node in ignored:
            out[node] = 0
        elif max_score == 0:
            out[node] = MAX_NODE_SCORE
        else:
            out[node] = MAX_NODE_SCORE * (max_score + min_score - s) // max_score
    return out


# ---------------------------------------------------------------------------
# InterPodAffinity
# ---------------------------------------------------------------------------

def _required_terms(affinity: dict) -> list[dict]:
    return affinity.get("requiredDuringSchedulingIgnoredDuringExecution") or []


def _preferred_terms(affinity: dict) -> list[dict]:
    return affinity.get("preferredDuringSchedulingIgnoredDuringExecution") or []


def interpod_pre_filter(ctx: "CycleContext", pod: PodView) -> "str | None":
    snapshot = ctx.snapshot
    affinity_terms = _required_terms(pod.pod_affinity)
    anti_terms = _required_terms(pod.pod_anti_affinity)
    # counts per (term index, topology value) for the incoming pod's terms,
    # and per (topologyKey, value) for existing pods' required anti-affinity
    affinity_counts: dict[tuple[int, str, str], int] = {}
    anti_counts: dict[tuple[int, str, str], int] = {}
    existing_anti: dict[tuple[str, str], int] = {}
    for ni in snapshot.node_list():
        node_labels = ni.node.labels
        for other in ni.pods:
            for i, term in enumerate(affinity_terms):
                if _term_matches_pod(term, pod.namespace, other, snapshot):
                    key = term.get("topologyKey", "")
                    if key in node_labels:
                        k = (i, key, node_labels[key])
                        affinity_counts[k] = affinity_counts.get(k, 0) + 1
            for i, term in enumerate(anti_terms):
                if _term_matches_pod(term, pod.namespace, other, snapshot):
                    key = term.get("topologyKey", "")
                    if key in node_labels:
                        k = (i, key, node_labels[key])
                        anti_counts[k] = anti_counts.get(k, 0) + 1
            # symmetry: existing pods' required anti-affinity vs incoming pod
            for term in _required_terms(PodView(other.obj).pod_anti_affinity):
                if _term_matches_pod(term, other.namespace, pod, snapshot):
                    key = term.get("topologyKey", "")
                    if key in node_labels:
                        k2 = (key, node_labels[key])
                        existing_anti[k2] = existing_anti.get(k2, 0) + 1
    ctx.state["interpod"] = {
        "affinity_terms": affinity_terms,
        "anti_terms": anti_terms,
        "affinity_counts": affinity_counts,
        "anti_counts": anti_counts,
        "existing_anti": existing_anti,
    }
    return None


def interpod_filter(ctx, pod: PodView, ni: "NodeInfo") -> "str | None":
    state = ctx.state.get("interpod")
    if state is None:
        interpod_pre_filter(ctx, pod)
        state = ctx.state["interpod"]
    node_labels = ni.node.labels
    # 1. existing pods' required anti-affinity
    for (key, val), cnt in state["existing_anti"].items():
        if cnt > 0 and node_labels.get(key) == val:
            return "node(s) didn't satisfy existing pods anti-affinity rules"
    # 2. incoming pod's required anti-affinity
    for i, term in enumerate(state["anti_terms"]):
        key = term.get("topologyKey", "")
        if key not in node_labels:
            continue
        if state["anti_counts"].get((i, key, node_labels[key]), 0) > 0:
            return "node(s) didn't match pod anti-affinity rules"
    # 3. incoming pod's required affinity
    terms = state["affinity_terms"]
    if terms:
        satisfied = True
        for i, term in enumerate(terms):
            key = term.get("topologyKey", "")
            if key not in node_labels or state["affinity_counts"].get(
                (i, key, node_labels[key]), 0
            ) <= 0:
                satisfied = False
                break
        if not satisfied:
            # first-pod-in-series rule: nothing matches anywhere AND the pod
            # matches its own terms — only on nodes that carry every
            # requested topology key (upstream satisfyPodAffinity fails
            # key-less nodes before the special case is considered)
            if (
                not state["affinity_counts"]
                and all(t.get("topologyKey", "") in node_labels for t in terms)
                and all(
                    _term_matches_pod(t, pod.namespace, pod, ctx.snapshot)
                    for t in terms
                )
            ):
                return None
            return "node(s) didn't match pod affinity rules"
    return None


def interpod_pre_score(ctx: "CycleContext", pod: PodView, feasible: list) -> "str | None":
    snapshot = ctx.snapshot
    hard_weight = int(ctx.args("InterPodAffinity").get("hardPodAffinityWeight", 1))
    topology_score: dict[tuple[str, str], int] = {}

    def add(term: dict, owner_ns: str, target: PodView, node_labels: dict, weight: int):
        if weight == 0:
            return
        if _term_matches_pod(term, owner_ns, target, snapshot):
            key = term.get("topologyKey", "")
            if key in node_labels:
                k = (key, node_labels[key])
                topology_score[k] = topology_score.get(k, 0) + weight

    incoming_pref_aff = _preferred_terms(pod.pod_affinity)
    incoming_pref_anti = _preferred_terms(pod.pod_anti_affinity)
    has_any = bool(incoming_pref_aff or incoming_pref_anti)
    for ni in snapshot.node_list():
        node_labels = ni.node.labels
        for other in ni.pods:
            opv = PodView(other.obj)
            # incoming pod's preferred terms vs existing pod
            for pref in incoming_pref_aff:
                add(pref.get("podAffinityTerm") or {}, pod.namespace, opv, node_labels, int(pref.get("weight", 0)))
            for pref in incoming_pref_anti:
                add(pref.get("podAffinityTerm") or {}, pod.namespace, opv, node_labels, -int(pref.get("weight", 0)))
            # existing pod's preferred terms vs incoming pod
            for pref in _preferred_terms(opv.pod_affinity):
                add(pref.get("podAffinityTerm") or {}, opv.namespace, pod, node_labels, int(pref.get("weight", 0)))
                has_any = True
            for pref in _preferred_terms(opv.pod_anti_affinity):
                add(pref.get("podAffinityTerm") or {}, opv.namespace, pod, node_labels, -int(pref.get("weight", 0)))
                has_any = True
            # existing pod's REQUIRED affinity, counted at hardPodAffinityWeight
            if hard_weight > 0:
                for term in _required_terms(opv.pod_affinity):
                    add(term, opv.namespace, pod, node_labels, hard_weight)
                    has_any = True
    ctx.state["interpod.score"] = {"topology_score": topology_score, "active": has_any or bool(topology_score)}
    return None


def interpod_score(ctx, pod: PodView, ni: "NodeInfo") -> int:
    state = ctx.state.get("interpod.score")
    if not state or not state["topology_score"]:
        return 0
    node_labels = ni.node.labels
    total = 0
    for (key, val), w in state["topology_score"].items():
        if node_labels.get(key) == val:
            total += w
    return total


def interpod_normalize(ctx, pod: PodView, raw: dict[str, int]) -> dict[str, int]:
    state = ctx.state.get("interpod.score")
    if not state or not state["topology_score"]:
        return {k: 0 for k in raw}
    min_c, max_c = min(raw.values()), max(raw.values())
    diff = max_c - min_c
    # integer floor-div (values nonneg) — float-portability, see SPREAD_SCALE
    return {
        k: MAX_NODE_SCORE * (v - min_c) // diff if diff > 0 else 0
        for k, v in raw.items()
    }


# ---------------------------------------------------------------------------
# ImageLocality
# ---------------------------------------------------------------------------

# Thresholds in Ki units (they are Mi multiples, so exact): this framework
# defines the ImageLocality sum in Ki so every intermediate fits int32 on
# the TPU (same portability rationale as BALANCED_SCALE above). Container
# counts clamp at 64 so 100*(sum-min) stays in range; divergence from
# upstream's byte-granular float math is at most 1 point.
_IMG_MIN_KI = 23 * 1024
_IMG_MAX_CONTAINER_KI = 1000 * 1024
_IMG_MAX_CONTAINERS = 64


def _normalized_image_name(name: str) -> str:
    if ":" not in name.rsplit("/", 1)[-1]:
        name = name + ":latest"
    return name


def image_locality_score(ctx, pod: PodView, ni: "NodeInfo") -> int:
    nodes = ctx.snapshot.node_list()
    total_nodes = len(nodes)
    if total_nodes == 0 or pod.num_containers == 0:
        return 0
    # image → (size, how many nodes have it)
    sum_scores = 0
    for image in pod.container_images:
        want = _normalized_image_name(image)
        size = 0
        have = 0
        for other in nodes:
            found = False
            for names, sz in other.node.images:
                if any(_normalized_image_name(n) == want for n in names):
                    found = True
                    if other is ni:
                        size = sz
            if found:
                have += 1
        if size:
            # per-image Ki contribution, integer floor-div — see the
            # threshold comment above for why not byte-granular floats
            sum_scores += (size * have // total_nodes) >> 10
    ncont = min(pod.num_containers, _IMG_MAX_CONTAINERS)
    max_threshold = _IMG_MAX_CONTAINER_KI * ncont
    sum_scores = min(max(sum_scores, _IMG_MIN_KI), max_threshold)
    return MAX_NODE_SCORE * (sum_scores - _IMG_MIN_KI) // (max_threshold - _IMG_MIN_KI)


# ---------------------------------------------------------------------------
# Volume plugins
# ---------------------------------------------------------------------------

def _pod_pvcs(ctx, pod: PodView) -> "list[tuple[str, dict | None]]":
    out = []
    for claim in pod.pvc_names:
        out.append((claim, ctx.snapshot.pvcs.get(f"{pod.namespace}/{claim}")))
    return out


def volume_binding_pre_filter(ctx: "CycleContext", pod: PodView) -> "str | None":
    for claim, pvc in _pod_pvcs(ctx, pod):
        if pvc is None:
            return f'persistentvolumeclaim "{claim}" not found'
    return None


def _pv_matches_node(pv: dict, ni: "NodeInfo") -> bool:
    required = ((pv.get("spec", {}) or {}).get("nodeAffinity") or {}).get("required")
    if not required:
        return True
    return match_node_selector_terms(required.get("nodeSelectorTerms") or [], ni.node)


def volume_binding_filter(ctx, pod: PodView, ni: "NodeInfo") -> "str | None":
    snapshot = ctx.snapshot
    for claim, pvc in _pod_pvcs(ctx, pod):
        if pvc is None:
            return f'persistentvolumeclaim "{claim}" not found'
        spec = pvc.get("spec", {}) or {}
        bound_pv_name = spec.get("volumeName")
        if bound_pv_name:
            pv = snapshot.pvs.get(bound_pv_name)
            if pv is not None and not _pv_matches_node(pv, ni):
                return "node(s) had volume node affinity conflict"
            continue
        sc_name = spec.get("storageClassName")
        sc = snapshot.storageclasses.get(sc_name) if sc_name else None
        if sc is not None and sc.get("volumeBindingMode") == "WaitForFirstConsumer":
            continue  # provisioning deferred to this node
        # Immediate binding: a compatible unbound PV must exist for this node
        if not any(
            _static_pv_matches(pv, pvc) and _pv_matches_node(pv, ni)
            for pv in snapshot.pvs.values()
        ):
            return "node(s) didn't find available persistent volumes to bind"
    return None


def _static_pv_matches(pv: dict, pvc: dict) -> bool:
    pv_spec = pv.get("spec", {}) or {}
    pvc_spec = pvc.get("spec", {}) or {}
    if (pv_spec.get("claimRef") or {}).get("name") not in (None, (pvc.get("metadata", {}) or {}).get("name")):
        return False
    if (pv_spec.get("storageClassName") or "") != (pvc_spec.get("storageClassName") or ""):
        return False
    want_modes = set(pvc_spec.get("accessModes") or [])
    if want_modes and not want_modes.issubset(set(pv_spec.get("accessModes") or [])):
        return False
    from ..utils.quantity import parse_quantity

    want = (pvc_spec.get("resources") or {}).get("requests", {}).get("storage")
    have = (pv_spec.get("capacity") or {}).get("storage")
    if want and have and parse_quantity(have).value < parse_quantity(want).value:
        return False
    sel = pvc_spec.get("selector")
    if sel is not None and not match_label_selector(sel, (pv.get("metadata", {}) or {}).get("labels") or {}):
        return False
    return True


_ZONE_LABELS = (
    "topology.kubernetes.io/zone",
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/region",
)


def volume_zone_filter(ctx, pod: PodView, ni: "NodeInfo") -> "str | None":
    snapshot = ctx.snapshot
    for claim, pvc in _pod_pvcs(ctx, pod):
        if pvc is None:
            continue
        pv_name = (pvc.get("spec", {}) or {}).get("volumeName")
        if not pv_name:
            continue
        pv = snapshot.pvs.get(pv_name)
        if pv is None:
            continue
        pv_labels = (pv.get("metadata", {}) or {}).get("labels") or {}
        for zl in _ZONE_LABELS:
            if zl not in pv_labels:
                continue
            allowed = set(pv_labels[zl].split("__"))
            if ni.node.labels.get(zl) not in allowed:
                return "node(s) had no available volume zone"
    return None


def pod_disk_keys(p: PodView) -> "list[tuple[str, str, bool]]":
    """(kind, identity, readOnly) per exclusive-disk volume of the pod —
    the conflict identity VolumeRestrictions compares (shared with the
    engine's volume featurizer, engine/encode_vol.py)."""
    keys = []
    for v in p.spec.get("volumes", []) or []:
        gce = v.get("gcePersistentDisk")
        if gce:
            keys.append(("gce", gce.get("pdName"), bool(gce.get("readOnly"))))
        ebs = v.get("awsElasticBlockStore")
        if ebs:
            keys.append(("ebs", ebs.get("volumeID"), bool(ebs.get("readOnly"))))
        rbd = v.get("rbd")
        if rbd:
            keys.append(("rbd", f"{rbd.get('pool')}/{rbd.get('image')}", bool(rbd.get("readOnly"))))
        iscsi = v.get("iscsi")
        if iscsi:
            keys.append(("iscsi", f"{iscsi.get('targetPortal')}/{iscsi.get('iqn')}", bool(iscsi.get("readOnly"))))
    return keys


def volume_restrictions_filter(ctx, pod: PodView, ni: "NodeInfo") -> "str | None":
    # ReadWriteOncePod: the claim must not be used by any other pod.
    for claim, pvc in _pod_pvcs(ctx, pod):
        if pvc is None:
            continue
        modes = (pvc.get("spec", {}) or {}).get("accessModes") or []
        if "ReadWriteOncePod" in modes:
            for other_ni in ctx.snapshot.node_list():
                for other in other_ni.pods:
                    if other.namespace == pod.namespace and claim in other.pvc_names:
                        return "node has pod using PersistentVolumeClaim with the same name and ReadWriteOncePod access mode"
    # GCEPD / AWS EBS: no two pods on a node may mount the same volume unless
    # both read-only.
    mine = pod_disk_keys(pod)
    if mine:
        for other in ni.pods:
            for kind, ident, ro in pod_disk_keys(other):
                for mkind, mident, mro in mine:
                    if kind == mkind and ident == mident and not (ro and mro):
                        return "node(s) conflicted with the pod's volumes"
    return None


_VOLUME_LIMITS = {"EBSLimits": ("awsElasticBlockStore", 39), "GCEPDLimits": ("gcePersistentDisk", 16), "AzureDiskLimits": ("azureDisk", 16)}


def _make_volume_limits_filter(plugin: str):
    vol_type, limit = _VOLUME_LIMITS[plugin]

    def _filter(ctx, pod: PodView, ni: "NodeInfo") -> "str | None":
        def count(p: PodView) -> int:
            return sum(1 for v in p.spec.get("volumes", []) or [] if v.get(vol_type))

        want = count(pod)
        if want == 0:
            return None
        have = sum(count(p) for p in ni.pods)
        if have + want > limit:
            return "node(s) exceed max volume count"
        return None

    return _filter


def node_volume_limits_filter(ctx, pod: PodView, ni: "NodeInfo") -> "str | None":
    # CSI volume limits require CSINode objects, which the simulator's store
    # (like the reference's 7 watched kinds) does not model; pass-through.
    return None


# ---------------------------------------------------------------------------
# DefaultPreemption (PostFilter)
# ---------------------------------------------------------------------------

def default_preemption(
    ctx: "CycleContext", pod: PodView, res: "PodSchedulingResult", oracle: "Oracle"
) -> tuple[str, list[str], dict[str, str]]:
    """Victim selection per upstream dry-run preemption: on each candidate
    node, remove pods with lower priority, check feasibility, then reprieve
    victims (highest priority first) that keep the pod feasible. Node choice:
    min highest-victim-priority, then min priority sum, then fewest victims,
    then lowest node index. (PDBs are not modeled — the store has no PDB
    kind, matching the reference's 7 watched kinds.)"""
    snapshot = ctx.snapshot
    pod_priority = snapshot.pod_priority(pod)
    messages: dict[str, str] = {}
    candidates: list[tuple[str, list[PodView]]] = []
    for ni in snapshot.node_list():
        lower = [p for p in ni.pods if snapshot.pod_priority(p) < pod_priority]
        if not lower:
            messages[ni.node.name] = "no lower-priority pods to preempt"
            continue
        saved = list(ni.pods)
        # remove all lower-priority pods
        for victim in lower:
            ni.remove_pod(victim.namespace, victim.name)
        fits = _feasible_after_removal(ctx, pod, ni)
        if not fits:
            _restore(ni, saved)
            messages[ni.node.name] = "preemption would not make pod schedulable"
            continue
        # reprieve: re-add victims (highest priority first) while still feasible
        lower_sorted = sorted(lower, key=lambda p: -snapshot.pod_priority(p))
        victims: list[PodView] = []
        for v in lower_sorted:
            ni.add_pod(v.obj)
            if not _feasible_after_removal(ctx, pod, ni):
                ni.remove_pod(v.namespace, v.name)
                victims.append(v)
        _restore(ni, saved)
        if victims:
            candidates.append((ni.node.name, victims))
            messages[ni.node.name] = (
                f"can preempt {len(victims)} victim(s): "
                + ", ".join(f"{v.namespace}/{v.name}" for v in victims)
            )
    if not candidates:
        return "", [], messages
    order = {ni.node.name: i for i, ni in enumerate(snapshot.node_list())}

    def rank(cand: tuple[str, list[PodView]]):
        node, victims = cand
        prios = [snapshot.pod_priority(v) for v in victims]
        return (max(prios), sum(prios), len(victims), order[node])

    best_node, best_victims = min(candidates, key=rank)
    messages[best_node] = "preemption victim(s): " + ", ".join(
        f"{v.namespace}/{v.name}" for v in best_victims
    )
    return best_node, [f"{v.namespace}/{v.name}" for v in best_victims], messages


def _restore(ni: "NodeInfo", saved_pods: list):
    current = {(p.namespace, p.name) for p in ni.pods}
    for p in saved_pods:
        if (p.namespace, p.name) not in current:
            ni.add_pod(p.obj)


def _feasible_after_removal(ctx: "CycleContext", pod: PodView, ni: "NodeInfo") -> bool:
    """Re-run every *enabled* filter plugin against the mutated NodeInfo
    (upstream dry-run preemption re-runs the full filter stack). Cycle
    state that depends on existing pods (inter-pod affinity, topology
    spread) is recomputed so victim removal is visible."""
    sub_ctx = type(ctx)(ctx.snapshot, ctx.config)
    for name in ctx.config.enabled("filter"):
        fn = FILTER_PLUGINS.get(name)
        if fn is None:
            continue
        if fn(sub_ctx, pod, ni) is not None:
            return False
    return True


# ---------------------------------------------------------------------------
# dispatch tables
# ---------------------------------------------------------------------------

PREFILTER_PLUGINS: dict[str, Callable] = {
    "NodeResourcesFit": fit_pre_filter,
    "NodePorts": node_ports_pre_filter,
    "PodTopologySpread": spread_pre_filter,
    "InterPodAffinity": interpod_pre_filter,
    "VolumeBinding": volume_binding_pre_filter,
    # State-caching-only prefilters: can never fail here, but the reference
    # records a success status for every enabled prefilter plugin (wrapped
    # PreFilter, simulator/scheduler/plugin/wrappedplugin.go:459-489), so
    # they must appear in the record.
    "VolumeRestrictions": lambda ctx, pod: None,
    "VolumeZone": lambda ctx, pod: None,
    "NodeAffinity": lambda ctx, pod: None,
}

FILTER_PLUGINS: dict[str, Callable] = {
    "NodeUnschedulable": node_unschedulable_filter,
    "NodeName": node_name_filter,
    "TaintToleration": taint_toleration_filter,
    "NodeAffinity": node_affinity_filter,
    "NodePorts": node_ports_filter,
    "NodeResourcesFit": fit_filter,
    "VolumeRestrictions": volume_restrictions_filter,
    "EBSLimits": _make_volume_limits_filter("EBSLimits"),
    "GCEPDLimits": _make_volume_limits_filter("GCEPDLimits"),
    "NodeVolumeLimits": node_volume_limits_filter,
    "AzureDiskLimits": _make_volume_limits_filter("AzureDiskLimits"),
    "VolumeBinding": volume_binding_filter,
    "VolumeZone": volume_zone_filter,
    "PodTopologySpread": spread_filter,
    "InterPodAffinity": interpod_filter,
}

PRESCORE_PLUGINS: dict[str, Callable] = {
    "InterPodAffinity": interpod_pre_score,
    "PodTopologySpread": spread_pre_score,
    "TaintToleration": lambda ctx, pod, feasible: None,
    "NodeAffinity": lambda ctx, pod, feasible: None,
    "NodeResourcesFit": lambda ctx, pod, feasible: None,
    "NodeResourcesBalancedAllocation": lambda ctx, pod, feasible: None,
}

# name → (score_fn, normalize_fn | None)
SCORE_PLUGINS: dict[str, tuple[Callable, "Callable | None"]] = {
    "NodeResourcesBalancedAllocation": (balanced_allocation_score, None),
    "ImageLocality": (image_locality_score, None),
    "InterPodAffinity": (interpod_score, interpod_normalize),
    "NodeResourcesFit": (fit_score, None),
    "NodeAffinity": (node_affinity_score, node_affinity_normalize),
    "PodTopologySpread": (spread_score, spread_normalize),
    "TaintToleration": (taint_toleration_score, taint_toleration_normalize),
}

POSTFILTER_PLUGINS: dict[str, Callable] = {
    "DefaultPreemption": default_preemption,
}
