"""Integer resource-unit conversion shared by the oracle and the encoder."""

from __future__ import annotations

import math
from fractions import Fraction


def to_int_resources(req: dict[str, Fraction]) -> dict[str, int]:
    """Fractions → the integer units upstream uses internally:
    cpu in millicores (ceil), everything else in base units (ceil)."""
    out = {}
    for name, v in req.items():
        if name == "cpu":
            out[name] = math.ceil(v * 1000)
        else:
            out[name] = math.ceil(v)
    return out
