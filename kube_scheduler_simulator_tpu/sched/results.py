"""Per-pod scheduling result records — the decision trace.

The reference's core product is the per-pod, per-node, per-plugin record of
every framework phase, serialized onto 13 pod annotations (reference:
simulator/scheduler/plugin/resultstore/store.go:39-86 for the shapes,
simulator/scheduler/plugin/annotation/annotation.go:3-30 for the keys). Here
the record is a first-class object emitted by the engine itself — there is no
informer/reflector race to work around (SURVEY.md §2 #10) — and
`to_annotations()` reproduces the reference's exact annotation wire format so
the reference web UI can render our traces.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

PASSED_FILTER_MESSAGE = "passed"
SUCCESS_MESSAGE = "success"
WAIT_MESSAGE = "wait"


def go_duration(seconds: float) -> str:
    """`time.Duration.String()` for the permit-timeout annotation
    (resultstore store.go:544-555 records `timeout.String()`): "0s",
    sub-second values in ns/µs/ms, otherwise "[Xh][Ym]Zs" with the
    fraction's trailing zeros trimmed."""
    ns = round(seconds * 1e9)
    if ns == 0:
        return "0s"

    def frac(value: float) -> str:
        s = f"{value:.9f}".rstrip("0").rstrip(".")
        return s

    if ns < 1_000:
        return f"{ns}ns"
    if ns < 1_000_000:
        return f"{frac(ns / 1_000)}µs"
    if ns < 1_000_000_000:
        return f"{frac(ns / 1_000_000)}ms"
    total_s = ns / 1e9
    h = int(total_s // 3600)
    m = int((total_s - h * 3600) // 60)
    s = total_s - h * 3600 - m * 60
    out = ""
    if h:
        out += f"{h}h"
    if m or h:
        out += f"{m}m"
    out += f"{frac(s)}s"
    return out


def record_bind_points(
    config,
    res: "PodSchedulingResult",
    permit: "dict[str, tuple[str, float]] | None" = None,
) -> None:
    """Record the post-selection extension points for a scheduled pod —
    one status per *enabled* plugin at each point, as the reference's
    wrapped plugins do (wrappedplugin.go:549-695: Reserve/Permit/PreBind/
    Bind/PostBind each record per registered plugin). None of the
    simulator-supported plugins can fail these points in-process (no real
    volume provisioning), so statuses default to "success".

    `permit`: optional {plugin name: (message, timeout_seconds)} from
    custom permit kernels (kernels.PERMIT_PLUGINS) — the reference's
    AddPermitResult records BOTH the status ("success" / "wait" / error
    message) and the timeout as a Go duration string
    (wrappedplugin.go:549-575, store.go:544-555); plugins without an
    entry record success with timeout 0."""
    for name in config.enabled("reserve"):
        res.reserve[name] = SUCCESS_MESSAGE
    for name in config.enabled("permit"):
        msg, timeout_s = (permit or {}).get(name, (SUCCESS_MESSAGE, 0.0))
        res.permit[name] = msg
        res.permit_timeout[name] = go_duration(timeout_s)
    for name in config.enabled("preBind"):
        res.prebind[name] = SUCCESS_MESSAGE
    for name in config.enabled("bind"):
        res.bind[name] = SUCCESS_MESSAGE

ANNOTATION_KEYS = {
    "pre_filter_status": "scheduler-simulator/prefilter-result-status",
    "pre_filter_result": "scheduler-simulator/prefilter-result",
    "filter": "scheduler-simulator/filter-result",
    "post_filter": "scheduler-simulator/postfilter-result",
    "pre_score": "scheduler-simulator/prescore-result",
    "score": "scheduler-simulator/score-result",
    "final_score": "scheduler-simulator/finalscore-result",
    "reserve": "scheduler-simulator/reserve-result",
    "permit": "scheduler-simulator/permit-result",
    "permit_timeout": "scheduler-simulator/permit-result-timeout",
    "prebind": "scheduler-simulator/prebind-result",
    "bind": "scheduler-simulator/bind-result",
    "selected_node": "scheduler-simulator/selected-node",
}


@dataclass
class PodSchedulingResult:
    """Everything recorded while scheduling one pod."""

    pod_namespace: str = "default"
    pod_name: str = ""
    selected_node: str = ""
    # plugin → status message
    pre_filter_status: dict[str, str] = field(default_factory=dict)
    # plugin → surviving node names (framework.PreFilterResult)
    pre_filter_result: dict[str, list[str]] = field(default_factory=dict)
    # plugin → status message
    pre_score: dict[str, str] = field(default_factory=dict)
    # node → plugin → "passed" | reason
    filter: dict[str, dict[str, str]] = field(default_factory=dict)
    # node → plugin → message
    post_filter: dict[str, dict[str, str]] = field(default_factory=dict)
    # node → plugin → raw score (stringified int)
    score: dict[str, dict[str, str]] = field(default_factory=dict)
    # node → plugin → normalized×weighted score (stringified int)
    final_score: dict[str, dict[str, str]] = field(default_factory=dict)
    # plugin → message
    permit: dict[str, str] = field(default_factory=dict)
    permit_timeout: dict[str, str] = field(default_factory=dict)
    reserve: dict[str, str] = field(default_factory=dict)
    prebind: dict[str, str] = field(default_factory=dict)
    bind: dict[str, str] = field(default_factory=dict)
    # engine-level outcome (not an annotation): Scheduled | Unschedulable | Nominated
    status: str = ""
    nominated_node: str = ""
    preemption_victims: list[str] = field(default_factory=list)

    def add_filter(self, node: str, plugin: str, msg: str):
        self.filter.setdefault(node, {})[plugin] = msg

    def add_score(self, node: str, plugin: str, value: int):
        self.score.setdefault(node, {})[plugin] = str(value)

    def add_final_score(self, node: str, plugin: str, value: int):
        self.final_score.setdefault(node, {})[plugin] = str(value)

    def to_annotations(self) -> dict[str, str]:
        """The 13 reference annotation payloads (JSON-in-string values)."""
        return {
            ANNOTATION_KEYS["pre_filter_status"]: json.dumps(self.pre_filter_status),
            ANNOTATION_KEYS["pre_filter_result"]: json.dumps(self.pre_filter_result),
            ANNOTATION_KEYS["filter"]: json.dumps(self.filter),
            ANNOTATION_KEYS["post_filter"]: json.dumps(self.post_filter),
            ANNOTATION_KEYS["pre_score"]: json.dumps(self.pre_score),
            ANNOTATION_KEYS["score"]: json.dumps(self.score),
            ANNOTATION_KEYS["final_score"]: json.dumps(self.final_score),
            ANNOTATION_KEYS["reserve"]: json.dumps(self.reserve),
            ANNOTATION_KEYS["permit"]: json.dumps(self.permit),
            ANNOTATION_KEYS["permit_timeout"]: json.dumps(self.permit_timeout),
            ANNOTATION_KEYS["prebind"]: json.dumps(self.prebind),
            ANNOTATION_KEYS["bind"]: json.dumps(self.bind),
            ANNOTATION_KEYS["selected_node"]: self.selected_node,
        }
