"""Serving shell: the reference simulator's HTTP API over the TPU engine."""

from .httpserver import SimulatorServer
from .service import (
    InvalidSchedulerConfiguration,
    SchedulerService,
    SimulatorService,
)

__all__ = [
    "SimulatorServer",
    "SimulatorService",
    "SchedulerService",
    "InvalidSchedulerConfiguration",
]
