"""CLI driver: boot the simulator server (the reference's entry point,
simulator/simulator.go:23-106, minus the etcd/apiserver/controller
processes the in-memory store replaces).

    python -m kube_scheduler_simulator_tpu.server [--port 1212]
                                                  [--auto-schedule]

Boot order mirrors startSimulator: env config → store + services →
optional boot snapshot import → HTTP server → wait for interrupt.
"""

from __future__ import annotations

import argparse

from . import config as envconfig
from .httpserver import SimulatorServer
from .service import SimulatorService


def main(argv: "list[str] | None" = None) -> int:
    # strict KSS_* validation BEFORE anything heavy: a typo'd knob is a
    # clear boot error, not a silently-defaulted value or a 500 deep
    # inside the first request handler (utils/envcheck.py)
    from ..utils import envcheck

    envcheck.fail_fast()

    parser = argparse.ArgumentParser(prog="kube-scheduler-simulator-tpu")
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--auto-schedule",
        action="store_true",
        help="run a scheduling pass automatically after resource changes",
    )
    parser.add_argument(
        "--replicate-from",
        default=None,
        metavar="URL",
        help="replicate an existing cluster from a simulator-compatible "
        "export endpoint at boot (IgnoreErr, keeps own scheduler config)",
    )
    parser.add_argument(
        "--replicate-from-cluster",
        default=None,
        metavar="URL",
        help="replicate from a REAL kube-apiserver at boot (lists "
        "pods/nodes/PVs/PVCs/storageclasses/priorityclasses/namespaces "
        "via the Kubernetes REST API; reference "
        "replicateexistingcluster.go:40-53)",
    )
    parser.add_argument(
        "--bearer-token-file",
        default=None,
        metavar="PATH",
        help="file holding a bearer token for --replicate-from-cluster",
    )
    parser.add_argument(
        "--no-device-probe",
        action="store_true",
        help="skip the boot-time accelerator watchdog (the probe guards "
        "against a wedged backend hanging the first scheduling pass "
        "forever; skipping is for environments where device init is "
        "known-good but slower than the probe window)",
    )
    args = parser.parse_args(argv)

    if not args.no_device_probe:
        # A wedged accelerator tunnel hangs even jax.devices(), which
        # would turn the FIRST /api/v1/schedule into an unbounded stall
        # (observed failure mode). Probe under a watchdog at boot and
        # re-exec on the scrubbed CPU backend when the accelerator is
        # unusable — a slower, labeled server beats a hung one.
        import os
        import sys

        from ..utils.axonenv import (
            PROBE_TIMEOUT_S,
            probe_devices,
            probe_why,
            reexec_on_cpu,
        )

        if not os.environ.get("_KSS_SERVER_CPU_FALLBACK"):
            devices, error = probe_devices()
            if not devices:
                reexec_on_cpu(
                    "server",
                    "_KSS_SERVER_CPU_FALLBACK",
                    [sys.executable, "-m", "kube_scheduler_simulator_tpu.server"]
                    + list(argv if argv is not None else sys.argv[1:]),
                    probe_why(error, PROBE_TIMEOUT_S),
                )

    # cold-start phase accounting (utils/ledger.py): the boot probe is
    # over (ran, was skipped, or re-exec'd us onto CPU) — everything
    # from here to the first scheduled pass is encode + compile wall
    from ..utils.ledger import COLD_START

    COLD_START.mark("bootProbe")

    cfg = envconfig.from_env()
    if args.port is not None:
        cfg.port = args.port
    service = SimulatorService(
        initial_config=cfg.initial_scheduler_config,
        external_scheduler_enabled=cfg.external_scheduler_enabled,
    )
    if cfg.external_import_enabled and cfg.snapshot_path:
        errors = service.import_(
            envconfig.load_snapshot(cfg.snapshot_path), ignore_err=True
        )
        for e in errors:
            print(f"import: skipped: {e}")
    if args.replicate_from:
        from .replicate import replicate_existing_cluster

        for e in replicate_existing_cluster(service, source_url=args.replicate_from):
            print(f"replicate: skipped: {e}")
    if args.replicate_from_cluster:
        from .replicate import replicate_existing_cluster

        token = ""
        if args.bearer_token_file:
            with open(args.bearer_token_file) as f:
                token = f.read().strip()
        for e in replicate_existing_cluster(
            service,
            kube_apiserver=args.replicate_from_cluster,
            bearer_token=token,
        ):
            print(f"replicate: skipped: {e}")
    server = SimulatorServer(
        service,
        host=args.host,
        port=cfg.port,
        auto_schedule=args.auto_schedule,
        cors_allowed_origins=cfg.cors_allowed_origins,
    )
    server.start()
    print(f"simulator serving on http://{args.host}:{server.port}/api/v1")

    # zero-loss graceful drain (docs/resilience.md): SIGTERM — the
    # rolling-restart signal — begins the drain (readyz flips to the
    # distinct `draining` 503, new requests shed, in-flight passes
    # finish under KSS_DRAIN_DEADLINE_S, every session snapshots to
    # KSS_SESSION_DIR, the broker quiesces) and the process exits 0; a
    # restart over the same session directory adopts the snapshots, so
    # no acknowledged write is lost. POST /api/v1/admin/drain reaches
    # the same path over HTTP.
    import signal

    def _term(signum, frame):
        server.begin_drain()

    try:
        signal.signal(signal.SIGTERM, _term)
    except ValueError:  # non-main thread (embedded use): skip
        pass
    try:
        while not server.drain_done.wait(0.5):
            if server._thread is not None and not server._thread.is_alive():
                # the HTTP server thread died without a drain (the shape
                # the old `_thread.join()` wait exited on): shut down and
                # return instead of spinning on a drain that will never
                # come — a supervisor must never see a live PID serving
                # nothing
                server.shutdown()
                return 0
    except KeyboardInterrupt:
        server.shutdown()
        return 0
    server.shutdown()
    # exit 0 is the ZERO-LOSS claim, so it must be earned: a drain that
    # raised outright, or lost any session's snapshot, reports failure —
    # a rolling-restart supervisor must not proceed as if nothing was
    # lost (docs/resilience.md)
    result = server.drain_status().get("result") or {}
    problems = result.get("error") or result.get("errors")
    if problems:
        import sys

        print(f"drain failed: {problems}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
