"""Cross-tenant continuous batching: the micro-batch dispatch plane.

PR 6 built the session plane (thousands of tenants, one shared
CompileBroker, bucket-compatible sessions already sharing warm
executables) and PR 12 killed the compile wall — but every scheduling
pass still drove the device ONE SESSION AT A TIME, so aggregate
decisions/s/process was flat in session count. Inference serving solved
exactly this shape with iteration-level batching (Orca; vLLM's batched
serving loop): stack compatible requests onto one device program and
keep occupancy high. Here the batch axis is the already-vmapped sweep
axis (parallel/sweep.py) — the kernel machinery existed, this module is
the missing serving plane.

How a window forms (docs/sessions.md "Continuous batching"):

  * Device-driving sequential passes that arrive inside a collection
    window (``KSS_BATCH_WINDOW_MS``) and are **batch-compatible** — the
    same engine kind, compile signature, shape bucket and device epoch,
    i.e. the exact broker key warm-engine sharing already uses — enroll
    in one window. The first enrollee is the window's LEADER; it waits
    out the window (or until ``KSS_BATCH_MAX_SESSIONS`` fills it) and
    then executes every enrolled pass as ONE broker-jitted program:
    ``vmap(run_fn)`` over a leading session axis, the `parallel/sweep.py`
    pattern with sessions where the sweep has policy variants.
  * The batch axis is padded to its geometric bucket (slot 0 replayed;
    results discarded — the sweep's ``valid=False`` analogue) so batch
    fills 3, 5..8 reuse the 4- and 8-wide compilations.
  * Results scatter back per-session: each enrollee receives ITS slice
    of the final state + trace and decodes/writes back on its own
    thread, under its own session context and pass id — placements and
    trace bytes are BYTE-IDENTICAL to solo dispatch (parity-pinned in
    tests/test_batchplane.py and `make batch-smoke`).

Fairness is a hard contract: a lone tenant never waits more than one
window — the leader's wait is bounded by
``min(KSS_BATCH_WINDOW_MS, KSS_BATCH_MAX_WAIT_MS)`` and a window that
closes with one enrollee is told to dispatch SOLO (today's path,
``soloFallbacks``) rather than pay a vmapped program for nothing.
Windows close on the timer, never on a quorum, so semaphore waiters
(the ``KSS_MAX_CONCURRENT_PASSES`` collection point, server/sessions.py)
can never deadlock against the window: a window with no second arrival
always flushes. Incompatible passes — different broker key, extender
mode, a recorded gang pass (its trace replay is per-session host work),
a session-scoped (or process) fault plane, an escalated device rung —
fall back to solo dispatch, counted per-session.

Gang passes batch too (``batch.gang.run``): the fused `gang.fixpoint`
program made one gang pass ONE broker-keyed dispatch, so bucket- and
window-compatible gang passes stack exactly like sequential ones — the
batch axis rides `vmap` over (arrays, state0, order, weights) and each
session gets back its (final state, rounds) slice. The vmapped
while_loops run until every session's fixpoint converges; converged
sessions' extra rounds are masked no-ops and the program's `lax.cond`
guards lower to both-branches-plus-select (the GangSweep tradeoff,
docs/performance.md).

Failure containment: ANY error inside the batched execution (compile
failure, device fault, a torn stack) marks every enrollee solo and each
falls back to today's dispatch on its own thread — with its own
resilience ladder (retry → shrink → CPU, eager fallback). The batch
plane can degrade throughput, never correctness.

Accounting: ``batchedPasses`` / ``batchWindows`` / ``batchOccupancySum``
/ ``soloFallbacks`` phases counters (utils/metrics.py — per-session for
passes/fallbacks, on the plane's default registry for windows/occupancy),
a ``fleet.batchOccupancy`` Perfetto counter track, ``batch.execute``
complete-events, and per-tenant program-ledger attribution: the ONE
``batch.seq.run`` call a window dispatches fans its session attribution
out to every enrolled tenant (`ProgramLedger.attribute_sessions`), so
`calls` counts device dispatches while per-session counts stay passes
served.

`POST /api/v1/admin/drain` flushes partially-filled windows before
snapshotting (`begin_drain`): a draining process must not sit out a
collection window, and new enrollments shed to solo immediately.
"""

from __future__ import annotations

import os
import threading
import time

from ..utils import locking, telemetry
from ..utils import broker as broker_mod
from ..utils import ledger as ledger_mod
from ..utils.compilecache import shape_bucket
from ..utils.envcheck import env_truthy

# the KSS7xx audit labels (and program-ledger keys) of the two batched
# program kinds: the vmapped sequential scan and the vmapped gang
# fixpoint (engine/gang.py `gang.fixpoint` — the fused rounds +
# preempt-alternation pass — over a leading session axis)
BATCH_SEQ_LABEL = "batch.seq.run"
BATCH_GANG_LABEL = "batch.gang.run"

# how long a follower waits on the leader's execution before giving up
# and dispatching solo. The leader ALWAYS signals (results or error) in
# a finally block, so this bound only matters if the leader thread is
# killed mid-execution — generous because a cold chip compile of the
# batched program can legitimately take minutes.
_FOLLOWER_TIMEOUT_S = 600.0

# batched programs kept warm, FIFO-evicted: each entry holds one vmapped
# jit + its template engine, keyed (broker key, batch bucket) — the same
# bound spirit as the broker's warm-engine LRU
_PROGRAM_CAP = 8


def _env_float_ms(name: str, default_ms: float) -> float:
    """A window knob in milliseconds (lenient like the broker's ladder
    knobs: a malformed value must not take the serving stack down).
    The env READ stays module-local so KSS1xx can tie the name to its
    reader; the coercion is the broker's shared helper."""
    return broker_mod._coerce_env_number(
        os.environ.get(name, ""), default_ms, float, 0.0
    )


def _env_int(name: str, default: int, minimum: int) -> int:
    return broker_mod._coerce_env_number(
        os.environ.get(name, ""), default, int, minimum
    )


def from_env(metrics=None) -> "BatchPlane | None":
    """The serving plane's constructor: an armed `BatchPlane` when
    ``KSS_BATCH`` is truthy, else None (batching is off by default —
    the historical one-session-at-a-time dispatch)."""
    if not env_truthy(os.environ.get("KSS_BATCH")):
        return None
    window_ms = _env_float_ms("KSS_BATCH_WINDOW_MS", 5.0)
    max_wait_ms = _env_float_ms("KSS_BATCH_MAX_WAIT_MS", window_ms)
    max_sessions = _env_int("KSS_BATCH_MAX_SESSIONS", 8, 1)
    return BatchPlane(
        window_ms=window_ms,
        max_wait_ms=max_wait_ms,
        max_sessions=max_sessions,
        metrics=metrics,
    )


class _Enrollee:
    """One pass enrolled in a window: its decode engine (carrying the
    encoding), padded queue, and the slot the leader scatters into."""

    __slots__ = (
        "engine", "queue", "session_id", "metrics",
        "done", "state", "trace", "error", "abandoned", "trace_id",
    )

    def __init__(self, engine, queue, session_id, metrics):
        self.engine = engine
        self.queue = queue
        self.session_id = session_id
        self.metrics = metrics
        self.done = threading.Event()
        self.state = None
        self.trace = None
        # the enrolling request's distributed-trace id, captured on the
        # submit thread: the ONE vmapped window dispatch links back to
        # every enrolled tenant's request trace (docs/observability.md)
        self.trace_id = telemetry.current_trace_id()
        self.error: "Exception | None" = None
        # set (under the plane lock) by a follower whose done-wait
        # expired: it is about to dispatch solo, so the late leader
        # must not count or attribute its pass as batched
        self.abandoned = False


class _Window:
    """One collection window for one batch key. `full` wakes the leader
    early when KSS_BATCH_MAX_SESSIONS enrollees arrived; `closed` stops
    late joiners (they open a successor window instead)."""

    __slots__ = ("key", "kind", "items", "closed", "full")

    def __init__(self, key, kind="seq"):
        self.key = key
        # "seq" | "gang" — which batched program the window dispatches.
        # Uniform per window by construction: the key's leading element
        # is the engine kind, so mixed-kind enrollment cannot happen.
        self.kind = kind
        self.items: "list[_Enrollee]" = []
        self.closed = False
        self.full = threading.Event()


@locking.guard_inferred
class BatchPlane:
    """The micro-batch dispatch plane (module docstring). One instance
    per SessionManager, shared by every session's SchedulerService."""

    def __init__(
        self,
        *,
        window_ms: float = 5.0,
        max_wait_ms: "float | None" = None,
        max_sessions: int = 8,
        metrics=None,
    ):
        self.window_s = max(0.0, float(window_ms)) / 1000.0
        wait_s = (
            self.window_s
            if max_wait_ms is None
            else max(0.0, float(max_wait_ms)) / 1000.0
        )
        # the fairness bound: the leader's collection wait — and with it
        # any enrollee's added latency — never exceeds one window
        self.wait_s = min(self.window_s, wait_s)
        self.max_sessions = max(1, int(max_sessions))
        # window/occupancy counters land here (the default session's
        # registry — the broker's fallback-attribution precedent);
        # per-pass counters land on each enrollee's own registry
        self.metrics = metrics
        self._lock = locking.make_lock("batchplane.windows")
        self._open: "dict[object, _Window]" = {}
        self._programs: "dict[tuple, dict]" = {}
        self._draining = False

    # -- observability --------------------------------------------------------

    def stats(self) -> dict:
        """Config + live-window stats for the session-plane stats block
        (`GET /api/v1/metrics` sessions.batching)."""
        with self._lock:
            return {
                "armed": True,
                "windowMs": round(self.window_s * 1000.0, 3),
                "maxWaitMs": round(self.wait_s * 1000.0, 3),
                "maxSessions": self.max_sessions,
                "openWindows": len(self._open),
                "warmPrograms": len(self._programs),
                "draining": self._draining,
            }

    # -- drain (docs/resilience.md) -------------------------------------------

    def begin_drain(self) -> None:
        """Flush every partially-filled window NOW and shed new
        enrollments to solo dispatch: a draining server must not sit
        out a collection window before snapshotting. Idempotent."""
        with self._lock:
            self._draining = True
            windows = list(self._open.values())
            self._open.clear()
        for win in windows:
            win.full.set()  # wakes the leader; it closes + executes/solos

    # -- the collection point -------------------------------------------------

    def submit(self, key, engine, queue, *, metrics, session_id=None,
               kind="seq"):
        """Enroll one pass under batch `key` (the broker engine key:
        kind, compile signature, queue bucket, device epoch). Blocks
        until the window executes, then returns
        ``(final_state_slice, trace_slice)`` for THIS pass — or None,
        meaning the caller must dispatch solo (lone window, draining,
        or a failed batched execution). `engine` is the caller's
        decode-engine instance; its encoding supplies the stacked
        arrays and its program shape defines the window's dispatch.

        ``kind="seq"`` (the default): `queue` is the bucket-padded pod
        queue, the program vmaps `run_fn`, and the trace slice is the
        record trace. ``kind="gang"``: `queue` is the [P] PrioritySort
        order tensor, the program vmaps `fixpoint_fn`
        (``batch.gang.run``), and the second slice is the pass's
        rounds-to-fixpoint scalar."""
        me = _Enrollee(engine, queue, session_id, metrics)
        with self._lock:
            if self._draining:
                return None
            win = self._open.get(key)
            if win is not None and (
                win.closed or len(win.items) >= self.max_sessions
            ):
                win = None  # missed it: open the successor window
            if win is None:
                win = _Window(key, kind)
                win.items.append(me)
                self._open[key] = win
                leader = True
                if len(win.items) >= self.max_sessions:
                    # max_sessions=1: the window is born full — close it
                    # immediately rather than taxing the pass one window
                    win.full.set()
            else:
                win.items.append(me)
                leader = False
                if len(win.items) >= self.max_sessions:
                    win.full.set()
        t0 = time.perf_counter()
        if leader:
            # the leader IS the window timer: it always wakes after one
            # window even if no second pass ever arrives — the no-
            # deadlock contract for semaphore waiters queued behind it
            win.full.wait(self.wait_s)
            with self._lock:
                win.closed = True
                if self._open.get(key) is win:
                    del self._open[key]
                items = list(win.items)
            if len(items) == 1:
                # lone tenant: dispatch solo, don't pay a vmapped
                # program for a batch of one (the fairness contract)
                telemetry.complete(
                    "batch.enroll", t0, time.perf_counter(), fill=1,
                    leader=True, outcome="solo",
                )
                return None
            self._execute(win.kind, key, items)
        else:
            if not me.done.wait(_FOLLOWER_TIMEOUT_S):
                # leader lost (killed thread, a compile beyond even the
                # generous bound): dispatch solo — and mark the slot so
                # a LATE leader can't also count this pass as batched
                # (it would be double-counted: batched AND solo)
                with self._lock:
                    if not me.done.is_set():
                        me.abandoned = True
        batched = (
            not me.abandoned and me.error is None and me.state is not None
        )
        telemetry.complete(
            "batch.enroll", t0, time.perf_counter(),
            fill=len(win.items), leader=leader,
            outcome="error" if me.error is not None else (
                "batched" if batched else "solo"
            ),
        )
        if not batched:
            return None
        return me.state, me.trace

    # -- batched execution ----------------------------------------------------

    def _program(self, kind, key, bucket: int, engine):
        """The vmapped program for (key, batch bucket), built once and
        kept warm (FIFO-bounded). For ``seq`` windows it is built from
        a fresh signature-equal template engine (masked preemption —
        the vmappable form of the solo cond path); for ``gang`` windows
        it vmaps the enrollee engine's own fused `fixpoint_fn` — the
        identical program text solo dispatch runs, so batched slices
        cannot diverge from solo placements. Returns (vrun, fresh)."""
        from ..engine.engine import BatchedScheduler

        with self._lock:
            entry = self._programs.get((key, bucket))
            if entry is not None:
                return entry["vrun"], False
        # build OUTSIDE the plane lock: kernel construction allocates
        # device constants and other windows' enrollment must not wait
        # on it. A concurrent duplicate build of the same (key, bucket)
        # is tolerated — last one wins, XLA's caches dedupe the compile.
        import jax

        if kind == "gang":
            run_fn = engine.fixpoint_fn
            aud = engine.audit_spec()
            label = BATCH_GANG_LABEL
        else:
            template = BatchedScheduler(
                engine.enc, record=True, strict=True, preempt_mode="masked"
            )
            run_fn = template.run_fn
            aud = template.audit_spec()
            label = BATCH_SEQ_LABEL
        # the batch axis joins the audit's static dims (it is pow2 by
        # construction; KSS713 would otherwise read fills 3/5/6/7 as
        # off-bucket) — the sweep's variant-axis waiver, scoped tighter
        aud["extra_dims"] = tuple(aud.get("extra_dims", ())) + (bucket,)
        vrun = broker_mod.jit(
            jax.vmap(run_fn, in_axes=(0, 0, 0, 0)),
            audit={**aud, "label": label},
        )
        # only `vrun` is cached, not the template engine: the program
        # closure retains what it retains (the build encoding, via
        # run_fn's kernel closures — exactly what a warm solo engine in
        # the broker's LRU pins), but the template's host-side decode
        # tables and trace state need not ride along. Bounded by
        # _PROGRAM_CAP, FIFO-evicted, same spirit as the broker's warm
        # map.
        with self._lock:
            self._programs[(key, bucket)] = {"vrun": vrun}
            while len(self._programs) > _PROGRAM_CAP:
                self._programs.pop(next(iter(self._programs)))
        return vrun, True

    def _execute(self, kind, key, items: "list[_Enrollee]") -> None:
        """Run one closed window as ONE device dispatch and scatter the
        slices back. Never raises: any failure marks every enrollee
        solo (their own dispatch ladders take over)."""
        try:
            self._execute_inner(kind, key, items)
        except Exception as e:  # noqa: BLE001 — contained: everyone solos
            for it in items:
                it.error = e
            telemetry.instant(
                "batch.error", fill=len(items), error=type(e).__name__
            )
        finally:
            for it in items:
                it.done.set()

    def _execute_inner(self, kind, key, items: "list[_Enrollee]") -> None:
        import jax
        import jax.numpy as jnp
        import numpy as np

        B = len(items)
        # pad the batch axis to its geometric bucket by replaying slot 0
        # (results discarded — the sweep's valid=False analogue), so
        # fills 3 and 5..8 reuse the 4- and 8-wide compilations
        bucket = shape_bucket(B, lo=2)
        padded = items + [items[0]] * (bucket - B)
        vrun, fresh = self._program(kind, key, bucket, items[0].engine)
        t0 = time.perf_counter()
        arrays_b = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[it.engine.enc.arrays for it in padded],
        )
        state_b = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[it.engine.enc.state0 for it in padded],
        )
        # seq: the bucket-padded pod queue; gang: the [P] order tensor
        queue_b = jnp.asarray(np.stack([it.queue for it in padded]))
        weights_b = jnp.stack([it.engine.weights for it in padded])
        state_out, trace_out = vrun(arrays_b, state_b, queue_b, weights_b)
        dt = time.perf_counter() - t0
        for i, it in enumerate(items):
            it.state = jax.tree.map(lambda x, i=i: x[i], state_out)
            # gang's second output is the rounds-to-fixpoint scalar;
            # tree.map slices both shapes identically
            it.trace = jax.tree.map(lambda x, i=i: x[i], trace_out)
        # -- accounting -----------------------------------------------------
        # enrollees whose done-wait already expired are dispatching solo
        # and must not ALSO be counted/attributed as batched (the
        # double-count a lost leader would otherwise cause)
        with self._lock:
            served = [it for it in items if not it.abandoned]
        if fresh:
            # first call of a fresh program IS its compile (jit is
            # lazy) — book it ONCE, on the leader, as an engine build;
            # followers book nothing (a compile wall must never inflate
            # executeSeconds — the same split the solo path keeps)
            leader_metrics = items[0].metrics
            if leader_metrics is not None:
                leader_metrics.record_engine_build(dt)
        for it in served:
            if it.metrics is not None:
                it.metrics.record_batching(batched_passes=1)
                if kind == "gang":
                    it.metrics.record_gang(batched_passes=1)
                if not fresh:
                    it.metrics.record_phase_seconds(execute=dt)
        if self.metrics is not None:
            self.metrics.record_batching(windows=1, occupancy=B)
        telemetry.counter("fleet.batchOccupancy", float(B))
        # span links: the one device dispatch names every enrolled
        # tenant's request trace — the N-tenants-one-dispatch
        # attribution, navigable from either end in the merged export
        links = sorted({it.trace_id for it in items if it.trace_id})
        extra = {"links": links} if links else {}
        telemetry.complete(
            "batch.execute", t0, time.perf_counter(),
            tid=telemetry.DEVICE_TID, fill=B, bucket=bucket, **extra,
        )
        # per-tenant ledger attribution: the window's ONE device
        # dispatch was recorded (by the AuditedJit/Bundled wrapper)
        # under the LEADER's session context; fan the attribution out
        # to every other enrolled tenant so /debug/programs answers
        # per-session truthfully (calls = dispatches, session counts =
        # passes served)
        if ledger_mod.ledger_enabled():
            label = BATCH_GANG_LABEL if kind == "gang" else BATCH_SEQ_LABEL
            others = [it.session_id for it in served[1:]]
            if others:
                ledger_mod.LEDGER.attribute_sessions(label, others)
