"""Environment-variable configuration (reference:
simulator/config/config.go:39-228, documented in
simulator/docs/environment-variables.md).

Honored variables — the reference's names where the concept carries over:

    PORT                        simulator server port (default 1212)
    CORS_ALLOWED_ORIGIN_LIST    comma-separated origins
    KUBE_SCHEDULER_CONFIG_PATH  initial KubeSchedulerConfiguration YAML
    EXTERNAL_IMPORT_ENABLED     import a snapshot at boot (see SNAPSHOT_PATH)
    SNAPSHOT_PATH               snapshot JSON for the boot import
    EXTERNAL_SCHEDULER_ENABLED  serve without the internal engine; an
                                external scheduler binds pods through the
                                resource CRUD surface
                                (config.go:34-35, :115-121)

etcd/kube-apiserver variables have no analogue: the typed in-process store
replaces both (SURVEY.md §2 #3).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from ..sched.config import SchedulerConfiguration


@dataclass
class Config:
    port: int = 1212
    cors_allowed_origins: list[str] = field(default_factory=list)
    initial_scheduler_config: "SchedulerConfiguration | None" = None
    external_import_enabled: bool = False
    snapshot_path: str = ""
    external_scheduler_enabled: bool = False


def _parse_bool(name: str, raw: str) -> bool:
    """strconv.ParseBool semantics (reference
    config.go getExternalSchedulerEnabled: non-bool values are an error,
    not silently false)."""
    low = raw.strip().lower()
    if low in ("1", "t", "true"):
        return True
    if low in ("0", "f", "false"):
        return False
    raise ValueError(f"{name} is specified, but it's not bool: {raw}")


def from_env(env: "dict | None" = None) -> Config:
    env = os.environ if env is None else env
    cfg = Config()
    if env.get("PORT"):
        cfg.port = int(env["PORT"])
    if env.get("CORS_ALLOWED_ORIGIN_LIST"):
        cfg.cors_allowed_origins = [
            o.strip()
            for o in env["CORS_ALLOWED_ORIGIN_LIST"].split(",")
            if o.strip()
        ]
    path = env.get("KUBE_SCHEDULER_CONFIG_PATH")
    if path:
        with open(path) as f:
            cfg.initial_scheduler_config = SchedulerConfiguration.from_yaml(
                f.read()
            )
    cfg.external_import_enabled = env.get("EXTERNAL_IMPORT_ENABLED") == "true"
    cfg.snapshot_path = env.get("SNAPSHOT_PATH", "")
    if env.get("EXTERNAL_SCHEDULER_ENABLED"):
        cfg.external_scheduler_enabled = _parse_bool(
            "EXTERNAL_SCHEDULER_ENABLED", env["EXTERNAL_SCHEDULER_ENABLED"]
        )
    return cfg


def load_snapshot(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
