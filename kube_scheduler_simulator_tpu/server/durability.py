"""The session durability plane: write-ahead journals + checkpoint
transport units (docs/fleet.md).

PR 15's fleet promises zero acknowledged-write loss only on the graceful
paths (SIGTERM drain, eviction) — a SIGKILL'd worker loses every write
since its last snapshot, and the re-home path `shutil.move`s files
between session dirs, which only works on a shared filesystem. This
module supplies the two primitives that close both gaps:

  * **SessionJournal** — an append-only JSONL of a session's
    acknowledged store mutations, fed synchronously from the store's
    watch-event dispatch (models/store.py `subscribe`): the event fires
    on the mutating request thread AFTER the mutation commits and
    BEFORE the HTTP layer acknowledges it, so a journaled write is
    exactly an acknowledged write. Each record carries the mutation's
    resourceVersion and the post-mutation object VERBATIM (rv/uid
    included), which makes replay byte-exact and idempotent: replaying
    a record the snapshot already contains is filtered by rv, and
    replaying verbatim objects twice lands the same state.
    ``KSS_FLEET_JOURNAL_SYNC=1`` fsyncs every append (and lets the
    replication plane ship it inline) — crash-kill then loses nothing.

  * **Transport units** — ``{"id", "sha256", "doc", "journal",
    "journalSha256"}``: a ``kss-session-checkpoint/v1`` document plus
    the journal entries past its store rv, each guarded by a sha256
    over `lifecycle.checkpoint.canonical_bytes`. The receive side
    (`verify_unit`) recomputes both digests and rejects mismatches —
    a torn or corrupted transfer is refused, never adopted.

`replay_store_state` is the adopt-side replay: a pure function over the
`ResourceStore.dump_state` shape, so it runs on checkpoint documents
BEFORE a service is built from them — replay never re-triggers
controllers, schedulers, or admission.
"""

from __future__ import annotations

import json
import os

from ..lifecycle.checkpoint import canonical_digest
from ..models.store import KINDS, ResourceStore, WatchEvent
from ..utils import locking

# journal + replica file layout inside a session snapshot dir:
#   <dir>/<sid>.json                   live checkpoint (adopt_snapshots)
#   <dir>/<sid>.journal.jsonl          the session's write-ahead journal
#   <dir>/replicas/<sid>.json          passively held successor replica
#   <dir>/replicas/<sid>.journal.jsonl the replica's shipped journal
JOURNAL_SUFFIX = ".journal.jsonl"
REPLICA_SUBDIR = "replicas"


def journal_path(snapshot_dir: str, sid: str) -> str:
    return os.path.join(snapshot_dir, f"{sid}{JOURNAL_SUFFIX}")


def replica_dir(snapshot_dir: str) -> str:
    return os.path.join(snapshot_dir, REPLICA_SUBDIR)


def replica_paths(snapshot_dir: str, sid: str) -> "tuple[str, str]":
    d = replica_dir(snapshot_dir)
    return (
        os.path.join(d, f"{sid}.json"),
        os.path.join(d, f"{sid}{JOURNAL_SUFFIX}"),
    )


@locking.guard_inferred
class SessionJournal:
    """One session's write-ahead mutation journal.

    Appends happen on the mutating thread (store event dispatch), so
    ordering matches the store's event log by construction. ``sync``
    fsyncs each append — the KSS_FLEET_JOURNAL_SYNC durability mode.
    ``base_rv`` is the resourceVersion high-water mark of the last full
    snapshot; entries at or below it are superseded and dropped on the
    next `rebase` (the snapshot IS those writes).
    """

    def __init__(self, path: str, base_rv: int = 0, sync: bool = False):
        self.path = path
        self.sync = bool(sync)
        self._lock = locking.make_lock("durability.journal")
        self.base_rv = int(base_rv)
        self.appended = 0
        self.bytes_written = 0
        # the sync-replication hook (server/replication.py): called with
        # each appended entry AFTER it is durable locally, still on the
        # acknowledging thread — the inline successor ship
        self.on_append = None

    def record(self, ev: WatchEvent) -> None:
        """Append one store watch event (the subscriber entry point)."""
        self.append(
            {
                "rv": ev.resource_version,
                "t": ev.event_type,
                "k": ev.kind,
                "o": ev.obj,
            }
        )

    def append(self, entry: dict) -> None:
        line = json.dumps(entry, separators=(",", ":"), sort_keys=True)
        data = line.encode() + b"\n"
        with self._lock:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "ab") as f:
                f.write(data)
                if self.sync:
                    f.flush()
                    os.fsync(f.fileno())
            self.appended += 1
            self.bytes_written += len(data)
            hook = self.on_append
        if hook is not None:
            hook(entry)

    def entries(self, since_rv: "int | None" = None) -> list[dict]:
        """Parsed journal records past `since_rv` (default: base_rv).
        A torn final line — the crash artifact an unsynced append can
        leave — is skipped, not fatal: everything before it was
        acknowledged with an intact record."""
        with self._lock:
            floor = self.base_rv if since_rv is None else int(since_rv)
            return read_journal(self.path, floor)

    def counters(self) -> "tuple[int, int]":
        """(appends, bytes written) so far — cumulative across rebases."""
        with self._lock:
            return (self.appended, self.bytes_written)

    def rebase(self, base_rv: int) -> None:
        """A full snapshot at `base_rv` just landed: entries it covers
        are obsolete — truncate the file and move the floor."""
        with self._lock:
            self.base_rv = int(base_rv)
            try:
                with open(self.path, "wb"):
                    pass
            except OSError:
                pass

    def drop(self) -> None:
        with self._lock:
            try:
                os.unlink(self.path)
            except OSError:
                pass


def write_journal(path: str, entries: "list[dict]") -> str:
    """Atomically replace the journal at `path` with `entries` — the
    replica-receive path (a shipped unit's journal REPLACES the held
    copy; same tmp+fsync+rename discipline as `write_checkpoint`)."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(directory, exist_ok=True)
    tmp = os.path.join(directory, f".{os.path.basename(path)}.tmp-{os.getpid()}")
    try:
        with open(tmp, "wb") as f:
            for entry in entries:
                f.write(
                    json.dumps(
                        entry, separators=(",", ":"), sort_keys=True
                    ).encode()
                    + b"\n"
                )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def read_journal(path: str, since_rv: int = 0) -> list[dict]:
    """Read a journal file's records past `since_rv`, tolerating a torn
    tail line (see `SessionJournal.entries`)."""
    out: list[dict] = []
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return out
    for line in raw.splitlines():
        if not line.strip():
            continue
        try:
            entry = json.loads(line)
        except ValueError:
            continue  # torn tail: the write it belonged to never ack'd
        if isinstance(entry, dict) and int(entry.get("rv", 0)) > since_rv:
            out.append(entry)
    return out


def replay_store_state(state: dict, entries: "list[dict]") -> dict:
    """Replay journal `entries` on top of a `ResourceStore.dump_state`
    dump, returning the advanced dump.

    Pure and idempotent: entries with ``rv <= state["rv"]`` are already
    IN the snapshot and are skipped, so replaying a journal twice (the
    double-adopt case) lands exactly one state. Objects land verbatim
    (rv/uid preserved) in event order, reproducing the insertion order
    the live store would have: ADDED (re-)inserts at the end, MODIFIED
    replaces in place, DELETED removes.
    """
    base_rv = int(state.get("rv", 0))
    books: "dict[str, dict[str, dict]]" = {}
    for kind in KINDS:
        book: "dict[str, dict]" = {}
        for obj in (state.get("objects") or {}).get(kind) or []:
            book[ResourceStore.key(kind, obj)] = obj
        books[kind] = book
    rv = base_rv
    for entry in sorted(entries, key=lambda e: int(e.get("rv", 0))):
        erv = int(entry.get("rv", 0))
        if erv <= base_rv:
            continue  # already folded into the snapshot
        kind = entry.get("k")
        obj = entry.get("o")
        if kind not in KINDS or not isinstance(obj, dict):
            continue
        key = ResourceStore.key(kind, obj)
        etype = entry.get("t")
        if etype == "DELETED":
            books[kind].pop(key, None)
        elif etype == "ADDED":
            books[kind].pop(key, None)
            books[kind][key] = obj
        else:  # MODIFIED (or unknown: treat as upsert-in-place)
            books[kind][key] = obj
        rv = max(rv, erv)
    return {
        "rv": rv,
        "objects": {kind: list(book.values()) for kind, book in books.items()},
    }


def replay_into_doc(doc: dict, entries: "list[dict]") -> dict:
    """A copy of `doc` with `entries` replayed into its store state
    (the input document is left untouched — it may be a still-verified
    transport payload). Counters/passSeq stay at the snapshot's values —
    the journal guarantees resource state, and the failure matrix
    (docs/fleet.md) says so out loud."""
    if not entries:
        return doc
    out = dict(doc)
    out["store"] = replay_store_state(doc.get("store") or {}, entries)
    return out


# -- transport units ---------------------------------------------------------


def build_unit(sid: str, doc: dict, entries: "list[dict] | None") -> dict:
    """The wire shape one session travels as (docs/fleet.md): digests
    computed over the canonical serialization, so the receiver can
    verify without trusting the transport."""
    unit = {"id": sid, "doc": doc, "sha256": canonical_digest(doc)}
    if entries:
        unit["journal"] = entries
        unit["journalSha256"] = canonical_digest(entries)
    return unit


def verify_unit(unit: dict) -> "tuple[dict, list[dict]]":
    """Validate a transport unit: shape, checkpoint format, and both
    payload digests. Returns (doc, journal entries); raises ValueError
    with a torn-transfer diagnosis on any mismatch."""
    if not isinstance(unit, dict):
        raise ValueError("transport unit must be a mapping")
    doc = unit.get("doc")
    if not isinstance(doc, dict):
        raise ValueError("transport unit carries no checkpoint document")
    claimed = unit.get("sha256")
    if not claimed:
        raise ValueError("transport unit carries no sha256 digest")
    actual = canonical_digest(doc)
    if actual != claimed:
        raise ValueError(
            f"checkpoint digest mismatch (claimed {claimed[:12]}…, got "
            f"{actual[:12]}…): torn or corrupted transfer, refusing to adopt"
        )
    entries = unit.get("journal") or []
    if not isinstance(entries, list):
        raise ValueError("transport unit journal must be a list")
    if entries:
        jclaimed = unit.get("journalSha256")
        if not jclaimed:
            raise ValueError("transport unit journal carries no digest")
        jactual = canonical_digest(entries)
        if jactual != jclaimed:
            raise ValueError(
                f"journal digest mismatch (claimed {jclaimed[:12]}…, got "
                f"{jactual[:12]}…): torn or corrupted transfer, refusing "
                f"to adopt"
            )
    return doc, entries
