"""The simulator HTTP server — the reference's full API surface.

Routes (reference: simulator/server/server.go:42-57):

    GET  /api/v1/schedulerconfiguration      current config (200)
    POST /api/v1/schedulerconfiguration      restart w/ new config (202)
    PUT  /api/v1/reset                       reset resources + config (202)
    GET  /api/v1/export                      ResourcesForImport JSON (200)
    POST /api/v1/import                      apply snapshot (200)
    GET  /api/v1/listwatchresources          list+watch stream (SSE-style)
    POST /api/v1/extender/<verb>/<id>        extender proxy (extender.py)

Two deliberate extensions (the reference exposes resource CRUD through its
embedded kube-apiserver, which this framework replaces with the in-process
typed store — SURVEY.md §2 #3):

    GET/PUT            /api/v1/resources/<kind>
    GET/DELETE         /api/v1/resources/<kind>/<ns>/<name>  (or /<name>)
    POST               /api/v1/schedule      run one batched scheduling pass
    GET                /api/v1/metrics       scheduling-pass counters
                                             (decisions/sec, utils/metrics.py;
                                             ?format=prometheus for text
                                             exposition)
    GET                /api/v1/debug/trace   flight-recorder window as Chrome
                                             trace-event JSON (Perfetto)
    POST               /api/v1/debug/profile arm/disarm a jax.profiler capture
    GET                /api/v1/events        live telemetry SSE stream
                                             (docs/observability.md)
    GET                /api/v1/timeseries    fleet & memory observatory
                                             sample window (per-pass HBM
                                             + cluster-quality samples,
                                             utils/fleetstats.py;
                                             KSS_FLEET_STATS=1)
    GET                /api/v1/alerts        SLO burn-rate alerts: active
                                             + per-objective status +
                                             the bounded transition
                                             history ring (utils/slo.py;
                                             KSS_SLO=1 or a PUT /slo
                                             override)
    GET/PUT            /api/v1/slo           the session's SLO objective
                                             set: GET status, PUT a
                                             declarative per-tenant
                                             override over the KSS_SLO_*
                                             defaults
    POST               /api/v1/lifecycle     run a ChaosSpec chaos timeline
                                             (lifecycle/engine.py, isolated store)
    GET                /api/v1/lifecycle/trace   last run's JSONL event trace
    GET                /  (or /ui)           built-in dashboard (webui.py)

The multi-tenant session plane (docs/sessions.md — server/sessions.py):

    GET/POST           /api/v1/sessions      list / create sessions
    GET/DELETE         /api/v1/sessions/<id> session info / destroy
    POST               /api/v1/sessions/<id>/fork    branch a session
    POST               /api/v1/sessions/<id>/evict   snapshot to disk now
    *                  /api/v1/sessions/<id>/<any route above>
                                             every route in this file,
                                             scoped to that session's
                                             store/scheduler/metrics

    GET                /api/v1/healthz       liveness (always 200)
    GET                /api/v1/readyz        readiness: 503 while the
                                             shared compile broker is
                                             cooldown-saturated or its
                                             worker crashed, or while
                                             the server is draining
                                             (state "draining", distinct
                                             from "cooldown-saturated")
    POST               /api/v1/admin/drain   begin the zero-loss drain
                                             (docs/resilience.md): shed
                                             new requests, finish
                                             in-flight passes, snapshot
                                             every session, quiesce the
                                             broker; GET reports status

Legacy (un-prefixed) routes operate on the implicit `default` session.
Admission control (session limit, per-session pending-pod quota, the
bounded concurrent-pass semaphore) sheds with the same structured 503 +
Retry-After as compile degradation.

The watch stream mirrors the reference's wire shape — a sequence of JSON
objects `{"Kind": ..., "EventType": ..., "Obj": {...}}` flushed per event
(simulator/resourcewatcher/streamwriter/streamwriter.go:18-51), with the
same `<kind>LastResourceVersion` query parameters and list-as-ADDED replay
when a version is absent (resourcewatcher.go:94-120). A stale version gets
a relist (the 410-Gone analogue) instead of silently dropped events.

Implementation is stdlib-only (ThreadingHTTPServer): the serving shell has
no third-party dependencies, matching the zero-install environment.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..models.store import KINDS, NAMESPACED, StaleResourceVersion
from ..utils import bundles as bundles_mod
from ..utils import fleetstats, locking
from ..utils import ledger as ledger_mod
from ..utils import metrics as metrics_mod
from ..utils import slo as slo_mod
from ..utils import telemetry
from ..utils.broker import CompileDeadlineExceeded, CompileUnavailable
from .replication import ReplicationPlane
from .service import (
    EngineDegraded,
    InvalidSchedulerConfiguration,
    SchedulerServiceDisabled,
    SimulatorService,
)
from .sessions import (
    DEFAULT_SESSION_ID,
    ServerSaturated,
    SessionBusy,
    SessionLimitExceeded,
    SessionManager,
    SessionQuotaExceeded,
    UnknownSession,
)

# Retry-After hint (seconds) on 503 degradation responses: long enough
# for a compile cooldown window to elapse, short enough that a client
# retry lands while the engine is probably healthy again.
DEGRADED_RETRY_AFTER_S = 2

# Bound of each SSE subscriber's event queue: past it the consumer is
# provably slower than the span source, and the subscriber is
# DISCONNECTED (drops counted in sseDroppedEvents) rather than served a
# silently gap-ridden stream (docs/sessions.md).
SSE_QUEUE_MAX = 4096

# kind → (watch wire name, lastResourceVersion query param); reference
# resourcewatcher.go:22-30 + handler/watcher.go:27-34 (note the singular
# "namespaceLastResourceVersion").
WATCH_KINDS = {
    "pods": ("pods", "podsLastResourceVersion"),
    "nodes": ("nodes", "nodesLastResourceVersion"),
    "pvs": ("persistentvolumes", "pvsLastResourceVersion"),
    "pvcs": ("persistentvolumeclaims", "pvcsLastResourceVersion"),
    "storageclasses": ("storageclasses", "scsLastResourceVersion"),
    "priorityclasses": ("priorityclasses", "pcsLastResourceVersion"),
    "namespaces": ("namespaces", "namespaceLastResourceVersion"),
}


@locking.guard_inferred
class SimulatorServer:
    """Owns the HTTP server thread over one `SimulatorService`."""

    def __init__(
        self,
        service: "SimulatorService | None" = None,
        host: str = "127.0.0.1",
        port: int = 1212,
        auto_schedule: bool = False,
        extender_service=None,
        cors_allowed_origins: "list[str] | None" = None,
        session_config: "dict | None" = None,
    ):
        self.service = service or SimulatorService()
        self.auto_schedule = auto_schedule
        self.extender_service = extender_service
        self.cors_allowed_origins = cors_allowed_origins or []
        # the multi-tenant session plane (server/sessions.py): adopts
        # self.service as the implicit `default` session and owns the
        # SHARED CompileBroker + admission knobs. `session_config`
        # overrides the KSS_* environment (tests, embedded drivers).
        self.sessions = SessionManager(self.service, **(session_config or {}))
        # the fleet durability plane's shipper (server/replication.py):
        # dormant until the router pushes a peer topology through
        # POST /api/v1/admin/replication. Registered with the manager so
        # drain ships one last round and sync-mode journal appends ride
        # the acknowledging thread to the ring successors.
        self.replication = ReplicationPlane(
            self.sessions, env=(session_config or {}).get("env")
        )
        self.sessions.set_replication(self.replication)
        # SSE subscriber accounting (the satellite hardening): live
        # subscriber count against the manager's cap, and the events
        # dropped on slow consumers (surfaced as sseDroppedEvents)
        self._sse_lock = locking.make_lock("http.sse")
        self._sse_subs = 0
        self._sse_dropped = 0
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self._thread: "threading.Thread | None" = None
        # one scenario/sweep run at a time over this server (KEP-140's
        # one-scenario-at-a-time; each request thread would otherwise
        # drive the device concurrently)
        self._scenario_lock = locking.make_lock("http.scenario")
        # POST /api/v1/debug/profile arming state: the active jax
        # profiler capture's log dir, or None (at most one per process —
        # jax.profiler is a process-wide singleton)
        self._profile_lock = locking.make_lock("http.profile")
        self._profile_dir: "str | None" = None
        # graceful-drain state (docs/resilience.md): begin_drain flips
        # `draining` (readyz 503 + request shedding) and runs the
        # session-plane drain on a background thread; `drain_done`
        # fires when every session is snapshotted and the broker is
        # quiesced — the CLI's SIGTERM path waits on it and exits 0
        self._drain_lock = locking.make_lock("http.drain")
        self._drain_thread: "threading.Thread | None" = None
        self._drain_result: "dict | None" = None
        self.drain_done = threading.Event()
        # birth stamp for healthz/readyz uptimeSeconds (docs/fleet.md):
        # the fleet router's probe reads structured health bodies, so
        # liveness, identity, and load ride the endpoints it already
        # polls instead of a second status surface
        self._started_monotonic = time.monotonic()

    def health_doc(self) -> dict:
        """The shared healthz/readyz body fields: worker identity
        (KSS_WORKER_ID, None outside a fleet), uptime, drain state, and
        the resident-session count — everything the fleet router's
        prober needs from the one endpoint it already polls."""
        return {
            "workerId": metrics_mod.worker_id(),
            "uptimeSeconds": round(
                time.monotonic() - self._started_monotonic, 3
            ),
            "draining": self.draining,
            "activeSessions": len(self.sessions.live_services()),
        }

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self):
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def shutdown(self):
        self.sessions.shutdown()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    # -- graceful drain (docs/resilience.md) --------------------------------

    @property
    def draining(self) -> bool:
        return self.sessions.is_draining()

    def begin_drain(self, deadline_s: "float | None" = None) -> bool:
        """Start the zero-loss drain on a background thread (the route
        and the SIGTERM handler both call this; neither may block for
        the drain deadline). False when a drain is already running —
        begin is idempotent, the first caller wins."""
        with self._drain_lock:
            # shed + readyz flip NOW — an atomic test-and-set under the
            # MANAGER's lock (the flag is its claimed state, KSS6xx)
            if not self.sessions.begin_draining():
                return False
            self._drain_thread = threading.Thread(
                target=self._drain_run,
                args=(deadline_s,),
                name="kss-drain",
                daemon=True,
            )
            self._drain_thread.start()
            return True

    def _drain_run(self, deadline_s: "float | None") -> None:
        try:
            self._drain_result = self.sessions.drain(deadline_s)
        except Exception as e:  # noqa: BLE001 — a failed drain must not hang exit
            self._drain_result = {"error": f"{type(e).__name__}: {e}"}
        finally:
            self.drain_done.set()

    def drain(self, deadline_s: "float | None" = None, timeout=None) -> dict:
        """Synchronous drain: begin (if not already begun) and wait for
        completion. Embedded drivers and tests use this; the serving
        CLI prefers begin_drain + waiting on `drain_done`."""
        self.begin_drain(deadline_s)
        self.drain_done.wait(timeout)
        return self.drain_status()

    def drain_status(self) -> dict:
        return {
            "draining": self.draining,
            "done": self.drain_done.is_set(),
            "result": self._drain_result,
        }

    def maybe_schedule(self, service: "SimulatorService | None" = None):
        """Post-mutation convergence for the mutated session: the
        controller subset always runs to fixpoint (the reference's
        continuously-running controllers — POST a Deployment, GET its
        Pods), then a scheduling pass follows when --auto-schedule is
        on."""
        svc = service if service is not None else self.service
        svc.run_controllers()
        if self.auto_schedule and not svc.scheduler.disabled:
            # auto-passes obey the same bounded-concurrency semaphore as
            # explicit /schedule; at saturation the pass is SKIPPED (the
            # pod stays pending — the next mutation or an explicit
            # schedule converges it) rather than 503-failing the CRUD
            # that triggered it, and rather than queueing unboundedly
            # behind the device
            if svc.scheduler._schedule_lock.locked():
                # a pass is already converging this session: skip, don't
                # queue on its lock while holding a global slot
                return
            try:
                with self.sessions.pass_slot():
                    svc.scheduler.schedule()
            except ServerSaturated:
                pass

    # -- SSE subscriber accounting ------------------------------------------

    def sse_acquire(self) -> bool:
        """Claim one SSE subscriber slot against the cap
        (KSS_SSE_MAX_SUBSCRIBERS); False = saturated, the route sheds."""
        with self._sse_lock:
            if self._sse_subs >= self.sessions.sse_max_subscribers:
                return False
            self._sse_subs += 1
            return True

    def sse_release(self) -> None:
        with self._sse_lock:
            self._sse_subs -= 1

    def sse_count_drop(self, n: int = 1) -> None:
        with self._sse_lock:
            self._sse_dropped += n

    @property
    def sse_dropped(self) -> int:
        with self._sse_lock:
            return self._sse_dropped


def _make_handler(server: SimulatorServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # -- plumbing -------------------------------------------------------

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _cors_headers(self):
            """CORS per the configured allowlist (reference: echo CORS
            middleware fed by CORS_ALLOWED_ORIGIN_LIST, server.go:29-32)."""
            origin = self.headers.get("Origin")
            if origin and origin in server.cors_allowed_origins:
                self.send_header("Access-Control-Allow-Origin", origin)
                self.send_header("Access-Control-Allow-Credentials", "true")

        def _json(self, code: int, payload=None, headers: "dict | None" = None):
            body = b"" if payload is None else json.dumps(payload).encode()
            self.send_response(code)
            self._cors_headers()
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for name, value in (headers or {}).items():
                self.send_header(name, value)
            self.end_headers()
            if body:
                self.wfile.write(body)

        def _error(
            self,
            code: int,
            msg: str,
            kind: str = "",
            detail: str = "",
            headers: "dict | None" = None,
        ):
            """Structured JSON error: `error` is the human line, `kind`
            the machine-matchable class (exception name or an HTTP-ish
            label), `detail` optional context. `message` mirrors `error`
            for pre-existing clients of the old single-key shape."""
            self._json(
                code,
                {
                    "error": msg,
                    "kind": kind or ("client-error" if code < 500 else "server-error"),
                    "detail": detail,
                    "message": msg,
                },
                headers=headers,
            )

        def _degraded(self, e: Exception):
            """Engine-degradation failures (compile deadline exhausted
            with the eager rung unable to serve) map to 503 + a
            Retry-After hint: the condition is load/compile-shaped and
            retryable, not a client error (docs/resilience.md)."""
            return self._error(
                503,
                str(e),
                kind=type(e).__name__,
                detail="engine degraded: compile ladder exhausted; retry "
                "after the cooldown",
                headers={"Retry-After": str(DEGRADED_RETRY_AFTER_S)},
            )

        def _body(self):
            """Parse the request body: JSON first, YAML fallback — the
            dashboard's editor submits the same YAML a kubectl user would
            paste (reference web: Monaco YAML editor)."""
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b""
            if not raw:
                return None
            try:
                return json.loads(raw)
            except json.JSONDecodeError:
                import yaml

                return yaml.safe_load(raw)

        # -- dispatch -------------------------------------------------------

        def do_GET(self):  # noqa: N802 (stdlib casing)
            self._route("GET")

        def do_POST(self):  # noqa: N802
            self._route("POST")

        def do_PUT(self):  # noqa: N802
            self._route("PUT")

        def do_DELETE(self):  # noqa: N802
            self._route("DELETE")

        def do_OPTIONS(self):  # noqa: N802 — CORS preflight
            self.send_response(204)
            self._cors_headers()
            self.send_header(
                "Access-Control-Allow-Methods", "GET, POST, PUT, DELETE"
            )
            self.send_header("Access-Control-Allow-Headers", "Content-Type")
            self.send_header("Content-Length", "0")
            self.end_headers()

        def end_headers(self):  # noqa: N802 (stdlib casing)
            # distributed tracing (docs/observability.md): report the
            # worker-side wall for this request so the router can split
            # request latency into net vs worker without a second probe.
            # Gated on propagation so untraced runs stay byte-identical.
            t0 = getattr(self, "_kss_t0", None)
            if t0 is not None and telemetry.propagate_enabled():
                self.send_header(
                    "X-KSS-Worker-Seconds",
                    f"{time.perf_counter() - t0:.6f}",
                )
            self._kss_t0 = None
            super().end_headers()

        def _route(self, method: str):
            # distributed-trace adoption chokepoint: EVERY api call
            # funnels through here, so parsing the router-minted
            # traceparent once and entering trace_context makes pass,
            # compile, and device.execute spans carry the originating
            # request's trace id (docs/observability.md). Malformed or
            # absent headers degrade to untraced — never an error.
            self._kss_t0 = time.perf_counter()
            tid = None
            if telemetry.propagate_enabled():
                tid = telemetry.parse_traceparent(
                    self.headers.get("traceparent")
                )
            if tid is None:
                return self._route_inner(method)
            with telemetry.trace_context(tid):
                return self._route_inner(method)

        def _route_inner(self, method: str):
            url = urlparse(self.path)
            parts = [p for p in url.path.split("/") if p]
            try:
                if method == "GET" and parts in ([], ["ui"]):
                    from .webui import PAGE

                    body = PAGE.encode()
                    self.send_response(200)
                    self._cors_headers()
                    self.send_header("Content-Type", "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return None
                if parts[:2] != ["api", "v1"]:
                    return self._error(404, "not found")
                rest = parts[2:]
                if rest == ["healthz"] and method == "GET":
                    # structured liveness (docs/fleet.md): the status
                    # code contract is unchanged (always 200); the body
                    # carries identity + uptime + drain state so the
                    # fleet prober needs no second endpoint
                    doc = {"ok": True}
                    doc.update(server.health_doc())
                    return self._json(200, doc)
                if rest == ["readyz"] and method == "GET":
                    return self._readyz()
                if rest == ["admin", "drain"]:
                    if method == "POST":
                        started = server.begin_drain()
                        doc = server.drain_status()
                        doc["started"] = started
                        return self._json(202, doc)
                    if method == "GET":
                        return self._json(200, server.drain_status())
                    return self._error(405, "method not allowed")
                if rest[:2] == ["admin", "checkpoints"] and method == "GET":
                    # the cross-host checkpoint transport's read side
                    # (docs/fleet.md): list sessions + payload digests,
                    # or fetch one as a digest-guarded transport unit.
                    # Deliberately ABOVE the draining shed — the router
                    # re-homes a draining/drained worker's sessions by
                    # fetching them from here.
                    if len(rest) == 2:
                        return self._json(
                            200, server.sessions.checkpoint_index()
                        )
                    if len(rest) == 3:
                        unit = server.sessions.checkpoint_unit(rest[2])
                        if unit is None:
                            return self._error(
                                404,
                                f"no checkpoint for session {rest[2]!r}",
                                kind="UnknownSession",
                            )
                        return self._json(200, unit)
                if rest == ["admin", "replication"]:
                    # router-pushed replication topology (docs/fleet.md);
                    # answerable while draining — membership pushes race
                    # rolling drains and must not bounce
                    if method == "POST":
                        return self._json(
                            200,
                            server.replication.configure(self._body() or {}),
                        )
                    if method == "GET":
                        return self._json(200, server.replication.stats())
                    return self._error(405, "method not allowed")
                if rest == ["admin", "adopt"] and not server.draining:
                    # session adoption (docs/fleet.md). The body selects
                    # the mode; an empty body is the legacy shared-dir
                    # re-scan of KSS_SESSION_DIR. Body-carried modes:
                    #   {"checkpoints": [unit...]}           adopt live
                    #   {"checkpoints": [...], "replica": 1} hold passively
                    #   {"journalAppend": {...}}             sync-mode entry
                    #   {"promote": [sid...]}                replica -> live
                    # All idempotent: known ids are skipped, digests are
                    # verified before anything lands. A DRAINING server
                    # falls through to the shed below — it must not
                    # admit tenants its own drain will never snapshot.
                    if method == "POST":
                        body = self._body()
                        if not body:
                            return self._json(
                                200,
                                {"adopted": server.sessions.adopt_snapshots()},
                            )
                        if not isinstance(body, dict):
                            return self._error(
                                400,
                                "adopt body must be a JSON object",
                                kind="BadAdoptBody",
                            )
                        try:
                            doc = {}
                            if body.get("checkpoints") is not None:
                                doc.update(
                                    server.sessions.receive_checkpoints(
                                        body["checkpoints"],
                                        replica=bool(body.get("replica")),
                                    )
                                )
                            if body.get("journalAppend") is not None:
                                doc.update(
                                    server.sessions.append_replica_journal(
                                        body["journalAppend"]
                                    )
                                )
                            if body.get("promote") is not None:
                                doc.update(
                                    server.sessions.promote_replicas(
                                        body["promote"] or None
                                    )
                                )
                        except ValueError as e:
                            return self._error(
                                400, str(e), kind="BadAdoptBody"
                            )
                        if not doc:
                            return self._error(
                                400,
                                "adopt body carries none of checkpoints/"
                                "journalAppend/promote",
                                kind="BadAdoptBody",
                            )
                        return self._json(200, doc)
                    return self._error(405, "method not allowed")
                if server.draining and not (
                    method == "GET" and rest == ["metrics"]
                ):
                    # the zero-loss drain path (docs/resilience.md):
                    # new work is shed with the same structured 503 +
                    # Retry-After shape as admission control, while
                    # in-flight passes finish and sessions snapshot.
                    # Health, readiness, drain status, and the legacy
                    # metrics scrape stay answerable — an operator must
                    # be able to watch the drain complete.
                    return self._error(
                        503,
                        "server is draining; retry against another replica",
                        kind="ServerDraining",
                        detail="graceful drain in progress: new requests "
                        "are shed, in-flight passes finish, sessions "
                        "snapshot to disk",
                        headers={"Retry-After": str(DEGRADED_RETRY_AFTER_S)},
                    )
                if rest and rest[0] == "sessions":
                    return self._sessions_route(method, rest[1:], url)
                # legacy (un-prefixed) surface: the implicit default
                # session — sid None marks the legacy entry, which the
                # metrics route uses to scrape EVERY session at once
                return self._api(method, rest, url, server.service, None)
            except BrokenPipeError:
                raise
            except UnknownSession as e:
                return self._error(404, str(e), kind="UnknownSession")
            except (
                SessionLimitExceeded,
                SessionQuotaExceeded,
                ServerSaturated,
            ) as e:
                # admission control sheds with the SAME structured 503 +
                # Retry-After shape as compile degradation: overload is
                # a retryable service condition (docs/sessions.md)
                return self._error(
                    503,
                    str(e),
                    kind=type(e).__name__,
                    detail="admission control: load shed; retry after the "
                    "hinted backoff",
                    headers={"Retry-After": str(e.retry_after_s)},
                )
            except SessionBusy as e:
                return self._error(409, str(e), kind="SessionBusy")
            except SchedulerServiceDisabled as e:
                # reference schedulerconfig.go:32-34: external-scheduler
                # mode answers config/scheduling calls with 400
                return self._error(400, str(e), kind="SchedulerServiceDisabled")
            except InvalidSchedulerConfiguration as e:
                return self._error(500, str(e), kind="InvalidSchedulerConfiguration")
            except (EngineDegraded, CompileUnavailable, CompileDeadlineExceeded) as e:
                # the degradation ladder's terminal failures are
                # retryable service conditions, not server bugs: 503
                return self._degraded(e)
            except Exception as e:  # noqa: BLE001 — boundary
                return self._error(
                    500,
                    f"{type(e).__name__}: {e}",
                    kind=type(e).__name__,
                    detail="unhandled error at the API boundary",
                )

        # -- session plane --------------------------------------------------

        def _readyz(self):
            """Readiness for external load balancers: not-ready while
            the SHARED broker is cooldown-saturated (some session's
            compile ladder is exhausted and cooling) or its speculative
            worker crashed — a sick compile plane should be drained, not
            handed fresh tenants. A DRAINING server is also not-ready,
            with the distinct ``state: "draining"`` (docs/resilience.md)
            so orchestrators can tell an intentional rolling-restart
            drain from a sick compile plane."""
            if server.draining:
                doc = {
                    "ready": False,
                    "state": "draining",
                    "reasons": ["server is draining"],
                    "drain": server.drain_status(),
                }
                doc.update(server.health_doc())
                return self._json(
                    503, doc, headers={"Retry-After": str(DEGRADED_RETRY_AFTER_S)}
                )
            health = server.sessions.broker.health()
            reasons = []
            if health["cooldownKeys"]:
                reasons.append(
                    f"{health['cooldownKeys']} compile key(s) in cooldown"
                )
            if health["stuckCompiles"]:
                reasons.append(
                    f"{health['stuckCompiles']} wedged compile thread(s)"
                )
            if health["workerCrashed"]:
                reasons.append("speculative compile worker crashed")
            doc = {
                "ready": not reasons,
                "state": "cooldown-saturated" if reasons else "ready",
                "reasons": reasons,
                "broker": health,
            }
            doc.update(server.health_doc())
            if reasons:
                return self._json(
                    503, doc, headers={"Retry-After": str(DEGRADED_RETRY_AFTER_S)}
                )
            return self._json(200, doc)

        def _sessions_route(self, method: str, rest: list[str], url):
            mgr = server.sessions
            if not rest:
                if method == "GET":
                    return self._json(
                        200,
                        {
                            "sessions": mgr.list_info(),
                            "broker": mgr.broker.stats(),
                            "limits": mgr.stats(),
                        },
                    )
                if method == "POST":
                    body = self._body() or {}
                    if not isinstance(body, dict):
                        return self._error(400, "session spec must be a mapping")
                    try:
                        sess, errors = mgr.create(
                            name=body.get("name"),
                            snapshot=body.get("snapshot"),
                            fault_inject=body.get("faultInject"),
                            slo=body.get("slo"),
                            # explicit id: the fleet router pins the id
                            # it hashed onto this worker (docs/fleet.md)
                            session_id=body.get("id"),
                        )
                    except ValueError as e:
                        # a malformed faultInject spec is the client's
                        # input (admission errors raise their own types)
                        return self._error(400, str(e))
                    doc = sess.info()
                    doc["errors"] = errors
                    return self._json(201, doc)
                return self._error(405, "method not allowed")
            sid, sub = rest[0], rest[1:]
            if not sub:
                if method == "GET":
                    return self._json(200, mgr.info(sid))
                if method == "DELETE":
                    try:
                        mgr.delete(sid)
                    except ValueError as e:
                        return self._error(400, str(e))
                    return self._json(200, {"deleted": sid})
                return self._error(405, "method not allowed")
            if sub == ["fork"] and method == "POST":
                body = self._body() or {}
                sess = mgr.fork(sid, name=(body or {}).get("name"))
                return self._json(201, sess.info())
            if sub == ["evict"] and method == "POST":
                try:
                    path = mgr.evict(sid)
                except ValueError as e:
                    return self._error(400, str(e))
                return self._json(200, {"evicted": sid, "snapshot": path})
            # any other sub-path: the full API surface scoped to this
            # session (restoring it from its snapshot if evicted). The
            # `using` window registers the request with the manager so
            # the idle sweeper cannot evict the service out from under a
            # mutation it is about to acknowledge
            with mgr.using(sid) as sess:
                return self._api(method, sub, url, sess.service, sid)

        # -- the per-session API surface ------------------------------------

        def _api(self, method: str, rest: list[str], url, svc, sid):
            """Every route of the original single-tenant surface, bound
            to `svc` — the default session for legacy paths (sid None)
            or the session addressed by /api/v1/sessions/<sid>/...."""
            if rest == ["schedulerconfiguration"]:
                return self._scheduler_config(method, svc)
            if rest == ["reset"] and method == "PUT":
                svc.reset()
                return self._json(202)
            if rest == ["export"] and method == "GET":
                return self._json(200, svc.export())
            if rest == ["import"] and method == "POST":
                body = self._body() or {}
                # bulk pod entry obeys the same per-session quota as
                # one-at-a-time CRUD (docs/sessions.md)
                server.sessions.admit_import(svc, body)
                errs = svc.import_(body)
                server.maybe_schedule(svc)
                return self._json(200, {"errors": errs})
            if rest == ["listwatchresources"] and method == "GET":
                return self._list_watch(parse_qs(url.query), svc)
            if rest == ["metrics"] and method == "GET":
                return self._metrics(parse_qs(url.query), svc, sid)
            if rest == ["alerts"] and method == "GET":
                # the SLO plane's alert surface (utils/slo.py,
                # docs/observability.md): active alerts + per-objective
                # status for the addressed session (legacy route: every
                # live session), and the process-wide bounded history
                # ring of pending -> firing -> resolved transitions.
                # Unarmed servers answer an honest empty document.
                return self._alerts(svc, sid)
            if rest == ["slo"]:
                # per-session SLO objectives: GET the current status,
                # PUT a declarative override (docs/observability.md) —
                # the per-tenant knob over the KSS_SLO_* defaults
                return self._slo(method, svc, sid)
            if rest == ["debug", "trace"] and method == "GET":
                # the flight recorder's retained window as Chrome
                # trace-event JSON — loadable as-is in Perfetto
                # (docs/observability.md). With tracing off the
                # document is empty but still loadable, and
                # otherData.tracingEnabled says why. Process-global:
                # every session's spans share the one ring (each span
                # carries its session id in args).
                rec = telemetry.active()
                events = rec.snapshot() if rec is not None else []
                doc = telemetry.chrome_trace(
                    events, dropped=rec.dropped if rec is not None else 0
                )
                doc["otherData"]["tracingEnabled"] = rec is not None
                # monotonic-clock sample for the router's merged-trace
                # offset handshake (docs/observability.md): the router
                # brackets this fetch with its own clock and estimates
                # offset = midpoint - clockUs
                doc["otherData"]["clockUs"] = telemetry.clock_us()
                return self._json(200, doc)
            if rest == ["debug", "programs"] and method == "GET":
                # the per-program performance ledger (utils/ledger.py,
                # docs/observability.md): every broker-jitted program's
                # compile wall (lowering/backend split), cost-model
                # FLOPs/bytes, memory bytes, call count, dispatch
                # seconds, sampled warm wall, and derived MFU — keyed
                # (site label, compile fingerprint), with per-session
                # call attribution. Nested routes filter to programs
                # the addressed session's passes dispatched. Armed by
                # KSS_PROGRAM_LEDGER=1; unarmed servers answer an
                # empty (but honest) document.
                doc = ledger_mod.LEDGER.snapshot(session=sid)
                doc["enabled"] = ledger_mod.ledger_enabled()
                return self._json(200, doc)
            if rest == ["timeseries"] and method == "GET":
                # the fleet & memory observatory's sample window
                # (utils/fleetstats.py, docs/observability.md): per-pass
                # device-HBM + cluster-quality samples from the bounded
                # ring. Nested session routes (and ?session= on the
                # legacy route) filter to one tenant's samples; ?limit=N
                # keeps the last N, ?sinceSeq=K resumes past a seen
                # sequence number. Unarmed servers answer an empty (but
                # honest) document.
                q = parse_qs(url.query)
                session_filter = sid or q.get("session", [None])[0]
                rec = fleetstats.active()
                samples = rec.snapshot() if rec is not None else []
                if session_filter is not None:
                    samples = [
                        s
                        for s in samples
                        if s.get("session") == session_filter
                    ]
                for param, key in (("sinceSeq", "since"), ("limit", "limit")):
                    raw = q.get(param, [None])[0]
                    if raw is None:
                        continue
                    try:
                        n = int(raw)
                    except ValueError:
                        return self._error(
                            400, f"{param} must be an integer, got {raw!r}"
                        )
                    if key == "since":
                        samples = [s for s in samples if s["seq"] > n]
                    elif n >= 0:
                        samples = samples[-n:] if n else []
                return self._json(
                    200,
                    {
                        "enabled": rec is not None,
                        "capacity": rec.capacity if rec is not None else 0,
                        "emitted": rec.emitted if rec is not None else 0,
                        "dropped": rec.dropped if rec is not None else 0,
                        "samples": samples,
                    },
                )
            if rest == ["debug", "profile"] and method == "POST":
                return self._debug_profile(self._body() or {})
            if rest == ["events"] and method == "GET":
                q = parse_qs(url.query)
                # nested routes filter to their session; the legacy
                # stream carries everything unless ?session= narrows it
                session_filter = sid or q.get("session", [None])[0]
                if sid is None and session_filter is not None:
                    # validate + resolve so the metrics feed is the
                    # filtered session's, not the default's
                    svc = server.sessions.get(session_filter).service
                return self._events_stream(q, svc, session_filter)
            if rest == ["schedule"] and method == "POST":
                mode = parse_qs(url.query).get("mode", ["sequential"])[0]
                if mode not in ("sequential", "gang"):
                    return self._error(
                        400, f"unknown scheduling mode {mode!r}"
                    )
                if svc.scheduler._schedule_lock.locked():
                    # this session already has a pass in flight: shed
                    # NOW, before claiming a concurrent-pass slot —
                    # queued same-session requests would otherwise sit
                    # on the global slots doing no device work, starving
                    # every other tenant (the semaphore bounds device
                    # concurrency, not waiting-room depth)
                    raise ServerSaturated(
                        f"session {svc.scheduler.session_id or 'default'!r} "
                        f"already has a pass in flight; retry later"
                    )
                if mode == "gang":
                    # records default ON (the annotations are the
                    # product); ?record=0 is the bulk opt-out;
                    # ?window=W passes eval_window through (the
                    # at-scale round-cost lever)
                    q = parse_qs(url.query)
                    rec_q = q.get("record", ["1"])[0]
                    record = rec_q not in ("0", "false", "no")
                    window = None
                    if "window" in q:
                        try:
                            window = int(q["window"][0])
                        except ValueError:
                            return self._error(
                                400,
                                f"window must be an integer, got"
                                f" {q['window'][0]!r}",
                            )
                    try:
                        with server.sessions.pass_slot():
                            placements, rounds, results = (
                                svc.scheduler.schedule_gang(
                                    record=record, window=window
                                )
                            )
                    except ValueError as e:
                        # known-unsupported combination (extenders
                        # configured) is the client's request, not a
                        # server fault
                        return self._error(400, str(e))
                    body = {
                        "mode": "gang",
                        "rounds": rounds,
                        "scheduled": sum(
                            1 for v in placements.values() if v
                        ),
                        "unschedulable": sum(
                            1 for v in placements.values() if not v
                        ),
                    }
                    if results is not None:
                        body["results"] = [
                            {
                                "namespace": r.pod_namespace,
                                "name": r.pod_name,
                                "status": r.status,
                                "selectedNode": r.selected_node,
                            }
                            for r in results
                        ]
                    return self._json(200, body)
                with server.sessions.pass_slot():
                    results = svc.scheduler.schedule()
                return self._json(
                    200,
                    {
                        "scheduled": sum(
                            1 for r in results if r.status == "Scheduled"
                        ),
                        "results": [
                            {
                                "namespace": r.pod_namespace,
                                "name": r.pod_name,
                                "status": r.status,
                                "selectedNode": r.selected_node,
                            }
                            for r in results
                        ],
                    },
                )
            if rest == ["scenario"] and method == "POST":
                # one-shot KEP-140 scenario / KEP-159 sweep run over
                # the serving shell: the body is a batch-job spec
                # (scenario/batch.py — operations + schedulerConfig,
                # or a sweep snapshot + weightVariants). Runs against
                # its OWN isolated store (KEP-140's one-scenario-at-
                # a-time pre-cleaned cluster, README.md:600-610), not
                # the server's; synchronous, returns the result doc.
                # Concurrent scenario requests serialize (KEP: one
                # scenario at a time; run_job additionally holds the
                # process-wide device lock for sweep jobs) and take a
                # concurrent-pass slot — scenario storms shed like any
                # other device-driving overload.
                from ..scenario.batch import BatchJob, run_job

                try:
                    spec = self._body() or {}
                    if not isinstance(spec, dict):
                        return self._error(400, "spec must be a mapping")
                    job = BatchJob.from_spec(
                        spec.get("name", "http-scenario"), spec
                    )
                except (ValueError, KeyError, AttributeError, TypeError) as e:
                    return self._error(400, f"{type(e).__name__}: {e}")
                # scenario lock FIRST (blocking, holding nothing), slot
                # second: waiting on the one-timeline-at-a-time lock
                # while holding a global pass slot would starve other
                # sessions' device work
                with server._scenario_lock, server.sessions.pass_slot():
                    return self._json(200, run_job(job))
            if rest == ["lifecycle"] and method == "POST":
                # one-shot cluster-lifecycle chaos run: the body is a
                # ChaosSpec (scenario/chaos.py — seeded fault schedule
                # + arrival processes + optional snapshot). Runs over
                # its OWN isolated store (svc.run_lifecycle), the
                # serving store is untouched; synchronous, returns the
                # result document WITH the replayable trace inline.
                # Serialized with scenario runs (one device-driving
                # timeline at a time); metrics flow into the addressed
                # session's registry.
                from ..scenario.chaos import ChaosSpec

                try:
                    spec = ChaosSpec.from_dict(self._body() or {})
                except (ValueError, KeyError, TypeError) as e:
                    return self._error(400, f"{type(e).__name__}: {e}")
                try:
                    # same ordering rationale as the scenario route
                    with server._scenario_lock, server.sessions.pass_slot():
                        result = svc.run_lifecycle(spec)
                        # read under the lock: a concurrent run must
                        # not swap ITS trace into THIS response
                        result["trace"] = svc.last_lifecycle_trace
                except ValueError as e:
                    # a spec that parses but can't build a run (bad
                    # snapshot, unusable scheduler config) is the
                    # client's input, not a server fault
                    return self._error(400, str(e))
                return self._json(200, result)
            if rest == ["lifecycle", "trace"] and method == "GET":
                # the last run's replayable event trace as JSONL
                # (application/x-ndjson), byte-identical across
                # re-runs of the same seeded spec
                trace = svc.last_lifecycle_trace
                if trace is None:
                    return self._error(404, "no lifecycle run yet")
                from ..lifecycle.engine import trace_jsonl

                body = trace_jsonl(trace).encode()
                self.send_response(200)
                self._cors_headers()
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return None
            if rest and rest[0] == "extender":
                return self._extender(method, rest[1:], svc)
            if rest and rest[0] == "resources":
                return self._resources(
                    method, rest[1:], parse_qs(url.query), svc
                )
            return self._error(404, "not found")

        # -- handlers -------------------------------------------------------

        def _scheduler_config(self, method: str, svc):
            if method == "GET":
                return self._json(200, svc.scheduler.get_config())
            if method == "POST":
                # only .profiles (+ .extenders) are honored, reference
                # convertConfigurationForSimulator semantics (config parse
                # enforces this downstream)
                svc.scheduler.restart(self._body() or {})
                return self._json(202)
            return self._error(405, "method not allowed")

        def _resources(self, method: str, rest: list[str], q: dict, svc):
            if not rest or rest[0] not in KINDS:
                return self._error(404, f"unknown kind {rest[:1]}")
            kind = rest[0]
            if len(rest) == 1:
                if method == "GET":
                    return self._json(200, {"items": svc.store.list(kind)})
                if method in ("POST", "PUT"):
                    body = self._body() or {}
                    if kind == "pods":
                        # per-session pending-pod quota: shed BEFORE the
                        # store mutation (docs/sessions.md)
                        server.sessions.admit_pod(svc, body)
                    obj = svc.store.apply(kind, body)
                    server.maybe_schedule(svc)
                    return self._json(201, obj)
            else:
                if len(rest) == 3:
                    namespace, name = rest[1], rest[2]
                elif len(rest) == 2:
                    namespace, name = "default", rest[1]
                else:
                    return self._error(404, "bad resource path")
                if method == "GET":
                    obj = svc.store.get(kind, name, namespace)
                    if obj is None:
                        return self._error(404, "not found")
                    if q.get("format", [None])[0] == "yaml":
                        import yaml

                        body = yaml.safe_dump(
                            obj, sort_keys=False, default_flow_style=False
                        ).encode()
                        self.send_response(200)
                        self._cors_headers()
                        self.send_header(
                            "Content-Type", "application/yaml; charset=utf-8"
                        )
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return None
                    return self._json(200, obj)
                if method == "PUT":
                    # item-path PUT = wholesale replace (kubectl replace):
                    # fields absent from the body are removed — the YAML
                    # editor's save semantics. Collection POST/PUT keeps
                    # the SSA-style merge.
                    obj = self._body() or {}
                    meta = obj.get("metadata", {}) or {}
                    if meta.get("name") and meta["name"] != name:
                        return self._error(
                            400,
                            f"body names {meta['name']!r}, path names {name!r}",
                        )
                    meta["name"] = name
                    if NAMESPACED.get(kind):
                        # a body namespace differing from the path would
                        # silently replace a DIFFERENT object; reject it
                        # like the name mismatch above
                        if meta.get("namespace") and meta["namespace"] != namespace:
                            return self._error(
                                400,
                                f"body namespace {meta['namespace']!r} does "
                                f"not match path namespace {namespace!r}",
                            )
                        meta["namespace"] = namespace
                    obj["metadata"] = meta
                    if kind == "pods":
                        # quota-metered like a collection apply, plus the
                        # replace-only transition: a body omitting
                        # spec.nodeName UNBINDS a bound pod back into the
                        # pending queue (replace deletes absent fields)
                        server.sessions.admit_pod(svc, obj, replace=True)
                    out = svc.store.replace(kind, obj)
                    server.maybe_schedule(svc)
                    return self._json(200, out)
                if method == "DELETE":
                    ok = svc.store.delete(kind, name, namespace)
                    if not ok:
                        return self._error(404, "not found")
                    server.maybe_schedule(svc)
                    return self._json(200)
            return self._error(405, "method not allowed")

        def _extender(self, method: str, rest: list[str], svc):
            ext = (
                server.extender_service
                or svc.scheduler.current_extender_service()
            )
            if method != "POST" or len(rest) != 2:
                return self._error(404, "bad extender path")
            verb, id_str = rest
            out = ext.handle(verb, int(id_str), self._body())
            return self._json(200, out)

        # -- telemetry plane ------------------------------------------------

        def _debug_profile(self, body: dict):
            """Arm / disarm a `jax.profiler` trace capture over HTTP
            (docs/observability.md): ``{"action": "start", "logDir":
            optional}`` begins a TensorBoard/XProf capture of everything
            the process runs next; ``{"action": "stop"}`` ends it. One
            capture at a time — jax.profiler is process-global."""
            import jax

            action = body.get("action")
            if action == "start":
                with server._profile_lock:
                    if server._profile_dir is not None:
                        return self._error(
                            409,
                            f"profile already running into "
                            f"{server._profile_dir!r}; stop it first",
                        )
                    log_dir = body.get("logDir")
                    if not log_dir:
                        import tempfile

                        log_dir = tempfile.mkdtemp(prefix="kss-profile-")
                    jax.profiler.start_trace(log_dir)
                    server._profile_dir = log_dir
                return self._json(
                    200, {"profiling": True, "logDir": log_dir}
                )
            if action == "stop":
                with server._profile_lock:
                    if server._profile_dir is None:
                        return self._error(409, "no profile running")
                    log_dir, server._profile_dir = server._profile_dir, None
                    jax.profiler.stop_trace()
                return self._json(
                    200, {"profiling": False, "logDir": log_dir}
                )
            return self._error(
                400, f"action must be start|stop, got {action!r}"
            )

        def _alerts(self, svc, sid):
            """GET /api/v1/alerts (+ nested session form): the SLO
            plane's judgement surface — per-objective status (burn
            rates, compliance, alert state) for the addressed session
            (legacy route: every live session), the currently
            pending/firing alerts, and the bounded history ring of
            transitions. Statuses evaluate BEFORE the history snapshot
            so a just-crossed threshold's transition is in both."""
            if sid is None:
                planes = [
                    (session_id, service.scheduler.metrics.slo_plane())
                    for session_id, service in server.sessions.live_services()
                ]
            else:
                planes = [(sid, svc.scheduler.metrics.slo_plane())]
            sessions_doc: dict = {}
            active: list = []
            enabled = False
            for session_id, plane in planes:
                if plane is None:
                    continue
                enabled = True
                # status() evaluates first: alert states are current,
                # and any transition lands in the ring before the
                # history snapshot below
                sessions_doc[session_id or DEFAULT_SESSION_ID] = (
                    plane.status()
                )
                active.extend(plane.active_alerts())
            log = slo_mod.alert_log()
            history = log.snapshot()
            if sid is not None:
                history = [
                    ev for ev in history if ev.get("session") == sid
                ]
            return self._json(
                200,
                {
                    "enabled": enabled,
                    "active": active,
                    "sessions": sessions_doc,
                    "history": history,
                    "historyEmitted": log.emitted,
                    "historyDropped": log.dropped,
                    "counters": log.counters(),
                },
            )

        def _slo(self, method: str, svc, sid):
            """GET/PUT /api/v1/slo (+ nested session form): the
            per-tenant objective override (docs/observability.md). PUT
            installs an explicit plane for the session — objectives
            layered over the defaults, optional window/burn/hold
            overrides — that survives eviction and drain through the
            metrics checkpoint state; ``{"reset": true}`` returns the
            session to the KSS_SLO_* environment's plane, and
            ``{"enabled": false}`` disarms it."""
            metrics = svc.scheduler.metrics
            session = sid or DEFAULT_SESSION_ID

            def plane_doc():
                plane = metrics.slo_plane()
                if plane is None:
                    return {"enabled": False, "session": session}
                return plane.status()

            if method == "GET":
                return self._json(200, plane_doc())
            if method != "PUT":
                return self._error(405, "method not allowed")
            body = self._body() or {}
            if not isinstance(body, dict):
                return self._error(400, "SLO spec must be a mapping")
            if body.get("reset"):
                metrics.clear_slo_override()
                return self._json(200, plane_doc())
            try:
                plane = slo_mod.plane_from_put_spec(body, session)
            except (ValueError, TypeError) as e:
                return self._error(400, str(e))
            metrics.set_slo_plane(plane)
            if plane is None:  # {"enabled": false}: explicitly disarmed
                return self._json(200, {"enabled": False, "session": session})
            return self._json(200, plane.status())

        def _metrics(self, q: dict, svc, sid):
            """GET /api/v1/metrics (+ per-session nested form): the
            session's counter snapshot as JSON, or Prometheus text with
            a `session` label on every sample (`?format=openmetrics`
            additionally attaches histogram bucket exemplars — the
            pass-id link into the Perfetto trace — and terminates with
            `# EOF`). The LEGACY (un-prefixed) Prometheus scrape
            renders EVERY live session in one document — the one
            endpoint an external Prometheus points at
            (docs/sessions.md)."""
            fmt = q.get("format", ["json"])[0]
            doc = None
            if fmt == "json" or sid is not None:
                # the legacy prometheus scrape (sid None) re-snapshots
                # every live session inside its consistent cut below —
                # don't pay a discarded extra snapshot per scrape
                doc = svc.scheduler.metrics.snapshot()
                # serving-stack configuration alongside the counters:
                # the encoding-cache bound (KSS_ENCODING_CACHE_CAP)
                doc["encodingCacheCapacity"] = (
                    svc.scheduler.encoding_cache_capacity
                )
                doc["sessionId"] = sid or DEFAULT_SESSION_ID
                # server-wide SSE hardening counter (the satellite): how
                # many events were dropped disconnecting slow subscribers
                doc["sseDroppedEvents"] = server.sse_dropped
                # execution-ladder + drain state (docs/resilience.md):
                # which rung this session's service dispatches on, and
                # the server-wide drain view
                doc["deviceRung"] = svc.scheduler.device_rung
                doc["draining"] = server.draining
                doc["drainedSessions"] = server.sessions.drained_sessions()
                # the observatory blocks (schema v3, utils/ledger.py):
                # process-wide cold-start phase accounting (boot probe →
                # first encode → first compile → first pass, summarized
                # as timeToFirstPassSeconds) and the per-program ledger
                # summary (full detail at GET /api/v1/debug/programs)
                doc["coldStart"] = ledger_mod.COLD_START.snapshot()
                doc["programs"] = ledger_mod.LEDGER.totals()
                # the AOT bundle store (utils/bundles.py): process-wide
                # load/save/bypass counts + the deserialize wall — the
                # per-session attribution rides the phases block
                doc["bundles"] = bundles_mod.STORE.stats()
                # fleet identity (docs/fleet.md): which worker served
                # this scrape — present only inside a fleet, so the
                # single-process document shape is unchanged
                wid = metrics_mod.worker_id()
                if wid is not None:
                    doc["workerId"] = wid
            if fmt in ("prometheus", "openmetrics"):
                openmetrics = fmt == "openmetrics"

                def entry(session_id, snapshot, cache_cap):
                    return (
                        {"session": session_id},
                        snapshot,
                        {
                            "kss_encoding_cache_capacity": (
                                "Capacity of the per-service encoding "
                                "cache (KSS_ENCODING_CACHE_CAP).",
                                cache_cap,
                            )
                        },
                    )

                if sid is None:
                    # the scrape endpoint: every LIVE session, labeled,
                    # from ONE consistent cut — no per-id re-lookup to
                    # race a concurrent DELETE into a scrape-wide 404,
                    # and no restore (scrapes must not defeat idle
                    # eviction; an evicted session's counters live in
                    # its snapshot file until the next real touch)
                    cut = server.sessions.live_services()
                    entries = [
                        entry(
                            session_id,
                            service.scheduler.metrics.snapshot(),
                            service.scheduler.encoding_cache_capacity,
                        )
                        for session_id, service in cut
                    ]
                    slo_planes = [
                        (session_id, service.scheduler.metrics.slo_plane())
                        for session_id, service in cut
                    ]
                else:
                    entries = [entry(sid, doc, doc["encodingCacheCapacity"])]
                    slo_planes = [(sid, svc.scheduler.metrics.slo_plane())]
                mgr_stats = server.sessions.stats()
                global_counters = {
                    "kss_sse_dropped_events_total": (
                        "Events dropped disconnecting slow SSE "
                        "subscribers.",
                        server.sse_dropped,
                    ),
                    "kss_session_evictions_total": (
                        "Idle sessions snapshotted to disk.",
                        mgr_stats["evictions"],
                    ),
                    "kss_drained_sessions_total": (
                        "Sessions snapshotted by the graceful drain "
                        "path.",
                        mgr_stats["drainedSessions"],
                    ),
                }
                if mgr_stats["journal"]["armed"] or mgr_stats[
                    "replication"
                ].get("armed"):
                    # the durability-plane families exist only where the
                    # plane does: a standalone unarmed server keeps its
                    # honest kss_fleet_-free exposition (fleet workers
                    # always journal — the router arms them)
                    global_counters["kss_fleet_replications_total"] = (
                        "Session transport units acknowledged by ring "
                        "successors (server/replication.py).",
                        mgr_stats["replication"].get("shippedUnits", 0),
                    )
                    global_counters["kss_fleet_journal_bytes_total"] = (
                        "Write-ahead session journal bytes appended "
                        "(server/durability.py).",
                        mgr_stats["journal"]["bytes"],
                    )
                text = metrics_mod.render_prometheus_sessions(
                    entries,
                    openmetrics=openmetrics,
                    global_counters=global_counters,
                    global_gauges={
                        "kss_sessions_live": (
                            "Sessions resident in memory.",
                            mgr_stats["live"],
                        ),
                        "kss_sessions_evicted": (
                            "Sessions evicted to disk snapshots.",
                            mgr_stats["evicted"],
                        ),
                        "kss_server_draining": (
                            "1 while the graceful drain is in progress.",
                            1 if mgr_stats["draining"] else 0,
                        ),
                    },
                )
                # the per-program ledger families (kss_program_*, one
                # series per (program, fingerprint) — utils/ledger.py);
                # empty string while the ledger has recorded nothing
                text += ledger_mod.LEDGER.render_prometheus()
                # the fleet observatory families (kss_device_hbm_* /
                # kss_fleet_*, utils/fleetstats.py) from the freshest
                # samples; empty while stats are off or unsampled
                text += fleetstats.render_prometheus()
                # the SLO plane families (kss_slo_* / kss_alert_*,
                # utils/slo.py): per-(objective, session) gauges from
                # every live plane — evaluated at scrape time so alert
                # states are current — plus the process-wide alert-ring
                # counters (always present, so dashboards can pin them)
                text += slo_mod.render_prometheus_planes(slo_planes)
                # the fleet's worker label (KSS_WORKER_ID): injected
                # into every sample AFTER the whole document — sessions,
                # ledger, observatory, and SLO families alike — is
                # assembled, so one rewrite covers every renderer
                wid = metrics_mod.worker_id()
                if wid is not None:
                    text = metrics_mod.label_exposition(
                        text, {"worker": wid}
                    )
                if openmetrics:
                    # the OpenMetrics terminator — LAST, after every
                    # appended observatory family
                    text += "# EOF\n"
                body = text.encode()
                self.send_response(200)
                self._cors_headers()
                self.send_header(
                    "Content-Type",
                    "application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8"
                    if openmetrics
                    else "text/plain; version=0.0.4; charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return None
            if fmt != "json":
                return self._error(400, f"unknown metrics format {fmt!r}")
            return self._json(200, doc)

        def _events_stream(self, q: dict, svc, session_filter: "str | None"):
            """GET /api/v1/events: live telemetry over SSE
            (text/event-stream), reusing the listwatch chunked plumbing.
            Two event types (docs/observability.md):

              * ``metrics`` — a full `SchedulingMetrics` snapshot; one is
                sent immediately on connect (the stream always yields at
                least one event) and again whenever the counters change;
              * ``span`` — each flight-recorder event as it is emitted
                (requires `KSS_TRACE=1`);
              * ``fleet`` — each fleet-observatory sample (requires
                `KSS_FLEET_STATS=1`);
              * ``alert`` — each SLO alert transition (utils/slo.py;
                requires an armed plane). Without any switch the stream
                carries metrics events only.

            With `session_filter` (a nested /sessions/<id>/events route,
            or ?session= on the legacy route) only that session's spans
            flow; metrics snapshots are the addressed session's.

            Robustness (the satellite hardening): subscriber count is
            capped (KSS_SSE_MAX_SUBSCRIBERS → 503 past it), and a slow
            consumer whose bounded queue overflows is DISCONNECTED —
            with the drop counted in `sseDroppedEvents` — instead of
            silently receiving a gap-ridden interleaving. A comment
            heartbeat (``:``) flows on idle so a vanished client is
            detected and the subscription reclaimed."""
            if not server.sse_acquire():
                return self._error(
                    503,
                    f"SSE subscriber limit reached "
                    f"({server.sessions.sse_max_subscribers}, "
                    f"KSS_SSE_MAX_SUBSCRIBERS)",
                    kind="SSESubscriberLimit",
                    headers={"Retry-After": str(DEGRADED_RETRY_AFTER_S)},
                )
            rec = telemetry.active()
            fleet_rec = fleetstats.active()
            # bounded feed: a slow/stalled client must not accumulate
            # every span the process emits (the unbounded growth the
            # ring buffer exists to prevent) — past the bound the
            # consumer is provably too slow and gets disconnected
            events: "queue.Queue" = queue.Queue(maxsize=SSE_QUEUE_MAX)
            overflowed = threading.Event()

            def feed(ev: dict) -> None:
                if overflowed.is_set():
                    return  # already condemned; don't count more drops
                if (
                    session_filter is not None
                    and (ev.get("args") or {}).get("session") != session_filter
                ):
                    return  # another tenant's span: filtered, not a drop
                try:
                    events.put_nowait(("span", ev))
                except queue.Full:
                    server.sse_count_drop()
                    overflowed.set()

            def fleet_feed(sample: dict) -> None:
                # the fleet observatory's samples ride the same stream
                # as `fleet` events (docs/observability.md) — the
                # dashboard's Observability-tab sparkline source
                if overflowed.is_set():
                    return
                if (
                    session_filter is not None
                    and sample.get("session") != session_filter
                ):
                    return
                try:
                    events.put_nowait(("fleet", sample))
                except queue.Full:
                    server.sse_count_drop()
                    overflowed.set()

            def alert_feed(ev: dict) -> None:
                # SLO alert transitions ride the stream as `alert`
                # events (utils/slo.py) — the dashboard's Alerts-panel
                # source; the ring exists regardless of arming, so the
                # subscription is unconditional and simply idle when no
                # plane is armed
                if overflowed.is_set():
                    return
                if (
                    session_filter is not None
                    and ev.get("session") != session_filter
                ):
                    return
                try:
                    events.put_nowait(("alert", ev))
                except queue.Full:
                    server.sse_count_drop()
                    overflowed.set()

            alerts = slo_mod.alert_log()
            if rec is not None:
                rec.subscribe(feed)
            if fleet_rec is not None:
                fleet_rec.subscribe(fleet_feed)
            alerts.subscribe(alert_feed)
            try:
                self.send_response(200)
                self._cors_headers()
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-store")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def push(event: str, payload) -> None:
                    data = (
                        f"event: {event}\n"
                        f"data: {json.dumps(payload)}\n\n"
                    ).encode()
                    self.wfile.write(b"%x\r\n%s\r\n" % (len(data), data))
                    self.wfile.flush()

                def counters():
                    snap = svc.scheduler.metrics.snapshot()
                    snap.pop("uptimeSeconds", None)  # changes every read
                    return snap

                last = counters()
                push("metrics", last)
                idle = 0
                checked = time.monotonic()
                while not overflowed.is_set():
                    try:
                        ev = events.get(timeout=1.0)
                    except queue.Empty:
                        ev = None
                    # counters are re-checked on a wall-clock cadence in
                    # BOTH branches: continuous span traffic must not
                    # starve the metrics feed
                    now_t = time.monotonic()
                    if now_t - checked >= 1.0:
                        checked = now_t
                        now = counters()
                        if now != last:
                            last = now
                            push("metrics", now)
                            idle = 0
                    if ev is not None:
                        idle = 0
                        push(*ev)
                        continue
                    idle += 1
                    if idle >= 15:
                        idle = 0
                        # SSE comment line: a spec-legal heartbeat
                        self.wfile.write(b"3\r\n:\n\n\r\n")
                        self.wfile.flush()
                # overflow: fall through — closing the connection IS the
                # disconnect (the client reconnects and re-syncs from a
                # fresh metrics snapshot)
            except (BrokenPipeError, ConnectionResetError):
                pass
            finally:
                if rec is not None:
                    rec.unsubscribe(feed)
                if fleet_rec is not None:
                    fleet_rec.unsubscribe(fleet_feed)
                alerts.unsubscribe(alert_feed)
                server.sse_release()

        # -- watch stream ---------------------------------------------------

        def _list_watch(self, q: dict, svc):
            store = svc.store
            # validate every lastResourceVersion BEFORE the 200/chunked
            # headers go out — past that point errors can't be reported
            last_rvs: dict[str, "int | None"] = {}
            for kind, (_, param) in WATCH_KINDS.items():
                raw = q.get(param, [None])[0]
                if raw is None:
                    last_rvs[kind] = None
                    continue
                try:
                    last_rvs[kind] = int(raw)
                except ValueError:
                    return self._error(400, f"bad {param}: {raw!r}")
                # a version older than the retained log cannot be resumed
                # (deletions in the gap would be lost): 410 Gone, client
                # relists from scratch — the reference apiserver behavior
                try:
                    store.events_since(kind, last_rvs[kind])
                except StaleResourceVersion as e:
                    return self._error(410, str(e))
            events: "queue.Queue" = queue.Queue()
            store.subscribe(events.put)
            try:
                self.send_response(200)
                self._cors_headers()
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def push(ev):
                    wire, _ = WATCH_KINDS[ev.kind]
                    data = (
                        json.dumps(
                            {
                                "Kind": wire,
                                "EventType": ev.event_type,
                                "Obj": ev.obj,
                            }
                        ).encode()
                        + b"\n"  # one JSON object per line
                    )
                    self.wfile.write(b"%x\r\n%s\r\n" % (len(data), data))
                    self.wfile.flush()

                # initial replay per kind: events since the client's last
                # seen version, or the full list as ADDED (reference
                # doListAndWatch, resourcewatcher.go:94-120)
                seen: dict[str, int] = {}
                for kind, (wire, param) in WATCH_KINDS.items():
                    last = last_rvs[kind]
                    if last is not None:
                        try:
                            replay = store.events_since(kind, last)
                        except StaleResourceVersion:
                            # pruned between validation and here: nothing
                            # safe to send — drop the stream so the client
                            # reconnects and gets the 410
                            return
                    else:
                        replay = store.list_as_added(kind)
                    for ev in replay:
                        push(ev)
                        seen[kind] = max(seen.get(kind, 0), ev.resource_version)
                # live stream until the client disconnects; events that
                # raced into the queue during replay are deduped by rv.
                # An idle stream sends a blank-line heartbeat every ~15s so
                # a vanished client is detected and the handler thread +
                # subscription are reclaimed (consumers skip blank lines).
                idle = 0
                while True:
                    try:
                        ev = events.get(timeout=1.0)
                    except queue.Empty:
                        idle += 1
                        if idle >= 15:
                            idle = 0
                            self.wfile.write(b"1\r\n\n\r\n")
                            self.wfile.flush()
                        continue
                    idle = 0
                    if ev.kind not in WATCH_KINDS:
                        continue  # workload kinds are stored, not watched
                    if ev.resource_version <= seen.get(ev.kind, 0):
                        continue
                    push(ev)
            except (BrokenPipeError, ConnectionResetError):
                pass
            finally:
                store.unsubscribe(events.put)

    return Handler
