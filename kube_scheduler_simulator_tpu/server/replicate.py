"""Replicate an existing cluster into the simulator (reference:
simulator/replicateexistingcluster/replicateexistingcluster.go:40-53 —
beta feature: export from a real cluster, import here, ignoring
per-object errors and the scheduler configuration).

The reference reads a KUBECONFIG and lists resources through client-go.
This framework's equivalent source is anything that speaks the export
wire format (`ResourcesForImport` JSON): another simulator instance's
`GET /api/v1/export`, a kube-apiserver dump converted to the snapshot
shape, or a snapshot file. Import runs in IgnoreErr mode and drops the
source's schedulerConfig, exactly like the reference
(`ImportFromExistingCluster` passes WithIgnoreErr +
IgnoreSchedulerConfiguration).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from ..utils.tasks import RetryError, retry
from .service import SimulatorService


def fetch_export(
    source_url: str, timeout: float = 60.0, retry_steps: int = 3
) -> dict:
    """GET a snapshot from a simulator-compatible export endpoint.

    Connection-level failures are retried with exponential backoff
    (utils/tasks.retry — the reference wraps its cluster I/O in backoff
    retries, util/retry.go); HTTP error statuses are not retried."""
    url = source_url.rstrip("/")
    if not url.endswith("/api/v1/export"):
        url = url + "/api/v1/export"

    def get():
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())

    def transient(e: BaseException) -> bool:
        return isinstance(e, urllib.error.URLError) and not isinstance(
            e, urllib.error.HTTPError
        )

    try:
        return retry(get, steps=retry_steps, retryable=transient)
    except urllib.error.HTTPError as e:
        raise RuntimeError(f"export from {url}: HTTP {e.code}") from e
    except RetryError as e:
        raise RuntimeError(f"export from {url}: {e.last.reason}") from e.last


def replicate_existing_cluster(
    service: SimulatorService,
    *,
    source_url: "str | None" = None,
    snapshot: "dict | None" = None,
    snapshot_path: "str | None" = None,
) -> list[str]:
    """Import an existing cluster's state from exactly one source.

    Returns the list of skipped objects (IgnoreErr mode). The source's
    scheduler configuration is ignored — the simulator keeps its own
    (replicateexistingcluster.go:47-52).
    """
    sources = [s for s in (source_url, snapshot, snapshot_path) if s is not None]
    if len(sources) != 1:
        raise ValueError(
            "exactly one of source_url / snapshot / snapshot_path required"
        )
    if source_url is not None:
        snapshot = fetch_export(source_url)
    elif snapshot_path is not None:
        from .config import load_snapshot

        snapshot = load_snapshot(snapshot_path)
    snapshot = dict(snapshot or {})
    snapshot.pop("schedulerConfig", None)  # IgnoreSchedulerConfiguration
    return service.import_(snapshot, ignore_err=True)
