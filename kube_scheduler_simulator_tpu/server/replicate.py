"""Replicate an existing cluster into the simulator (reference:
simulator/replicateexistingcluster/replicateexistingcluster.go:40-53 —
beta feature: export from a real cluster, import here, ignoring
per-object errors and the scheduler configuration).

The reference reads a KUBECONFIG and lists resources through client-go.
Two equivalent sources here:

  * anything that speaks the export wire format (`ResourcesForImport`
    JSON): another simulator instance's `GET /api/v1/export`, or a
    snapshot file (`fetch_export` / `replicate_existing_cluster`);
  * a REAL kube-apiserver: `list_cluster` speaks the Kubernetes REST
    list API directly (`GET /api/v1/{pods,nodes,...}`,
    `/apis/{storage,scheduling}.k8s.io/v1/...`, optional bearer token)
    and converts the typed Lists into the snapshot shape — the client-go
    listing of replicateexistingcluster.go:40-53 without client-go.

Import always runs in IgnoreErr mode and drops the source's
schedulerConfig, exactly like the reference (`ImportFromExistingCluster`
passes WithIgnoreErr + IgnoreSchedulerConfiguration).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from ..utils.tasks import RetryError, retry
from .service import SimulatorService

# snapshot key → kube-apiserver list path (group/version fixed at the
# reference's supported versions: core/v1, storage.k8s.io/v1,
# scheduling.k8s.io/v1)
_CLUSTER_LIST_PATHS = {
    "pods": "/api/v1/pods",
    "nodes": "/api/v1/nodes",
    "pvs": "/api/v1/persistentvolumes",
    "pvcs": "/api/v1/persistentvolumeclaims",
    "storageClasses": "/apis/storage.k8s.io/v1/storageclasses",
    "priorityClasses": "/apis/scheduling.k8s.io/v1/priorityclasses",
    "namespaces": "/api/v1/namespaces",
}


def list_cluster(
    server: str,
    *,
    bearer_token: str = "",
    timeout: float = 60.0,
    retry_steps: int = 3,
) -> dict:
    """List every replicated kind from a kube-apiserver and return the
    snapshot wire shape (`ResourcesForImport` minus schedulerConfig).

    `server`: the apiserver base URL (e.g. ``https://10.0.0.1:6443`` or a
    ``kubectl proxy`` address). `bearer_token` is sent as
    ``Authorization: Bearer ...`` when non-empty, covering the
    serviceaccount/token flows a KUBECONFIG usually encodes; cert-based
    auth is out of scope (run ``kubectl proxy`` for those clusters).
    Connection-level failures retry with backoff; HTTP errors don't.
    """
    base = server.rstrip("/")
    out: dict = {}

    def get(url):
        def go():
            req = urllib.request.Request(url)
            if bearer_token:
                req.add_header("Authorization", f"Bearer {bearer_token}")
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read())

        def transient(e: BaseException) -> bool:
            return isinstance(e, urllib.error.URLError) and not isinstance(
                e, urllib.error.HTTPError
            )

        try:
            return retry(go, steps=retry_steps, retryable=transient)
        except urllib.error.HTTPError as e:
            raise RuntimeError(f"list {url}: HTTP {e.code}") from e
        except RetryError as e:
            raise RuntimeError(f"list {url}: {e.last.reason}") from e.last

    for jkey, path in _CLUSTER_LIST_PATHS.items():
        body = get(base + path)
        items = body.get("items") or []
        # apiserver Lists omit each item's kind/apiVersion; the snapshot
        # shape doesn't need them, only metadata/spec/status
        out[jkey] = items
    return out


def fetch_export(
    source_url: str, timeout: float = 60.0, retry_steps: int = 3
) -> dict:
    """GET a snapshot from a simulator-compatible export endpoint.

    Connection-level failures are retried with exponential backoff
    (utils/tasks.retry — the reference wraps its cluster I/O in backoff
    retries, util/retry.go); HTTP error statuses are not retried."""
    url = source_url.rstrip("/")
    if not url.endswith("/api/v1/export"):
        url = url + "/api/v1/export"

    def get():
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())

    def transient(e: BaseException) -> bool:
        return isinstance(e, urllib.error.URLError) and not isinstance(
            e, urllib.error.HTTPError
        )

    try:
        return retry(get, steps=retry_steps, retryable=transient)
    except urllib.error.HTTPError as e:
        raise RuntimeError(f"export from {url}: HTTP {e.code}") from e
    except RetryError as e:
        raise RuntimeError(f"export from {url}: {e.last.reason}") from e.last


def replicate_existing_cluster(
    service: SimulatorService,
    *,
    source_url: "str | None" = None,
    snapshot: "dict | None" = None,
    snapshot_path: "str | None" = None,
    kube_apiserver: "str | None" = None,
    bearer_token: str = "",
) -> list[str]:
    """Import an existing cluster's state from exactly one source.

    Sources: a simulator export endpoint (`source_url`), an in-memory
    snapshot, a snapshot file, or a real kube-apiserver
    (`kube_apiserver`, optionally with `bearer_token` — see
    `list_cluster`). Returns the list of skipped objects (IgnoreErr
    mode). The source's scheduler configuration is ignored — the
    simulator keeps its own (replicateexistingcluster.go:47-52).
    """
    sources = [
        s
        for s in (source_url, snapshot, snapshot_path, kube_apiserver)
        if s is not None
    ]
    if len(sources) != 1:
        raise ValueError(
            "exactly one of source_url / snapshot / snapshot_path / "
            "kube_apiserver required"
        )
    if source_url is not None:
        snapshot = fetch_export(source_url)
    elif snapshot_path is not None:
        from .config import load_snapshot

        snapshot = load_snapshot(snapshot_path)
    elif kube_apiserver is not None:
        snapshot = list_cluster(kube_apiserver, bearer_token=bearer_token)
    snapshot = dict(snapshot or {})
    snapshot.pop("schedulerConfig", None)  # IgnoreSchedulerConfiguration
    return service.import_(snapshot, ignore_err=True)
