"""Worker-side successor replication — the fleet durability plane's
shipping half (docs/fleet.md).

The router places sessions on a consistent-hash ring (fleet/ring.py) and
tells every worker who its peers are (`POST /api/v1/admin/replication`,
pushed at fleet start and on every membership change). Each worker then
re-derives the SAME ring locally — ownership is a pure sha256 function
of (worker set, replicas, key), so router and workers agree with no
coordination protocol — and ships each session it owns to its
``KSS_FLEET_REPLICAS`` ring successors:

  * on a ``KSS_FLEET_REPLICATE_EVERY_S`` cadence (the ticker thread):
    the session's replication base document + the journal entries past
    it, as a digest-guarded transport unit (server/durability.py),
    POSTed to each successor's adopt endpoint with ``"replica": true``
    — the receiver stores it passively under ``<dir>/replicas/``,
    never adopting until the router promotes;
  * inline per acknowledged write when ``KSS_FLEET_JOURNAL_SYNC=1``
    (the journal's ``on_append`` hook): the entry rides a
    ``journalAppend`` body to the same successors BEFORE the HTTP ack
    returns, so a crash-kill loses nothing;
  * once more at drain (`ship_once` from the drain path), closing the
    window for the graceful exit too.

A successor that is down just misses this round: shipping NEVER raises
into the serving path — durability degrades to the previous round's
replica and the counters say so (``kss_fleet_replications_total`` stops
advancing, ``shipErrors`` climbs).
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
from urllib.parse import urlsplit

from ..fleet.ring import HashRing
from ..lifecycle.checkpoint import canonical_digest
from ..utils import locking
from ..utils import telemetry


def _env_int(env, name: str, default: int, minimum: int) -> int:
    raw = env.get(name, "")
    if not raw:
        return default
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected an integer") from None
    if v < minimum:
        raise ValueError(f"{name}={raw!r}: must be >= {minimum}")
    return v


def _env_float(env, name: str, default: float, minimum: float) -> float:
    raw = env.get(name, "")
    if not raw:
        return default
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected a number") from None
    if v < minimum:
        raise ValueError(f"{name}={raw!r}: must be >= {minimum}")
    return v


def _post_json(url: str, path: str, body: dict, timeout: float) -> dict:
    """POST `body` to `url` + `path`; returns the decoded JSON response.
    Raises OSError-family on transport failure, ValueError on a non-2xx
    status — the caller counts either as one missed ship."""
    parts = urlsplit(url)
    conn = http.client.HTTPConnection(
        parts.hostname, parts.port, timeout=timeout
    )
    try:
        conn.request(
            "POST",
            path,
            body=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        data = resp.read()
        if not 200 <= resp.status < 300:
            raise ValueError(
                f"{url}{path}: HTTP {resp.status} {data[:200]!r}"
            )
        try:
            return json.loads(data) if data else {}
        except ValueError:
            return {}
    finally:
        conn.close()


@locking.guard_inferred
class ReplicationPlane:
    """One worker's view of the replication topology + the shipper.

    Dormant until `configure` delivers a peer list (a standalone server
    with no fleet around it never ships). The manager back-reference is
    how units are built (`SessionManager.replication_unit`) — the plane
    owns WHO and WHEN, the manager owns WHAT.
    """

    def __init__(self, manager, env: "dict | None" = None):
        env = os.environ if env is None else env
        self.manager = manager
        self.replicas = _env_int(env, "KSS_FLEET_REPLICAS", 1, 0)
        self.every_s = _env_float(env, "KSS_FLEET_REPLICATE_EVERY_S", 5.0, 0.05)
        # the successor push shares the adopt deadline budget: a slow
        # replica must not wedge the ticker (or, in sync mode, the ack)
        self.ship_timeout_s = _env_float(
            env, "KSS_FLEET_ADOPT_TIMEOUT_S", 60.0, 0.05
        )
        self._lock = locking.make_lock("replication.plane")
        self.self_id = env.get("KSS_WORKER_ID") or ""
        self._peers: "dict[str, str]" = {}  # wid -> base url
        self._ring: "HashRing | None" = None
        self.ships = 0  # ship rounds completed
        self.shipped_units = 0  # unit x successor deliveries acknowledged
        self.shipped_entries = 0  # sync-mode journal entries delivered
        self.ship_errors = 0  # deliveries a dead/slow successor missed
        self.skipped_units = 0  # unchanged units the digest memo elided
        # (sid, successor wid) -> (base digest, journal digest) of the
        # last unit that successor ACKNOWLEDGED: an idle session costs
        # one digest comparison per round, not a full unit POST
        self._shipped_digests: "dict[tuple[str, str], tuple]" = {}
        self._stop = threading.Event()
        self._ticker: "threading.Thread | None" = None

    # -- topology -----------------------------------------------------------

    def configure(self, doc: dict) -> dict:
        """Install the router-pushed topology: ``{"self": wid, "peers":
        [{"id", "url"}...], "replicas": R, "everyS": s}``. Idempotent;
        re-pushes on membership change just rebuild the ring. Starts
        (or leaves running) the ticker when there is anyone to ship to."""
        peers_doc = doc.get("peers") or []
        peers: "dict[str, str]" = {}
        for p in peers_doc:
            if isinstance(p, dict) and p.get("id") and p.get("url"):
                peers[str(p["id"])] = str(p["url"])
        with self._lock:
            if doc.get("self"):
                self.self_id = str(doc["self"])
            if doc.get("replicas") is not None:
                self.replicas = max(0, int(doc["replicas"]))
            if doc.get("everyS") is not None:
                self.every_s = max(0.05, float(doc["everyS"]))
            self._peers = peers
            # the SAME ring the router builds (fleet/ring.py default
            # virtual-node count): placement agreement by construction
            self._ring = HashRing(sorted(peers)) if peers else None
            # membership changed: a successor may have restarted with
            # an empty disk, so the digest memo can no longer prove a
            # replica is current — re-ship everything next round
            self._shipped_digests.clear()
            armed = self._armed_locked()
            if armed and self._ticker is None:
                self._stop.clear()
                self._ticker = threading.Thread(
                    target=self._tick_loop,
                    name="kss-replication-ticker",
                    daemon=True,
                )
                self._ticker.start()
        return self.stats()

    def _armed_locked(self) -> bool:
        return bool(
            self.replicas > 0
            and self._ring is not None
            and any(wid != self.self_id for wid in self._peers)
        )

    def targets(self, sid: str) -> "list[tuple[str, str]]":
        """The (worker id, url) successors `sid` replicates to: the
        ring's next `replicas` DISTINCT owners clockwise of the session,
        excluding this worker."""
        with self._lock:
            if not self._armed_locked():
                return []
            owners = self._ring.owners(sid, self.replicas + 1)
            return [
                (wid, self._peers[wid])
                for wid in owners
                if wid != self.self_id and wid in self._peers
            ][: self.replicas]

    # -- shipping -----------------------------------------------------------

    def ship_once(self) -> dict:
        """One replication round: every session this manager holds,
        shipped as a digest-guarded unit to each of its successors.
        Failures are counted, never raised — a down replica degrades
        durability, not serving."""
        shipped = 0
        errors = 0
        for sid in self.manager.session_ids():
            per_sid = self.ship_session(sid)
            shipped += per_sid[0]
            errors += per_sid[1]
        with self._lock:
            self.ships += 1
        return {"shipped": shipped, "errors": errors}

    def ship_session(self, sid: str) -> "tuple[int, int]":
        """Ship one session to its successors; returns (ok, errors)."""
        targets = self.targets(sid)
        if not targets:
            return (0, 0)
        unit = self.manager.replication_unit(sid)
        if unit is None:
            return (0, 0)
        body = {"replica": True, "checkpoints": [unit]}
        digest = (unit.get("sha256"), unit.get("journalSha256"))
        ok = errors = skipped = 0
        for wid, url in targets:
            with self._lock:
                if self._shipped_digests.get((sid, wid)) == digest:
                    skipped += 1
                    continue
            try:
                _post_json(
                    url, "/api/v1/admin/adopt", body, self.ship_timeout_s
                )
                ok += 1
                # stamped with the causing request's trace id (when the
                # shipping thread carries one) by the telemetry plane
                telemetry.instant(
                    "fleet.ship", session=sid, target=wid, kind="unit"
                )
                with self._lock:
                    self._shipped_digests[(sid, wid)] = digest
            except (OSError, ValueError):
                errors += 1
        with self._lock:
            self.shipped_units += ok
            self.ship_errors += errors
            self.skipped_units += skipped
        return (ok, errors)

    def ship_entry(self, sid: str, entry: dict) -> int:
        """The sync-mode inline ship (journal ``on_append`` hook): one
        acknowledged mutation to every successor BEFORE the ack returns.
        Returns deliveries that succeeded; failures degrade to the next
        full-unit round."""
        targets = self.targets(sid)
        if not targets:
            return 0
        body = {
            "journalAppend": {
                "id": sid,
                "entries": [entry],
                "sha256": canonical_digest([entry]),
            }
        }
        ok = errors = 0
        for wid, url in targets:
            try:
                _post_json(
                    url, "/api/v1/admin/adopt", body, self.ship_timeout_s
                )
                ok += 1
                # sync-mode ship runs ON the acking request thread, so
                # the instant carries the mutation's own trace id
                telemetry.instant(
                    "fleet.ship", session=sid, target=wid, kind="entry"
                )
            except (OSError, ValueError):
                errors += 1
        with self._lock:
            self.shipped_entries += ok
            self.ship_errors += errors
        return ok

    def _tick_loop(self) -> None:
        while True:
            with self._lock:
                stop = self._stop
                every = self.every_s
            if stop.wait(every):
                return
            with self._lock:
                armed = self._armed_locked()
            if not armed:
                continue
            try:
                self.ship_once()
            except Exception:  # noqa: BLE001 — the ticker must survive
                with self._lock:
                    self.ship_errors += 1

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "armed": self._armed_locked(),
                "self": self.self_id,
                "peers": len(self._peers),
                "replicas": self.replicas,
                "everySeconds": self.every_s,
                "ships": self.ships,
                "shippedUnits": self.shipped_units,
                "shippedEntries": self.shipped_entries,
                "shipErrors": self.ship_errors,
                "skippedUnits": self.skipped_units,
            }

    def stop(self) -> None:
        with self._lock:
            self._stop.set()
            t, self._ticker = self._ticker, None
        if t is not None:
            t.join(timeout=2)
