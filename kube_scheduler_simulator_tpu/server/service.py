"""Simulator services: scheduler lifecycle + scheduling passes + the
export/import/reset composites over one `ResourceStore`.

This is the analogue of the reference's DI-wired service layer
(simulator/server/di/di.go:44-78) collapsed to plain constructors:

  * `SchedulerService` owns the scheduler lifecycle — current
    KubeSchedulerConfiguration, restart-with-new-config with rollback on a
    config the engine cannot run (reference:
    simulator/scheduler/scheduler.go:70-91), and the batched scheduling
    pass itself.
  * Scheduling results are written straight back onto the pod objects in
    the store — `spec.nodeName` plus the 13 result annotations — replacing
    the reference's informer-hooked store reflector
    (simulator/scheduler/storereflector/storereflector.go:54-119): the
    batched engine's outputs ARE the record, so there is no informer race
    and no conflict-retry loop.
  * Preemption victims are deleted from the store, mirroring the upstream
    scheduler's API-delete of victims.
  * `SimulatorService` composes store + scheduler with export / import /
    reset (reference: simulator/export/export.go:187-263,
    simulator/reset/reset.go:57-84).

Divergence (documented): the reference scheduler is a long-running loop
that drains a watch-fed queue one pod at a time; here a scheduling pass is
an explicit, synchronous batch (`schedule()`), optionally auto-triggered
after imports/CRUD by the HTTP layer. One pass schedules every pending pod
in PrioritySort order with identical placement semantics.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import ExitStack

import jax

from ..engine import BatchedScheduler
from ..engine.delta import DeltaEncoder
from ..engine.encode import EncodingCache, policy_from_env
from ..engine.engine import unsupported_plugins
from ..models.snapshot import export_snapshot, import_snapshot
from ..models.store import ResourceStore
from ..sched.config import SchedulerConfiguration
from ..sched.extender import ExtenderService
from ..sched.results import PodSchedulingResult
from ..utils import broker as broker_mod
from ..utils import devices as devices_mod
from ..utils import faultinject, fleetstats, locking
from ..utils import ledger as ledger_mod
from ..utils import metrics as metrics_mod
from ..utils import telemetry
from ..utils.broker import (
    CompileBroker,
    CompileUnavailable,
    adjacent_bucket_targets,
    eager_execution,
)


class InvalidSchedulerConfiguration(ValueError):
    pass


class EngineDegraded(RuntimeError):
    """The degradation ladder is fully exhausted: compilation kept
    failing AND the un-jitted eager fallback failed too. The HTTP layer
    maps this to 503 + Retry-After (docs/resilience.md)."""


# The gang engine's evaluation-chunk size on the SERVING path. Placements
# are chunk-invariant (chunking only batches the per-round evaluation);
# what the chunk sets is the granularity of compact mode's skip-settled
# cond — a live round evaluates ceil(pending / chunk) chunks of
# [chunk x N] kernels. Churn-heavy serving passes have 1-2 pending pods,
# so the warm-pass floor is ONE chunk's evaluation: 64 measures ~3.5x
# faster than the 256 default at the lifecycle-probe shape (520 bound +
# 2 pending x 64 nodes: 141 ms -> 40 ms) while bulk passes keep the same
# total work. Must accompany every GangScheduler build AND every
# effective_window computation here, or engine-cache keys drift.
GANG_CHUNK = 64


def gang_chunk() -> int:
    """The serving-path gang evaluation chunk: ``KSS_GANG_CHUNK`` when
    set (>= 1), else the measured `GANG_CHUNK` default. Placements are
    chunk-invariant, so this is a pure performance knob — compact
    mode's skip-settled granularity on both the fused fixpoint and the
    record path's replay evaluation. Lenient coercion (the broker's
    ladder-knob rule: a malformed value must not take serving down);
    read per pass so the knob is honored without a restart — the chunk
    is part of the engine signature, so a changed value simply keys a
    new engine."""
    return broker_mod._coerce_env_number(
        os.environ.get("KSS_GANG_CHUNK", ""), GANG_CHUNK, int, 1
    )


class SchedulerServiceDisabled(RuntimeError):
    """An external scheduler is enabled, so the internal scheduler
    service refuses config/scheduling calls (reference
    scheduler.go:55 ErrServiceDisabled; the disabled service is built
    when ExternalSchedulerEnabled, scheduler.go:58-61)."""

    def __init__(self):
        super().__init__(
            "an external scheduler is enabled: scheduler service is disabled"
        )


class SchedulingPassHandle:
    """An in-flight scheduling pass: dispatched, not yet resolved.

    `begin_pass`/`begin_gang_pass` return one with the pass lock HELD —
    device execution (and the occasional compile, normally served warm
    by the broker) proceeds while the caller does other host-side work.
    `resolve()` performs the deferred tail — result decode (one batched
    device transfer), store write-backs, pass metrics — releases the
    lock, and returns the number of pods scheduled. Callers MUST resolve
    (or `abandon`) exactly once before starting another pass; the
    lifecycle engine's async pipeline is the canonical driver."""

    def __init__(self, service, mode: str, finish, encode_info, pass_id=None):
        self._service = service
        self._finish = finish
        self._done = False
        self.mode = mode
        # the encode path that served the dispatch (delta/full/cached/…)
        self.encode_info = encode_info
        # causal id of this pass in the service's monotonic sequence —
        # every telemetry span of the pass (including the broker's
        # speculative builds it arms) carries it (utils/telemetry.py)
        self.pass_id = pass_id
        self.scheduled: "int | None" = None

    def resolve(self) -> int:
        if self._done:
            return self.scheduled or 0
        try:
            self.scheduled = self._finish()
        finally:
            self._done = True
            self._service._unlease_engine()
            self._service._schedule_lock.release()
        return self.scheduled

    def abandon(self) -> None:
        """Release the pass lock WITHOUT the deferred write-backs (error
        paths only — the store is left without this pass's results)."""
        if not self._done:
            self._done = True
            self._service._unlease_engine()
            self._service._schedule_lock.release()


@locking.guard_inferred
class SchedulerService:
    """Scheduler lifecycle + batched scheduling passes."""

    def __init__(
        self,
        store: ResourceStore,
        initial_config: "SchedulerConfiguration | None" = None,
        metrics: "metrics_mod.SchedulingMetrics | None" = None,
        disabled: bool = False,
        broker: "CompileBroker | None" = None,
        session_id: "str | None" = None,
        fault_plane=None,
    ):
        self.store = store
        # multi-tenant session plane (docs/sessions.md): the session id
        # labels this service's telemetry spans (SSE filtering, the
        # Prometheus `session` label) and namespaces its cooldowns on a
        # SHARED broker; the optional per-session fault plane
        # (utils/faultinject.FaultPlane) rules this service's passes
        # only — the bulkhead that confines a tenant's injected storm
        self.session_id = session_id
        self.fault_plane = fault_plane
        # the engine lease held across the current pass's dispatch→finish
        # window (broker.lease — cross-session engine serialization);
        # at most one, since _schedule_lock serializes passes
        self._engine_lease: "threading.RLock | None" = None
        # external-scheduler mode: the service exists (the HTTP layer
        # still routes to it) but refuses config and scheduling calls
        self.disabled = disabled
        # per-service pass counters: embedded/test use may run several
        # services in one process, and a process-wide registry would
        # interleave their numbers (ADVICE r3). Each service defaults to
        # its own instance; the serving shell reads it through
        # GET /api/v1/metrics. Pass `metrics=metrics_mod.GLOBAL` to opt
        # into the shared process registry.
        self.metrics = (
            metrics if metrics is not None else metrics_mod.SchedulingMetrics()
        )
        # the SLO plane labels this registry's alerts by tenant
        # (utils/slo.py); a shared/pre-labeled registry keeps its label
        if self.metrics.session_id is None and session_id is not None:
            self.metrics.session_id = session_id
        self._initial = initial_config or SchedulerConfiguration.default()
        self._config = self._initial
        self._lock = locking.make_lock("service.state")
        # whole-pass serialization (held across dispatch→resolve for
        # async passes — see SchedulingPassHandle)
        self._schedule_lock = locking.make_lock("service.schedule")
        # ALL compiled engines (sequential / gang / extender, keyed by
        # kind + compile signature) live in the CompileBroker: it dedupes
        # concurrent builds, counts hits/misses/stall seconds into this
        # service's metrics, and hosts the predictive background compiles
        # `_maybe_speculate` arms (utils/broker.py)
        self.broker = broker if broker is not None else CompileBroker(
            metrics=self.metrics
        )
        # speculation arming memory: one background compile per
        # (bucket, target) pair — cleared when the live bucket moves
        self._spec_bucket: "int | None" = None
        self._spec_armed: set = set()
        # the incremental encoding stack (docs/performance.md):
        #   * EncodingCache — bounded LRU keyed (latest rv, config
        #     identity): back-to-back passes over an unchanged store
        #     reuse the encoding verbatim, across recent configs;
        #     capacity from KSS_ENCODING_CACHE_CAP (default 8, surfaced
        #     in /api/v1/metrics as encodingCacheCapacity);
        #   * DeltaEncoder — on a cache miss, replays the store's event
        #     log into the retained encoding with device scatter
        #     updates, falling back to a full re-encode when it can't
        #     prove exactness. The lifecycle event loop leans on this
        #     for its O(Δ) steady state.
        self.encoding_cache_capacity = self._encoding_cache_cap_from_env()
        self._enc_cache = EncodingCache(capacity=self.encoding_cache_capacity)
        self._delta = DeltaEncoder()
        # the last _encode_current outcome ({"mode": ..., ...}) — read
        # by the lifecycle engine to stamp per-pass encode modes
        self.last_encode_info: "dict | None" = None
        # monotonic pass sequence (telemetry causality): advanced under
        # the schedule lock, so ids order exactly like passes do
        self._pass_seq = 0
        # -- execution ladder state (docs/resilience.md) -----------------
        # the rung this service currently dispatches on:
        #   "device" — the healthy default;
        #   "shrunk" — a device was lost, engines rebuilt over the
        #              surviving mesh (self.mesh) under a bumped epoch;
        #   "cpu"    — mid-process CPU failover: every pass re-encodes
        #              and runs on the CPU backend (the generalization
        #              of the boot-time re-exec in utils/axonenv.py).
        # Rungs latch: once escalated, later passes run there directly
        # instead of re-walking the ladder per pass.
        self._device_rung = "device"
        self._dispatch_device = None  # default-device override per rung
        self._lost_devices: set = set()
        # joins broker keys once non-zero, so rebuilt engines never
        # collide with a warm engine compiled for a dead device
        self._device_epoch = 0
        # the cross-tenant micro-batch dispatch plane
        # (server/batchplane.py), shared across every session of one
        # SessionManager and assigned by it at session wiring time;
        # None (the default) = solo dispatch, the historical path
        self.batch_plane = None
        # per-pass batching bookkeeping (both only touched inside the
        # schedule-lock window): whether THIS pass already counted its
        # soloFallbacks tick (supervised-dispatch retries re-enter the
        # dispatch closure — one pass must count once), and the reusable
        # decode-engine for batched passes, keyed by broker sig (the
        # broker's retarget pattern: construction is paid on signature
        # change, not per pass)
        self._batch_fallback_counted = False
        self._batch_decode_cache: "tuple | None" = None
        self._batch_gang_decode_cache: "tuple | None" = None
        self.extender_service = ExtenderService(self._config.extenders)

    def _next_pass_id(self) -> int:
        """The next causal pass id — call only with `_schedule_lock`
        held (passes are serialized, so the increment is exact; the
        state lock makes the counter safe for out-of-pass readers like
        `next_pass_id_hint` — guarded-state contract KSS6xx)."""
        with self._lock:
            self._pass_seq += 1
            return self._pass_seq

    def next_pass_id_hint(self) -> int:
        """The pass id the NEXT pass will carry — exact only while the
        caller is the sole driver of this service (the lifecycle engine
        is: it owns its service and runs single-threaded). Used to stamp
        host-side work that FEEDS the next pass (event application under
        the async pipeline) with that pass's causal id."""
        with self._lock:
            return self._pass_seq + 1

    def pass_seq(self) -> int:
        """The completed-pass counter, read under the state lock — the
        session checkpoint writer's accessor (the counter is
        lock-claimed state, KSS6xx: the KSS_RACE_CHECK witness caught
        the bare cross-class read on the live snapshot path)."""
        with self._lock:
            return self._pass_seq

    def restore_pass_seq(self, n: int) -> None:
        """Restore the pass counter from a session checkpoint (the
        restored service has no pass in flight; the state lock makes
        the publication safe for concurrent hint readers)."""
        with self._lock:
            self._pass_seq = int(n)

    def encode_info(self) -> "dict | None":
        """The last pass's encode-path outcome, read under the state
        lock (the lifecycle engine stamps per-pass encodeMode from it
        AFTER the pass released the schedule lock — a bare read there is
        exactly what the KSS_RACE_CHECK witness flags)."""
        with self._lock:
            return self.last_encode_info

    def current_extender_service(self) -> ExtenderService:
        """The live extender service, read under the state lock
        (restart() swaps it there) — the HTTP extender proxy's
        accessor."""
        with self._lock:
            return self.extender_service

    def _session_scope(self) -> ExitStack:
        """The per-pass bulkhead contexts (docs/sessions.md): spans
        emitted inside carry this service's session id, and the
        session's private fault plane (when it has one) shadows the
        process plane on this thread for the duration. Empty for
        sessionless services — the historical behavior."""
        stack = ExitStack()
        if self.session_id is not None:
            stack.enter_context(telemetry.session_context(self.session_id))
        if self.fault_plane is not None:
            stack.enter_context(faultinject.scoped(self.fault_plane))
        return stack

    def _lease_engine(self, sig: tuple) -> None:
        """Hold `sig`'s engine lease for the rest of this pass: warm
        engines in a SHARED broker are stateful (retarget mutates them),
        so two bucket-compatible sessions may share the executable but
        never a concurrent mutation of it. Released by the pass finish
        (or any error path) via `_unlease_engine`."""
        lease = self.broker.lease(sig)
        lease.acquire()
        self._engine_lease = lease

    def _unlease_engine(self) -> None:
        """Release the held engine lease, if any (idempotent — finish
        paths and outer error handlers may both call it)."""
        lease, self._engine_lease = self._engine_lease, None
        if lease is not None:
            lease.release()

    @staticmethod
    def _encoding_cache_cap_from_env() -> int:
        """EncodingCache capacity: KSS_ENCODING_CACHE_CAP when it parses
        to a positive integer, else the default 8 (a bad value must not
        take the serving stack down — the cache is an optimization)."""
        raw = os.environ.get("KSS_ENCODING_CACHE_CAP", "")
        try:
            cap = int(raw) if raw else 8
        except ValueError:
            return 8
        return cap if cap >= 1 else 8

    # -- configuration lifecycle -------------------------------------------

    @property
    def config(self) -> SchedulerConfiguration:
        with self._lock:
            return self._config

    def get_config(self) -> dict:
        if self.disabled:
            raise SchedulerServiceDisabled()
        with self._lock:
            config = self._config
        return config.to_dict()

    def restart(self, new_config: "dict | SchedulerConfiguration") -> None:
        """Swap in a new configuration; on an unusable one, keep the old
        (reference RestartScheduler rolls back to oldSchedulerCfg,
        scheduler.go:70-87)."""
        if self.disabled:
            raise SchedulerServiceDisabled()
        if not isinstance(new_config, SchedulerConfiguration):
            new_config = SchedulerConfiguration.from_dict(new_config)
        missing = unsupported_plugins(new_config)
        if missing:
            raise InvalidSchedulerConfiguration(
                f"no kernel for enabled plugins: {missing}"
            )
        with self._lock:
            self._config = new_config
            self.extender_service = ExtenderService(new_config.extenders)

    def reset(self) -> None:
        """Restore the boot-time configuration (reference
        ResetScheduler, scheduler.go:89-91 — which goes through
        RestartScheduler and hence errors when disabled)."""
        if self.disabled:
            raise SchedulerServiceDisabled()
        with self._lock:
            self._config = self._initial
            self.extender_service = ExtenderService(self._initial.extenders)

    # -- scheduling ---------------------------------------------------------

    def schedule(self) -> list[PodSchedulingResult]:
        """One batched sequential scheduling pass over the store's state.

        Encodes the cluster, runs the engine, writes `spec.nodeName` and
        the 13 result annotations back onto pod objects, and deletes
        preemption victims. Returns the per-pod records. Passes are
        serialized — concurrent HTTP triggers queue up rather than
        interleaving their write-backs. For bulk throughput without
        per-plugin records, see `schedule_gang`.
        """
        if self.disabled:
            raise SchedulerServiceDisabled()
        with self._schedule_lock, self._session_scope():
            try:
                # one config read per pass: encode, branch, and label must
                # all see the same configuration even if restart() lands
                # mid-pass
                with self._lock:
                    config = self._config
                mode = "extender" if config.extenders else "sequential"
                pass_id = self._next_pass_id()
                with telemetry.pass_context(pass_id), telemetry.span(
                    f"pass.{mode}", pass_id=pass_id
                ):
                    with self.metrics.time_pass(mode) as ctx:
                        results = self._schedule_locked(config)
                        # a preempting pod yields two records (Nominated +
                        # retry): count distinct pods so decisions/sec isn't
                        # inflated
                        ctx.done(
                            pods=len(
                                {(r.pod_namespace, r.pod_name) for r in results}
                            ),
                            scheduled=sum(
                                1 for r in results if r.status == "Scheduled"
                            ),
                        )
                return results
            finally:
                # error paths between dispatch and finish (eager-ladder
                # exhaustion, device faults) must not strand the lease
                self._unlease_engine()

    def schedule_gang(
        self, record: bool = True, window: "int | None" = None
    ) -> "tuple[dict, int, list[PodSchedulingResult] | None]":
        """Gang pass with pass serialization; returns
        ({(ns, name): node | ""}, rounds, results).

        `record=True` (default — the annotations ARE the product,
        reference resultstore/store.go:129-190) runs the record path:
        the 13 result annotations are written back onto every queued
        pod exactly like the sequential pass, and the per-pod records
        are returned. `record=False` is the bulk-throughput opt-out
        (results is None, only nodeName is written back).

        `window` passes GangScheduler's eval_window through (the
        at-scale round-cost lever — docs/gang-scheduler.md); placements
        are a valid greedy order of the windowed contract."""
        if self.disabled:
            raise SchedulerServiceDisabled()
        if window is not None and int(window) < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        with self._schedule_lock, self._session_scope():
            try:
                return self._schedule_gang_timed(record, window)
            finally:
                self._unlease_engine()

    def _schedule_gang_timed(self, record: bool, window: "int | None" = None):
        with self._lock:
            config = self._config
        if config.extenders:
            raise ValueError(
                "gang mode does not support extenders; use sequential mode"
            )
        pass_id = self._next_pass_id()
        with telemetry.pass_context(pass_id), telemetry.span(
            "pass.gang", pass_id=pass_id
        ):
            with self.metrics.time_pass("gang") as ctx:
                placements, rounds, results = self._schedule_gang_locked(
                    config, record, window
                )
                ctx.done(
                    pods=len(placements),
                    scheduled=sum(1 for v in placements.values() if v),
                    rounds=rounds,
                )
        return placements, rounds, results

    def _schedule_gang_locked(self, config, record: bool, window=None):
        """Gang pass: encode, run to fixpoint, write results back."""
        t0 = time.perf_counter()
        disp = self._gang_dispatch(config, record, window)
        if disp is not None:
            telemetry.complete(
                "device.execute",
                t0,
                time.perf_counter(),
                tid=telemetry.DEVICE_TID,
                mode="gang",
            )
        if disp is None:
            return {}, 0, ([] if record else None)
        return self._gang_finish(disp, record)

    def _fire_device_dispatch(self) -> None:
        """The fault plane's device-dispatch site (``device_error`` /
        ``device_lost`` / ``dispatch_hang``, utils/faultinject.py):
        fired once per pass dispatch, upstream of engine acquisition,
        under the KSS_DISPATCH_DEADLINE_S watchdog (an injected hang
        must trip the deadline exactly like a wedged real dispatch). An
        injected device fault escalates through the EXECUTION ladder
        (`_supervised_dispatch`) — retried, mesh-shrunk, then failed
        over to CPU; on the CPU rung the sites no longer fire (they
        model the accelerator, and that rung no longer touches it)."""
        if self._device_rung == "cpu":
            return
        plane = faultinject.active()
        if plane is None:
            return

        def probe():
            plane.delay("dispatch_hang")
            plane.maybe_raise("device_error")
            plane.maybe_raise("device_lost")

        devices_mod.run_with_deadline(probe, devices_mod.dispatch_deadline_s())

    # -- the execution ladder (docs/resilience.md) --------------------------

    @property
    def device_rung(self) -> str:
        """The execution ladder rung this service dispatches on
        (``device`` / ``shrunk`` / ``cpu``) — surfaced by
        GET /api/v1/metrics as ``deviceRung``. Read under the state
        lock: rung transitions publish under it (`_try_shrink`,
        `_engage_cpu_failover`), and the metrics scrape must not block
        on a whole pass to observe them."""
        with self._lock:
            return self._device_rung

    def _epoch_sig(self, sig: tuple) -> tuple:
        """Append the device epoch to a broker key once any escalation
        happened: a rebuilt engine must never collide with a warm engine
        compiled for a dead (or abandoned) device. Epoch 0 keys stay
        byte-identical to the historical shape, so bucket-compatible
        sessions keep sharing executables."""
        if self._device_epoch:
            return sig + (("devepoch", self._device_epoch),)
        return sig

    def _run_rung(self, once):
        """Run one dispatch attempt on the current rung: under the
        rung's default-device override (a shrink survivor or a CPU
        device) when one is set, inline otherwise."""
        if self._dispatch_device is not None:
            with jax.default_device(self._dispatch_device):
                return once()
        return once()

    def _invalidate_encodings(self) -> None:
        """Escalation invalidates every retained encoding: the cached /
        delta-retained arrays live on the device that just failed, so
        the next `_encode_current` must re-encode from the store under
        the new rung's placement."""
        self._enc_cache = EncodingCache(capacity=self.encoding_cache_capacity)
        self._delta = DeltaEncoder()
        # the batched-pass decode engines retain their encodings too —
        # on the failed device; drop them (batch eligibility already
        # excludes escalated rungs, this just releases the dead buffers)
        self._batch_decode_cache = None
        self._batch_gang_decode_cache = None

    def _try_shrink(self) -> bool:
        """The ladder's mesh-shrink rung: mark the dispatch device lost,
        rebuild the (replicas, nodes) mesh over the survivors (the
        replicas axis absorbs the loss — parallel/mesh.surviving_mesh),
        bump the engine epoch so the broker rebuilds on the new
        topology, and re-encode. False when nothing survives (single
        device, or backend enumeration itself failing) — the caller's
        cue to fall straight to the CPU rung."""
        try:
            all_devices = jax.devices()
        except Exception:  # noqa: BLE001 — a dead backend can't enumerate
            return False
        if len(all_devices) <= 1:
            return False
        # the faulted device is the one dispatches were landing on: the
        # rung's override when set, else the process default (devices[0])
        faulted = (
            self._dispatch_device
            if self._dispatch_device is not None
            else all_devices[0]
        )
        self._lost_devices.add(faulted)
        survivors = [d for d in all_devices if d not in self._lost_devices]
        if not survivors:
            return False
        from ..parallel.mesh import surviving_mesh

        try:
            # validates that a (replicas, nodes) topology exists over
            # the survivors (odd counts fall to node_shards=1) — the
            # rung's actual effect is the dispatch-device pin + epoch
            mesh = surviving_mesh(self._lost_devices, devices=all_devices)
        except ValueError:
            return False
        # rung state publishes under the state lock so out-of-pass
        # readers (the deviceRung scrape) see it without the pass lock
        with self._lock:
            self._dispatch_device = survivors[0]
            self._device_rung = "shrunk"
            self._device_epoch += 1
        self._invalidate_encodings()
        self.metrics.record_resilience(mesh_shrinks=1)
        telemetry.instant(
            "dispatch.mesh_shrink",
            survivors=len(survivors),
            replicas=mesh.shape["replicas"],
        )
        return True

    def _engage_cpu_failover(self, err: "Exception | None") -> None:
        """The ladder's last rung — the mid-process generalization of
        the boot-time CPU re-exec (utils/axonenv.py): re-encode on the
        CPU backend and run the SAME pass there. Same placements, same
        trace bytes; only latency degrades. Latches for the rest of the
        process (like the re-exec'd server). With no usable CPU backend
        the ladder is truly exhausted: EngineDegraded, the 503 path."""
        cpus = devices_mod.cpu_devices()
        if not cpus:
            raise EngineDegraded(
                f"device ladder exhausted ({err}) and no CPU backend is "
                f"available for the failover rung"
            ) from err
        with self._lock:
            self._device_rung = "cpu"
            self._dispatch_device = cpus[0]
            self._device_epoch += 1
        self._invalidate_encodings()
        self.metrics.record_resilience(device_failovers=1)
        telemetry.instant("dispatch.cpu_failover", reason=str(err))

    def _supervised_dispatch(self, once):
        """Walk the execution ladder around one dispatch closure
        (docs/resilience.md). `once` is the FULL dispatch — encode,
        engine acquisition through the broker, device run — so every
        escalation re-encodes and rebuilds on the new rung's devices:

          1. up to 1 + KSS_DISPATCH_RETRIES attempts on the current
             rung (each re-run counted as ``dispatchRetries``);
          2. one mesh shrink (drop the faulted device, rebuild over the
             survivors under a bumped epoch) and one attempt there;
          3. CPU failover: the same pass, re-encoded and re-run on the
             CPU backend (``deviceFailovers``).

        Only device faults (`utils/devices.is_device_fault`) escalate;
        every other exception propagates untouched. Rungs latch — a
        failed-over service dispatches straight on CPU next pass."""
        if self._device_rung == "cpu":
            return self._run_rung(once)
        last: "Exception | None" = None
        for attempt in range(1 + devices_mod.dispatch_retries()):
            if attempt:
                self.metrics.record_resilience(dispatch_retries=1)
                telemetry.instant("dispatch.retry", attempt=attempt + 1)
            try:
                return self._run_rung(once)
            except Exception as e:  # noqa: BLE001 — classified below
                if not devices_mod.is_device_fault(e):
                    raise
                last = e
                self._unlease_engine()
        if self._device_rung == "device" and self._try_shrink():
            try:
                return self._run_rung(once)
            except Exception as e:  # noqa: BLE001 — classified below
                if not devices_mod.is_device_fault(e):
                    raise
                last = e
                self._unlease_engine()
        self._engage_cpu_failover(last)
        return self._run_rung(once)

    def _fleet_sample(self, enc, state, mode: str) -> None:
        """One fleet-observatory sample over this pass's encoded
        tensors + final engine state (utils/fleetstats.py): per-device
        HBM, the live-buffer census, and the jitted cluster-quality
        reductions. Read-only over the pass's arrays — placements are
        byte-identical with stats on or off (sampling-invariance,
        test-pinned) — and never-raise: observability must not fail a
        pass. No-op unless KSS_FLEET_STATS armed a recorder."""
        rec = fleetstats.active()
        if rec is None:
            return
        try:
            rec.sample_pass(self, enc, state, mode)
        except Exception:  # noqa: BLE001 — a failed sample never fails a pass
            pass

    def _eager_fallback(self, build, err: Exception):
        """The degradation ladder's last rung (docs/resilience.md): run
        the SAME engine pass un-jitted. Inside `eager_execution`,
        `broker.jit` is a pass-through, so `build()` constructs an engine
        whose programs execute eagerly — no XLA compile to fail or wedge.
        The engine is NOT stored in the broker's warm map (it is not
        compiled); the pass completes slowly instead of not at all."""
        t0 = time.perf_counter()
        try:
            with telemetry.span("pass.eager_fallback", reason=str(err)):
                with eager_execution():
                    engine = build()
        except Exception as e:
            self.metrics.record_resilience(degraded_passes=1)
            raise EngineDegraded(
                f"compile ladder exhausted ({err}) and eager fallback "
                f"failed: {type(e).__name__}: {e}"
            ) from e
        self.metrics.record_resilience(degraded_passes=1, eager_fallbacks=1)
        self.metrics.record_phase_seconds(execute=time.perf_counter() - t0)
        # downstream finish steps (the gang record decode) lazily create
        # MORE jits on this engine — they must stay on the eager rung too
        engine._kss_eager_fallback = True
        return engine

    def _count_solo_fallback(self) -> None:
        """One ``soloFallbacks`` tick per PASS: the supervised dispatch
        re-enters the dispatch closure on device-fault retries and
        ladder rungs, and a retried pass must not inflate the counter
        (per-pass flag, reset by the dispatch wrappers under the
        schedule lock)."""
        if self.batch_plane is None or self._batch_fallback_counted:
            return
        self._batch_fallback_counted = True
        self.metrics.record_batching(solo_fallbacks=1)

    def _gang_dispatch(self, config, record: bool, window=None):
        """One gang dispatch under the execution ladder: the full
        encode + engine-acquire + run closure walks
        `_supervised_dispatch`, so a device fault anywhere inside is
        retried, mesh-shrunk, or failed over to CPU — with the SAME
        pass re-encoded and re-run, never a changed answer."""
        self._batch_fallback_counted = False
        return self._supervised_dispatch(
            lambda: self._gang_dispatch_once(config, record, window)
        )

    def _gang_dispatch_once(self, config, record: bool, window=None):
        """Encode + execute one gang pass, engine served by the broker;
        returns an opaque tuple for `_gang_finish`, or None when nothing
        is schedulable. Everything downstream of this (decode,
        write-backs) is deferrable — the async pipeline's split point."""
        from ..engine.gang import GangScheduler

        enc = self._encode_current(config)
        if enc is None:
            return None
        self._fire_device_dispatch()
        chunk = gang_chunk()
        # the window joins the broker key as the CANONICAL chunk-rounded
        # value program identity actually depends on (raw windows that
        # round to the same WP share one compilation)
        sig = self._epoch_sig((
            "gang",
            GangScheduler.compile_signature(enc),
            GangScheduler.effective_window(enc, window, chunk),
        ))
        if not record:
            # the fused fixpoint made the whole pass one broker-keyed
            # program, so gang passes enroll in the batch plane exactly
            # like sequential ones (batch.gang.run)
            disp = self._maybe_batched_gang_dispatch(sig, enc, chunk, window)
            if disp is not None:
                return disp
        else:
            # record passes stay solo: the byte-parity trace replay is
            # per-session host work by design (docs/performance.md)
            self._count_solo_fallback()
        # cross-session serialization of the (possibly shared) engine:
        # held until _gang_finish (docs/sessions.md)
        self._lease_engine(sig)
        t0 = time.perf_counter()
        holder: dict = {}

        def build():
            g = GangScheduler(
                enc, strict=True, chunk=chunk, eval_window=window
            )
            # jit is lazy: the first drive IS the XLA compile, so the
            # broker's miss wall time is the true request-thread stall
            if record:
                g.run_recorded()
            else:
                g.run()
            holder["ran"] = True
            return g

        broker_info: dict = {}
        try:
            gang = self.broker.get_resilient(
                sig, build, info=broker_info,
                metrics=self.metrics, scope=self.session_id,
            )
        except CompileUnavailable as e:
            # the ladder's last rung: the SAME pass, un-jitted (build
            # runs the engine, so the finish path is identical)
            return (enc, self._eager_fallback(build, e))
        if not holder.get("ran"):
            gang.retarget(enc)
            if record:
                gang.run_recorded()
            else:
                gang.run()
        dt = time.perf_counter() - t0
        # a fresh build's first run IS the XLA compile (jit is lazy)
        if holder.get("ran"):
            self.metrics.record_engine_build(dt)
        else:
            # time spent blocked on someone else's in-flight compile is
            # already booked as stallSeconds — keep it out of execute
            self.metrics.record_phase_seconds(
                execute=max(0.0, dt - broker_info.get("wait_s", 0.0))
            )
        self._maybe_speculate(enc, config, "gang", record=record, window=window)
        return (enc, gang)

    def _gang_finish(self, disp, record: bool):
        """The deferred tail of a gang pass: decode (ONE batched device
        transfer for the assignment diff), victim deletes, write-backs.
        Releases the pass's engine lease on every exit."""
        try:
            return self._gang_finish_inner(disp, record)
        finally:
            self._unlease_engine()

    def _gang_finish_inner(self, disp, record: bool):
        import numpy as np

        enc, gang = disp
        t_decode = time.perf_counter()
        if record and getattr(gang, "_kss_eager_fallback", False):
            # a degraded pass's record decode lazily builds its replay
            # programs (_recorder/_assemble_trace) — those compiles must
            # run un-jitted too, or the "slow but completes" guarantee
            # dies right here on the same wedged compiler
            with eager_execution():
                results = gang.results()
        else:
            results = gang.results() if record else None
        # preemption victims: pre-bound pods the preempt phase evicted.
        # They are NOT in placements (decode covers queued pods only), so
        # diff the full [P] assignment exactly like the sequential path —
        # upstream preemption deletes victims through the API. One
        # device_get fetches both sides of the diff; placements decode
        # reads the already-landed `after` rows (no second sync).
        before, after = jax.device_get(
            (enc.state0.assignment, gang._final_state.assignment)
        )
        before = np.asarray(before)
        after = np.asarray(after)
        placements = gang.enc.decode_assignment(after)
        rounds = int(np.asarray(gang._rounds))
        # booked here, not at dispatch: the rounds scalar stays on
        # device until this finish-path fetch (async overlap depends on
        # the dispatch staying sync-free)
        self.metrics.record_gang(fixpoint_rounds=rounds)
        for p_idx in np.nonzero((before >= 0) & (after < 0))[0]:
            ns, name = enc.pod_keys[int(p_idx)]
            self.store.delete("pods", name, ns)
        if results is not None:
            # the sequential write-back rule: last record per pod wins
            # (a nominated pod's retry overwrites its first record)
            for res in results:
                patch: dict = {
                    "metadata": {
                        "name": res.pod_name,
                        "namespace": res.pod_namespace,
                        "annotations": res.to_annotations(),
                    }
                }
                sel = placements.get((res.pod_namespace, res.pod_name), "")
                if sel:
                    patch["spec"] = {"nodeName": sel}
                if (
                    self.store.get("pods", res.pod_name, res.pod_namespace)
                    is not None
                ):
                    self.store.apply("pods", patch)
        else:
            for (ns, name), node_name in placements.items():
                if not node_name:
                    continue
                if self.store.get("pods", name, ns) is not None:
                    self.store.apply(
                        "pods",
                        {
                            "metadata": {"name": name, "namespace": ns},
                            "spec": {"nodeName": node_name},
                        },
                    )
        self.metrics.record_phase_seconds(
            decode=time.perf_counter() - t_decode
        )
        self._fleet_sample(enc, gang._final_state, "gang")
        return placements, rounds, results

    def _encode_current(self, config) -> "object | None":
        """Encode the store's current pending state under the pass's
        single config read (shared by the sequential and gang passes);
        None when nothing is schedulable.

        Three tiers, cheapest first: the (latest rv, config) LRU serves
        byte-unchanged stores verbatim; the delta encoder replays the
        store's events into the retained encoding (O(Δ)); a full
        `encode_cluster` covers everything the delta path can't prove
        exact. Encode wall time + the path taken land in the metrics'
        phase breakdown."""
        t0 = time.perf_counter()
        # the dtype policy is re-read each pass, and both cache tiers are
        # policy-aware: the LRU keys on the policy name, and the delta
        # encoder falls back to a full re-encode when its retained
        # tensors carry another policy's widths — a KSS_DTYPE_POLICY flip
        # can never serve a stale encoding or scatter into a wrong-width
        # tensor (counted as encodePolicyMisses)
        policy = policy_from_env()
        cache_key = (self.store.latest_rv(), policy.name)
        cached = self._enc_cache.get(cache_key, config)
        if cached is not EncodingCache.MISS:
            # published under the state lock: out-of-pass readers
            # (`encode_info`) must not race the write (KSS6xx)
            with self._lock:
                self.last_encode_info = {"mode": "cached"}
            self.metrics.record_encode("cached", time.perf_counter() - t0)
            telemetry.complete(
                "pass.encode", t0, time.perf_counter(), mode="cached"
            )
            return cached
        self._delta.policy = policy
        enc, info = self._delta.encode(self.store, config)
        if info.get("reason") == "dtype-policy-change":
            self.metrics.record_encode_policy_miss()
        self._enc_cache.put(cache_key, config, enc)
        with self._lock:
            self.last_encode_info = info
        self.metrics.record_encode(info["mode"], time.perf_counter() - t0)
        telemetry.complete(
            "pass.encode", t0, time.perf_counter(), mode=info["mode"]
        )
        if enc is not None:
            # cold-start accounting (utils/ledger.py): the process's
            # first real cluster encode just landed (latched)
            ledger_mod.COLD_START.mark("firstEncode")
        return enc

    # -- predictive compilation --------------------------------------------

    def _maybe_speculate(
        self, enc, config, kind: str, record: bool = False, window=None
    ) -> None:
        """The watermark trigger of the predictive warm-up service: when
        the live pod count drifts past 80% of the current pod-capacity
        bucket (or would fit the next bucket down with the same
        headroom), hand the broker a background task that re-encodes the
        cluster at the adjacent bucket and compiles the matching engine
        — so the eventual bucket crossing finds a warm executable
        instead of stalling the request thread for the XLA compile.
        Armed once per (bucket, target); disabled entirely by
        KSS_NO_SPECULATIVE_COMPILE=1 (docs/performance.md)."""
        broker = self.broker
        if not broker.speculative:
            return
        targets = adjacent_bucket_targets(
            enc.n_pods, enc.P, lo=self._delta.pod_lo
        )
        if not targets:
            return
        if self._spec_bucket != enc.P:
            # the live bucket moved: re-arm (each pair speculates once)
            self._spec_bucket = enc.P
            self._spec_armed = set()
        for target in targets:
            token = (kind, id(config), enc.N, target, window, bool(record))
            if token in self._spec_armed:
                continue
            self._spec_armed.add(token)
            broker.speculate(
                token,
                self._speculation_task(config, kind, record, window, target),
                metrics=self.metrics,
            )

    def _speculation_task(self, config, kind: str, record: bool, window, target: int):
        """A broker background task: encode the CURRENT store at the
        predicted pod-capacity bucket and return (key, build) for an
        engine warmed at those shapes. Runs entirely off the request
        thread (the store is internally locked; encode + compile are
        pure); a cluster that outgrew the prediction by the time the
        worker runs simply skips."""
        store = self.store
        policy = self._delta.policy
        node_lo = self._delta.node_lo
        pod_lo = self._delta.pod_lo
        # the device epoch at ARMING time: a speculative build must key
        # like the passes it serves (a failover between arming and the
        # worker running simply wastes the stale build)
        epoch = self._device_epoch

        def _sig(base: tuple) -> tuple:
            return base + (("devepoch", epoch),) if epoch else base

        def task():
            from ..engine.encode import encode_cluster
            from ..utils.compilecache import capacity_buckets

            nodes = store.list("nodes")
            pods = store.list("pods")
            if not nodes or not pods or len(pods) > target:
                return None
            if kind == "seq" and not any(
                not (p.get("spec") or {}).get("nodeName") for p in pods
            ):
                # an empty pending queue would bake a zero-length scan —
                # useless for serving the crossing
                return None
            ncap, _ = capacity_buckets(
                len(nodes), len(pods), node_lo=node_lo, pod_lo=pod_lo
            )
            enc_s = encode_cluster(
                nodes,
                pods,
                config,
                policy=policy,
                priorityclasses=store.list("priorityclasses"),
                namespaces=store.list("namespaces"),
                pvcs=store.list("pvcs"),
                pvs=store.list("pvs"),
                storageclasses=store.list("storageclasses"),
                node_capacity=ncap,
                pod_capacity=target,
            )
            if kind == "gang":
                from ..engine.gang import GangScheduler

                chunk = gang_chunk()
                sig = _sig((
                    "gang",
                    GangScheduler.compile_signature(enc_s),
                    GangScheduler.effective_window(enc_s, window, chunk),
                ))

                def build():
                    return GangScheduler(
                        enc_s, strict=True, chunk=chunk, eval_window=window
                    ).warmup(record=record)

            else:
                sig = _sig(("seq", BatchedScheduler.compile_signature(enc_s)))

                def build():
                    return BatchedScheduler(
                        enc_s, record=True, strict=True
                    ).warmup()

            return sig, build

        return task

    # -- async (pipelined) passes ------------------------------------------

    def begin_pass(self) -> SchedulingPassHandle:
        """Dispatch one sequential pass and return without the decode /
        write-back tail: device execution proceeds while the caller does
        other host-side work; `handle.resolve()` finishes the pass (one
        batched device transfer, store write-backs, pass metrics) and
        returns the scheduled count. The pass lock stays held until
        resolve — see SchedulingPassHandle."""
        if self.disabled:
            raise SchedulerServiceDisabled()
        self._schedule_lock.acquire()
        try:
            with self._session_scope():
                with self._lock:
                    config = self._config
                mode = "extender" if config.extenders else "sequential"
                pass_id = self._next_pass_id()
                t0 = time.perf_counter()
                with telemetry.pass_context(pass_id), telemetry.span(
                    f"pass.{mode}.dispatch", pass_id=pass_id
                ):
                    disp = self._seq_dispatch(config)
                info = self.last_encode_info
                # the originating request's distributed-trace id: resolve
                # may run on a different thread, so the handle carries it
                armed_trace = telemetry.current_trace_id()
        except BaseException:
            self._unlease_engine()
            self._schedule_lock.release()
            raise

        def finish() -> int:
            # the in-flight window: device execution of THIS pass ran
            # from dispatch until now, overlapping whatever host work
            # the caller did in between — the one span shape that lands
            # on the synthetic device track and can OVERLAP host spans
            telemetry.complete(
                "device.execute",
                t0,
                time.perf_counter(),
                tid=telemetry.DEVICE_TID,
                pass_id=pass_id,
                mode=mode,
                trace=armed_trace,
            )
            with self._session_scope(), telemetry.trace_context(
                armed_trace
            ), telemetry.pass_context(
                pass_id
            ), telemetry.span(f"pass.{mode}.resolve", pass_id=pass_id):
                results = [] if disp is None else self._seq_finish(disp)
                scheduled = sum(
                    1 for r in results if r.status == "Scheduled"
                )
            # distinct pods, like the synchronous pass (a preempting pod
            # yields two records); the explicit pass_id keeps the
            # latency histogram's exemplar causal outside pass_context
            self.metrics.record(
                metrics_mod.PassRecord(
                    mode,
                    len({(r.pod_namespace, r.pod_name) for r in results}),
                    scheduled,
                    time.perf_counter() - t0,
                ),
                pass_id=pass_id,
            )
            return scheduled

        return SchedulingPassHandle(self, mode, finish, info, pass_id=pass_id)

    def begin_gang_pass(
        self, record: bool = False, window: "int | None" = None
    ) -> SchedulingPassHandle:
        """Gang-mode `begin_pass` (see above): dispatch now, decode /
        write-backs at `resolve()`."""
        if self.disabled:
            raise SchedulerServiceDisabled()
        if window is not None and int(window) < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._schedule_lock.acquire()
        try:
            with self._session_scope():
                with self._lock:
                    config = self._config
                if config.extenders:
                    raise ValueError(
                        "gang mode does not support extenders; use "
                        "sequential mode"
                    )
                pass_id = self._next_pass_id()
                t0 = time.perf_counter()
                with telemetry.pass_context(pass_id), telemetry.span(
                    "pass.gang.dispatch", pass_id=pass_id
                ):
                    disp = self._gang_dispatch(config, record, window)
                info = self.last_encode_info
                armed_trace = telemetry.current_trace_id()
        except BaseException:
            self._unlease_engine()
            self._schedule_lock.release()
            raise

        def finish() -> int:
            telemetry.complete(
                "device.execute",
                t0,
                time.perf_counter(),
                tid=telemetry.DEVICE_TID,
                pass_id=pass_id,
                mode="gang",
                trace=armed_trace,
            )
            if disp is None:
                self.metrics.record(
                    metrics_mod.PassRecord(
                        "gang", 0, 0, time.perf_counter() - t0
                    ),
                    pass_id=pass_id,
                )
                return 0
            with self._session_scope(), telemetry.trace_context(
                armed_trace
            ), telemetry.pass_context(
                pass_id
            ), telemetry.span("pass.gang.resolve", pass_id=pass_id):
                placements, rounds, _results = self._gang_finish(disp, record)
            scheduled = sum(1 for v in placements.values() if v)
            self.metrics.record(
                metrics_mod.PassRecord(
                    "gang",
                    len(placements),
                    scheduled,
                    time.perf_counter() - t0,
                    rounds,
                ),
                pass_id=pass_id,
            )
            return scheduled

        return SchedulingPassHandle(self, "gang", finish, info, pass_id=pass_id)

    def _schedule_locked(self, config) -> list[PodSchedulingResult]:
        # the synchronous pass's device window, on the synthetic device
        # track like the async handle's (encode + engine execution live
        # inside the dispatch): pass/session/trace ids stamp from the
        # ambient contexts — the request thread runs the whole pass
        t0 = time.perf_counter()
        disp = self._seq_dispatch(config)
        if disp is None:
            return []  # nothing schedulable: no device work to record
        telemetry.complete(
            "device.execute",
            t0,
            time.perf_counter(),
            tid=telemetry.DEVICE_TID,
            mode="extender" if config.extenders else "sequential",
        )
        return self._seq_finish(disp)

    def _seq_dispatch(self, config):
        """One sequential dispatch under the execution ladder (see
        `_gang_dispatch`): device faults inside the closure escalate
        through retry → mesh shrink → CPU failover."""
        self._batch_fallback_counted = False
        return self._supervised_dispatch(
            lambda: self._seq_dispatch_once(config)
        )

    def _seq_dispatch_once(self, config):
        """Encode + execute one sequential pass (engine via the broker);
        returns an opaque tuple for `_seq_finish`, or None when nothing
        is schedulable. Trace decode and write-backs are deferred to the
        finish — the async pipeline's split point."""
        enc = self._encode_current(config)
        if enc is None:
            return None
        self._fire_device_dispatch()
        if config.extenders:
            # host-callback loop: device segments + extender HTTP calls,
            # with the same compiled-program reuse as the batch path.
            # Inherently synchronous (the extenders answer over HTTP
            # mid-pass), so the run happens here; only write-backs defer.
            # Extender-touched passes are never batch-eligible (the
            # mid-pass HTTP callbacks are per-session): solo, counted.
            self._count_solo_fallback()
            from ..engine.extender_loop import ExtenderScheduler

            sig = self._epoch_sig(
                ("ext", BatchedScheduler.compile_signature(enc))
            )
            self._lease_engine(sig)
            holder: dict = {}
            # one extender-service read per pass, under the state lock
            # (restart() swaps it there — guarded-state contract KSS6xx):
            # the whole pass runs against one consistent service
            with self._lock:
                ext_service = self.extender_service

            def build():
                t0 = time.perf_counter()
                es = ExtenderScheduler(enc, ext_service)
                holder["built_s"] = time.perf_counter() - t0
                return es

            try:
                ext_sched = self.broker.get_resilient(
                    sig, build, metrics=self.metrics, scope=self.session_id
                )
            except CompileUnavailable as e:
                ext_sched = self._eager_fallback(build, e)
            else:
                if "built_s" in holder:
                    self.metrics.record_engine_build(holder["built_s"])
                else:
                    ext_sched.retarget(enc, ext_service)
            t0 = time.perf_counter()
            results = ext_sched.run()
            self.metrics.record_phase_seconds(execute=time.perf_counter() - t0)
            return ("ext", enc, ext_sched, results)
        # reuse the previous pass's compiled program when the encoding
        # is compile-compatible (same padded shapes + baked statics)
        sig = self._epoch_sig(("seq", BatchedScheduler.compile_signature(enc)))
        # cross-tenant continuous batching (server/batchplane.py): a
        # batch-compatible pass may be served by ONE device dispatch
        # shared with other sessions' concurrent passes; None falls
        # through to today's solo dispatch
        disp = self._maybe_batched_dispatch(sig, enc)
        if disp is not None:
            return disp
        self._lease_engine(sig)
        t0 = time.perf_counter()
        holder = {}

        def build():
            s = BatchedScheduler(enc, record=True, strict=True)
            # jit is lazy: the first run IS the XLA compile, so the
            # broker's miss wall time is the true request-thread stall
            s.run()
            holder["ran"] = True
            return s

        broker_info: dict = {}
        try:
            sched = self.broker.get_resilient(
                sig, build, info=broker_info,
                metrics=self.metrics, scope=self.session_id,
            )
        except CompileUnavailable as e:
            return ("batch", enc, self._eager_fallback(build, e), None)
        if not holder.get("ran"):
            sched.retarget(enc)
            sched.run()
        dt = time.perf_counter() - t0
        # a fresh build's first run IS the XLA compile (jit is
        # lazy): book it as compile; warm passes book as execute —
        # minus any wait on an in-flight compile (that is stallSeconds)
        if holder.get("ran"):
            self.metrics.record_engine_build(dt)
        else:
            self.metrics.record_phase_seconds(
                execute=max(0.0, dt - broker_info.get("wait_s", 0.0))
            )
        self._maybe_speculate(enc, config, "seq")
        return ("batch", enc, sched, None)

    def _maybe_batched_dispatch(self, sig: tuple, enc):
        """Try to serve this sequential pass through the cross-tenant
        batch plane (server/batchplane.py): eligible passes enroll in a
        collection window under the broker key `sig` and come back with
        their slice of ONE vmapped device dispatch — placements and
        trace bytes identical to solo. Returns the same opaque tuple
        `_seq_dispatch_once` builds, or None for solo dispatch.

        Ineligible (counted ``soloFallbacks``): a session-scoped or
        process fault plane (injected faults are per-tenant semantics a
        shared dispatch would conflate — the bulkhead contract), or an
        escalated device rung (rung overrides pin dispatch devices per
        session; escalated sessions also key differently via the epoch
        suffix). A window that closes with one enrollee, a draining
        plane, or a failed batched execution also return solo — the
        plane can degrade throughput, never correctness."""
        import numpy as np

        plane = self.batch_plane
        if plane is None:
            return None
        if (
            self.fault_plane is not None
            or faultinject.active() is not None
            or self.device_rung != "device"
        ):
            self._count_solo_fallback()
            return None
        # the decode-engine for THIS pass: its jitted programs are never
        # invoked (the batch slice lands in _final_state and _trace
        # before results() could trigger a run), so it costs kernel
        # closures, not an XLA compile — and a signature-stable session
        # reuses the previous pass's instance via retarget (the broker's
        # warm-engine pattern), paying construction only when the
        # bucket/config actually moves
        cached = self._batch_decode_cache
        if cached is not None and cached[0] == sig:
            engine = cached[1].retarget(enc)
        else:
            engine = BatchedScheduler(enc, record=True, strict=True)
            self._batch_decode_cache = (sig, engine)
        queue = np.asarray(enc.queue, np.int32)
        bucket = BatchedScheduler.queue_bucket(len(queue))
        if bucket > len(queue):
            queue = np.concatenate(
                [queue, np.full(bucket - len(queue), -1, np.int32)]
            )
        out = plane.submit(
            sig, engine, queue,
            metrics=self.metrics, session_id=self.session_id,
        )
        if out is None:
            self._count_solo_fallback()
            return None
        engine._final_state, engine._trace = out
        return ("batch", enc, engine, None)

    def _maybe_batched_gang_dispatch(self, sig: tuple, enc, chunk, window):
        """Gang-pass twin of `_maybe_batched_dispatch`: enroll this gang
        pass's fused fixpoint in the batch plane (`batch.gang.run` — the
        vmapped `gang.fixpoint` over the session axis) and come back
        with this session's slice of ONE device dispatch. Returns the
        same `(enc, gang)` tuple `_gang_dispatch_once` builds for solo,
        so `_gang_finish` is oblivious to how the pass was served, or
        None for solo dispatch.

        Same ineligibility rules as the sequential path (fault planes,
        escalated rungs, lone windows, draining or failed planes — all
        counted ``soloFallbacks``); additionally, record passes never
        reach here (`_gang_dispatch_once` keeps them solo: the trace
        replay is per-session host work by design)."""
        import numpy as np

        from ..engine.gang import GangScheduler

        plane = self.batch_plane
        if plane is None:
            return None
        if (
            self.fault_plane is not None
            or faultinject.active() is not None
            or self.device_rung != "device"
        ):
            self._count_solo_fallback()
            return None
        # the decode-engine for THIS pass (never dispatched solo: the
        # batch slice lands in _final_state/_rounds before anything
        # could trigger a run) — signature-stable sessions reuse it via
        # retarget, exactly like the sequential decode cache
        cached = self._batch_gang_decode_cache
        if cached is not None and cached[0] == sig:
            gang = cached[1].retarget(enc)
        else:
            gang = GangScheduler(
                enc, strict=True, chunk=chunk, eval_window=window
            )
            self._batch_gang_decode_cache = (sig, gang)
        if gang.fixpoint_fn is None:
            # no fused program for this configuration (static loop):
            # nothing to vmap — solo dispatch
            self._count_solo_fallback()
            return None
        # the PrioritySort queue rides the batch axis as the [P] order
        # tensor (the gang program's queue encoding — fixed length, so
        # bucket-compatible sessions stack without padding logic)
        order, _ = gang.order_arrays()
        out = plane.submit(
            sig, gang, np.asarray(order, np.int32),
            metrics=self.metrics, session_id=self.session_id,
            kind="gang",
        )
        if out is None:
            self._count_solo_fallback()
            return None
        gang._final_state, gang._rounds = out
        return (enc, gang)

    def _seq_finish(self, disp) -> list[PodSchedulingResult]:
        """The deferred tail of a sequential pass: trace decode (batched
        device transfers inside `results()`), victim deletes, write-backs.
        Releases the pass's engine lease on every exit."""
        try:
            return self._seq_finish_inner(disp)
        finally:
            self._unlease_engine()

    def _seq_finish_inner(self, disp) -> list[PodSchedulingResult]:
        import numpy as np

        kind, enc, engine, results = disp
        # one consistent extender service for the whole finish (swapped
        # under the state lock by restart() — KSS6xx)
        with self._lock:
            ext_service = self.extender_service
        t0 = time.perf_counter()
        if kind == "ext":
            final_assignment = engine.final_state.assignment
        elif getattr(engine, "_kss_eager_fallback", False):
            # same trap the gang record decode has: any jit `results()`
            # creates lazily must stay on a degraded pass's eager rung
            # (today the sequential engine jits everything in __init__,
            # but this guard keeps that an implementation detail)
            with eager_execution():
                results = engine.results()
            final_assignment = engine._final_state.assignment
        else:
            results = engine.results()
            final_assignment = engine._final_state.assignment
        self.metrics.record_phase_seconds(decode=time.perf_counter() - t0)

        # preemption victims: pre-bound pods that lost their node (upstream
        # preemption deletes victims through the API). ONE device_get for
        # both sides of the diff instead of two separate host syncs; the
        # placements decode reads the already-landed `after` rows.
        t_decode = time.perf_counter()
        before, after = jax.device_get((enc.state0.assignment, final_assignment))
        before = np.asarray(before)
        after = np.asarray(after)
        placements = enc.decode_assignment(after)
        for p_idx in np.nonzero((before >= 0) & (after < 0))[0]:
            ns, name = enc.pod_keys[int(p_idx)]
            self.store.delete("pods", name, ns)

        # write results back onto the pod objects (last record per pod wins
        # — a nominated pod's retry attempt overwrites its first record,
        # like the reference's sequential annotation updates)
        for res in results:
            annotations = res.to_annotations()
            annotations.update(
                ext_service.annotations_for(res.pod_namespace, res.pod_name)
            )
            patch: dict = {
                "metadata": {
                    "name": res.pod_name,
                    "namespace": res.pod_namespace,
                    "annotations": annotations,
                }
            }
            sel = placements.get((res.pod_namespace, res.pod_name), "")
            if sel:
                patch["spec"] = {"nodeName": sel}
            if self.store.get("pods", res.pod_name, res.pod_namespace) is not None:
                self.store.apply("pods", patch)
            # flushed results are purged, like the reference reflector's
            # DeleteData after AddStoredResultToPod (storereflector.go:70-119)
            ext_service.delete_data(res.pod_namespace, res.pod_name)
        self.metrics.record_phase_seconds(
            decode=time.perf_counter() - t_decode
        )
        self._fleet_sample(
            enc,
            engine.final_state if kind == "ext" else engine._final_state,
            "extender" if kind == "ext" else "sequential",
        )
        return results


@locking.guard_inferred
class SimulatorService:
    """Store + scheduler + snapshot composites (the DI container analogue).

    `external_scheduler_enabled` mirrors the reference's
    EXTERNAL_SCHEDULER_ENABLED (simulator.go:75-80: the internal
    scheduler is never started): the scheduler service is built disabled,
    and pod binds arriving through the resource CRUD surface (an external
    scheduler setting `spec.nodeName`) are recorded into the service's
    metrics as mode="external" passes."""

    def __init__(
        self,
        initial_config: "SchedulerConfiguration | None" = None,
        external_scheduler_enabled: bool = False,
        broker: "CompileBroker | None" = None,
        session_id: "str | None" = None,
        fault_plane=None,
    ):
        self.store = ResourceStore()
        self._controllers_lock = locking.make_lock("service.controllers")
        self.external_scheduler_enabled = external_scheduler_enabled
        # replayable JSONL trace of the most recent lifecycle chaos run
        # (run_lifecycle; served by GET /api/v1/lifecycle/trace)
        self.last_lifecycle_trace: "list[dict] | None" = None
        self.scheduler = SchedulerService(
            self.store,
            initial_config,
            disabled=external_scheduler_enabled,
            broker=broker,
            session_id=session_id,
            fault_plane=fault_plane,
        )
        if external_scheduler_enabled:
            # key -> last-seen bound state; a recorded external bind is
            # specifically the pending→bound TRANSITION, so pods imported
            # or replicated already-bound never count as scheduler
            # activity (they enter the map as bound on their ADDED event)
            self._ext_seen: dict[tuple[str, str], bool] = {}
            self._ext_lock = locking.make_lock("service.external")
            self.store.subscribe(self._record_external_bind)
        self.store.snapshot_initial()

    def _record_external_bind(self, ev) -> None:
        """Store subscriber (external mode only): a pod the simulator has
        seen pending that now carries a nodeName is an external
        scheduler's bind — count it. All such transitions are external
        here by construction (the internal engine is disabled)."""
        if ev.kind != "pods":
            return
        meta = (ev.obj or {}).get("metadata", {})
        key = (meta.get("namespace", "default"), meta.get("name", ""))
        with self._ext_lock:
            if ev.event_type == "DELETED":
                self._ext_seen.pop(key, None)
                return
            bound = bool(((ev.obj or {}).get("spec", {}) or {}).get("nodeName"))
            if bound and self._ext_seen.get(key) is False:
                self.scheduler.metrics.record(
                    metrics_mod.PassRecord(
                        mode="external", pods=1, scheduled=1, wall_s=0.0
                    )
                )
            self._ext_seen[key] = bound

    def run_controllers(self) -> int:
        """Run the deterministic controller subset (deployment →
        replicaset expansion, PV binding; controllers/steps.py) to a
        fixpoint over the store. The reference's controller subset runs
        CONTINUOUSLY against its apiserver (simulator/controller/
        controller.go:31-46 — create a Deployment, get Pods); here the
        serving shell invokes this after every resource mutation, which
        is the same convergence expressed deterministically. Returns the
        rounds executed (0 when nothing the controllers read exists —
        the cheap early-exit that keeps bulk pod/node loads O(N)).
        Fixpoints are serialized: concurrent request threads must not
        interleave partial reconciles (one thread's freshly created pods
        racing another's round)."""
        store = self.store
        if (
            store.count("deployments") == 0
            and store.count("replicasets") == 0
            and (store.count("pvcs") == 0 or store.count("pvs") == 0)
        ):
            return 0
        from ..controllers.steps import run_to_fixpoint

        with self._controllers_lock:
            return run_to_fixpoint(store)

    # -- export / import / reset -------------------------------------------

    def export(self) -> dict:
        """Export resources + config. In external mode the config is not
        exported (reference export.go:400-412 tolerates
        ErrServiceDisabled and omits it)."""
        try:
            cfg = self.scheduler.get_config()
        except SchedulerServiceDisabled:
            cfg = None
        return export_snapshot(self.store, cfg)

    def import_(self, snapshot: dict, ignore_err: bool = False) -> list[str]:
        """Restart the scheduler with the imported config (unless absent),
        then apply resources in dependency order (reference
        export.go:246-263 Import). In external mode the config restart is
        skipped, resources still apply (export.go:251-257)."""
        cfg = snapshot.get("schedulerConfig")
        if cfg:
            try:
                self.scheduler.restart(cfg)
            except SchedulerServiceDisabled:
                pass
        _, errors = import_snapshot(self.store, snapshot, ignore_err=ignore_err)
        return errors

    def reset(self) -> None:
        """Reset resources, and the scheduler config unless disabled
        (reference reset.go:80 tolerates ErrServiceDisabled)."""
        # note: no _ext_seen maintenance needed here — store.reset()
        # dispatches DELETED + re-ADDED events through the subscriber,
        # which rebuilds the map (clearing afterwards would wipe the
        # pending-state of boot-snapshot pods and undercount their
        # later external binds)
        self.store.reset()
        try:
            self.scheduler.reset()
        except SchedulerServiceDisabled:
            pass

    # -- lifecycle / chaos runs --------------------------------------------

    def run_lifecycle(self, spec: "dict | object") -> dict:
        """Run one cluster-lifecycle chaos timeline (lifecycle/engine.py)
        over its OWN isolated store — like the /api/v1/scenario route, the
        serving store is never mutated — while the passes and disruption
        tallies flow into THIS service's scheduler metrics, so
        `GET /api/v1/metrics` reflects lifecycle activity. The run's
        replayable JSONL trace is retained on `last_lifecycle_trace` for
        `GET /api/v1/lifecycle/trace`."""
        from ..lifecycle.engine import LifecycleEngine
        from ..scenario.chaos import ChaosSpec

        if not isinstance(spec, ChaosSpec):
            spec = ChaosSpec.from_dict(spec)
        engine = LifecycleEngine(spec, metrics=self.scheduler.metrics)
        result = engine.run()
        self.last_lifecycle_trace = engine.trace
        return result
