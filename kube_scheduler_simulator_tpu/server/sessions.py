"""The multi-tenant session plane (docs/sessions.md).

One process, many isolated simulations: each session owns its own
`ResourceStore`, `SchedulerService` (and with it per-session
`SchedulingMetrics`, encoding cache, and delta-encoder state), while a
single SHARED `CompileBroker` keys warm engines by
``(kind, compile signature, window)`` — bucket-compatible tenants reuse
executables for free, and the per-key engine lease plus per-scope
cooldowns (utils/broker.py) keep sharing safe and failures bulkheaded.
The failure domain is a *session*, not the process: a tenant's wedged
compile, fault-injected pass, or oversized cluster degrades that tenant
only.

Robustness machinery owned here:

  * **Admission control** — ``KSS_MAX_SESSIONS`` bounds the session
    count, ``KSS_MAX_PENDING_PODS_PER_SESSION`` bounds each tenant's
    queue, and a bounded concurrent-pass semaphore
    (``KSS_MAX_CONCURRENT_PASSES``) sheds device-driving requests past
    capacity. All three surface as the existing structured 503 +
    Retry-After (server/httpserver.py), so clients back off the same
    way they do for compile degradation.
  * **Idle eviction** — a session idle past
    ``KSS_SESSION_IDLE_EVICT_S`` is snapshotted to disk in the PR 4
    checkpoint family (``kss-session-checkpoint/v1``: verbatim store
    dump, scheduler config, cumulative metrics, pass sequence) and its
    in-memory state released; the next touch restores it transparently.
    Eviction is load shedding, never data loss.
  * **Fork** — `fork()` round-trips the same checkpoint document into a
    fresh session id: what-if experiments branch from live (or evicted)
    state without copying code paths.
  * **Graceful drain** — `drain()` (SIGTERM / POST /api/v1/admin/drain,
    docs/resilience.md) lets every in-flight pass finish under
    ``KSS_DRAIN_DEADLINE_S``, snapshots EVERY live session — the
    default included — through the same checkpoint family, and
    quiesces the shared broker; a server restarted over the same
    ``KSS_SESSION_DIR`` adopts the snapshots at boot
    (`adopt_snapshots`), so a rolling restart loses zero acknowledged
    writes.

The ``default`` session wraps the server's original `SimulatorService`,
so every legacy single-session route keeps working unchanged.
"""

from __future__ import annotations

import json
import os
import secrets
import tempfile
import threading
import time
import weakref
from contextlib import contextmanager

from ..lifecycle.checkpoint import (
    SESSION_CHECKPOINT_FORMAT,
    canonical_digest,
    load_checkpoint,
    write_checkpoint,
)
from ..utils import envcheck, faultinject, fleetstats, locking
from ..utils import telemetry
from ..utils import ledger as ledger_mod
from ..utils import slo as slo_mod
from ..utils.broker import CompileBroker
from . import batchplane as batchplane_mod
from . import durability
from .service import SchedulerServiceDisabled, SimulatorService

DEFAULT_SESSION_ID = "default"


class UnknownSession(KeyError):
    """No session with that id (404)."""

    def __init__(self, sid: str):
        super().__init__(sid)
        self.sid = sid

    def __str__(self):
        return f"unknown session {self.sid!r}"


class SessionLimitExceeded(RuntimeError):
    """KSS_MAX_SESSIONS reached: session creation is shed (503)."""

    retry_after_s = 5


class SessionQuotaExceeded(RuntimeError):
    """A per-session quota (pending pods) is full: the mutation is shed
    (503) until the tenant schedules or deletes some of its queue."""

    retry_after_s = 2


class ServerSaturated(RuntimeError):
    """Every concurrent-pass slot is taken: the device-driving request
    is shed (503) instead of queueing unboundedly behind the device."""

    retry_after_s = 1


class SessionBusy(RuntimeError):
    """The session has a pass in flight; eviction refused (409)."""


def _env_int(env, name: str, default: int, minimum: int) -> int:
    raw = env.get(name, "")
    if not raw:
        return default
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected an integer") from None
    if v < minimum:
        raise ValueError(f"{name}={raw!r}: must be >= {minimum}")
    return v


def _env_float(env, name: str, default: float, minimum: float) -> float:
    raw = env.get(name, "")
    if not raw:
        return default
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected a number") from None
    if v < minimum:
        raise ValueError(f"{name}={raw!r}: must be >= {minimum}")
    return v


class Session:
    """One tenant: id + its `SimulatorService` (None while evicted)."""

    def __init__(self, sid: str, name: str, service: "SimulatorService | None"):
        self.id = sid
        self.name = name
        self.service = service
        self.state = "live"  # "live" | "evicted"
        # serializes THIS session's live<->evicted transitions (and the
        # checkpoint I/O they do) so the manager-wide lock never spans
        # disk reads/writes: one tenant's multi-second snapshot must not
        # stall every other tenant's request routing. Lock order:
        # _state_lock OUTSIDE manager._lock, never the reverse.
        self._state_lock = locking.make_lock("session.state")
        self.created_at = time.time()
        self.last_touch = time.monotonic()
        self.snapshot_path: "str | None" = None
        self.fault_spec: "str | None" = None
        self.restores = 0
        # requests currently routed INTO this session (manager.using):
        # eviction refuses while any is live, and aborts its commit when
        # one raced in mid-snapshot — a 201'd write must never vanish
        # into a discarded service object (guarded by manager._lock)
        self._active_requests = 0

    def info(self) -> dict:
        doc = {
            "id": self.id,
            "name": self.name,
            "state": self.state,
            "createdAt": round(self.created_at, 3),
            "idleSeconds": round(time.monotonic() - self.last_touch, 3),
            "restores": self.restores,
            "faultInject": self.fault_spec,
        }
        svc = self.service
        if svc is not None:
            snap = svc.scheduler.metrics.snapshot()
            doc["passes"] = snap["passes"]
            doc["totalScheduled"] = snap["totalScheduled"]
            doc["pendingPods"] = svc.store.count_pending_pods()
            doc["pods"] = svc.store.count("pods")
            doc["nodes"] = svc.store.count("nodes")
        else:
            doc["snapshotPath"] = self.snapshot_path
        return doc


@locking.guard_inferred
class SessionManager:
    """Owns every session, the shared broker, and the admission knobs."""

    def __init__(
        self,
        default_service: SimulatorService,
        *,
        broker: "CompileBroker | None" = None,
        max_sessions: "int | None" = None,
        pending_pod_quota: "int | None" = None,
        max_concurrent_passes: "int | None" = None,
        idle_evict_s: "float | None" = None,
        snapshot_dir: "str | None" = None,
        sse_max_subscribers: "int | None" = None,
        journal: "bool | None" = None,
        journal_sync: "bool | None" = None,
        env: "dict | None" = None,
    ):
        env = os.environ if env is None else env
        self.max_sessions = (
            max_sessions
            if max_sessions is not None
            else _env_int(env, "KSS_MAX_SESSIONS", 64, 1)
        )
        # 0 = unlimited (the historical behavior)
        self.pending_pod_quota = (
            pending_pod_quota
            if pending_pod_quota is not None
            else _env_int(env, "KSS_MAX_PENDING_PODS_PER_SESSION", 0, 0)
        )
        self.max_concurrent_passes = (
            max_concurrent_passes
            if max_concurrent_passes is not None
            else _env_int(env, "KSS_MAX_CONCURRENT_PASSES", 4, 1)
        )
        self.idle_evict_s = (
            idle_evict_s
            if idle_evict_s is not None
            else _env_float(env, "KSS_SESSION_IDLE_EVICT_S", 0.0, 0.0)
        )
        self.sse_max_subscribers = (
            sse_max_subscribers
            if sse_max_subscribers is not None
            else _env_int(env, "KSS_SSE_MAX_SUBSCRIBERS", 64, 1)
        )
        # graceful-drain budget: how long in-flight passes may keep
        # running before a draining snapshot proceeds without them
        # (docs/resilience.md). 0 = snapshot immediately.
        self.drain_deadline_s = _env_float(
            env, "KSS_DRAIN_DEADLINE_S", 30.0, 0.0
        )
        self._snapshot_dir = snapshot_dir or env.get("KSS_SESSION_DIR") or None
        # the fleet durability plane (server/durability.py, docs/fleet.md):
        # KSS_FLEET_JOURNAL arms per-session write-ahead journaling of
        # acknowledged store mutations; KSS_FLEET_JOURNAL_SYNC fsyncs
        # every append AND ships it inline to ring successors before the
        # HTTP ack (the zero-loss crash-kill mode). The fleet router arms
        # KSS_FLEET_JOURNAL on the workers it spawns (sync mode passes
        # through from the caller's env); a standalone server opts in
        # explicitly.
        self.journal_enabled = (
            journal
            if journal is not None
            else envcheck.env_truthy(env.get("KSS_FLEET_JOURNAL"))
        )
        self.journal_sync = (
            journal_sync
            if journal_sync is not None
            else envcheck.env_truthy(env.get("KSS_FLEET_JOURNAL_SYNC"))
        )
        self._journals: "dict[str, durability.SessionJournal]" = {}
        # serializes every replica-file mutation (full-unit store,
        # inline journal append, promote) — an append racing a rewrite
        # would land on the replaced inode and silently vanish
        self._replica_lock = locking.make_lock("sessions.replica-files")
        # sid -> the store object whose watch feed the journal rides:
        # distinguishes a re-arm onto the SAME store (rebase in place)
        # from a fresh service (new journal, new subscription)
        self._journal_stores: "dict[str, object]" = {}
        # sid -> the checkpoint document the journal is relative to:
        # base + journal entries IS the session's replication unit, with
        # no quiesce on the hot path (the base is immutable, the journal
        # append-only)
        self._repl_base: "dict[str, dict]" = {}
        # counters carried across journal replacement (restore re-arms)
        self._journal_appends_retired = 0
        self._journal_bytes_retired = 0
        # transport bookkeeping (receive_checkpoints / promote_replicas)
        self.adopted_units = 0
        self.stored_replicas = 0
        self.rejected_units = 0
        self.promoted_replicas = 0
        # the worker-side successor shipper (server/replication.py),
        # wired by the HTTP server after construction; never under the
        # manager lock — shipping does network I/O
        self.replication = None
        # ONE broker for every session: warm engines shared by compile
        # signature; per-session bulkheading lives in the broker's
        # scope-keyed cooldowns and per-key leases (utils/broker.py).
        # Broker-level events nobody attributes per call — real worker
        # crashes, speculative builds armed before the metrics kwarg
        # existed — fall back to the default session's registry, keeping
        # the legacy /api/v1/metrics surface (brokerWorkerCrashes,
        # speculativeCompiles) live
        self.broker = (
            broker
            if broker is not None
            else CompileBroker(metrics=default_service.scheduler.metrics)
        )
        self._lock = locking.make_rlock("sessions.manager")
        self._pass_sem = threading.BoundedSemaphore(self.max_concurrent_passes)
        self.evictions = 0
        # graceful-drain state (docs/resilience.md): `draining` flips
        # /readyz to the distinct `draining` 503; `drained` counts the
        # sessions snapshotted by drain() (kss_drained_sessions_total)
        self.draining = False
        self.drained = 0
        # cross-tenant continuous batching (server/batchplane.py,
        # KSS_BATCH=1): bucket-compatible concurrent passes from
        # different sessions stack onto ONE device dispatch through the
        # shared plane; window/occupancy counters fall back to the
        # default session's registry, like the broker's
        self.batch_plane = batchplane_mod.from_env(
            metrics=default_service.scheduler.metrics
        )
        # adopt the boot service as the implicit default session: it
        # joins the shared compile plane and gains the session label,
        # and every legacy route keeps hitting it unchanged
        default_service.scheduler.session_id = DEFAULT_SESSION_ID
        default_service.scheduler.broker = self.broker
        default_service.scheduler.batch_plane = self.batch_plane
        self._sessions: "dict[str, Session]" = {
            DEFAULT_SESSION_ID: Session(
                DEFAULT_SESSION_ID, DEFAULT_SESSION_ID, default_service
            )
        }
        # the fleet observatory's census + Prometheus exposition read
        # the known session ids through this hook (the most recent
        # manager wins — one serving process owns one session plane;
        # utils/fleetstats.py). Weakref-backed: a shut-down embedded
        # server must not stay reachable — and its whole session plane
        # with it — through a module-level global
        manager_ref = weakref.ref(self)

        def _known_session_ids() -> "list[str] | None":
            mgr = manager_ref()
            return None if mgr is None else mgr.session_ids()

        fleetstats.set_session_provider(_known_session_ids)
        self._stop = threading.Event()
        self._sweeper: "threading.Thread | None" = None
        if self.idle_evict_s > 0:
            self._sweeper = threading.Thread(
                target=self._sweep_loop, name="kss-session-sweeper", daemon=True
            )
            self._sweeper.start()
        # a previous process's drain (or idle eviction) may have left
        # session snapshots in the configured directory: adopt them so
        # the restart is transparent to every tenant (the default
        # session's state restores in place; others restore on touch)
        if self._snapshot_dir:
            self.adopt_snapshots()
        # arm the default session's journal LAST: adopt_snapshots has
        # already restored its snapshot (if one survived), so any
        # journal tail past that snapshot — the acknowledged writes a
        # crash-kill left un-snapshotted — replays into the live store
        # before new appends begin (the local half of the zero-loss
        # story; the cross-host half is the replica ship)
        if self.journal_enabled:
            with self._lock:
                dsess = self._sessions[DEFAULT_SESSION_ID]
                armed = DEFAULT_SESSION_ID in self._journals
            if not armed:
                base_doc = self._session_doc(dsess)
                tail = durability.read_journal(
                    durability.journal_path(
                        self.snapshot_dir(), DEFAULT_SESSION_ID
                    ),
                    int((base_doc.get("store") or {}).get("rv", 0)),
                )
                if tail:
                    svc = dsess.service
                    svc.store.load_state(
                        durability.replay_store_state(base_doc["store"], tail)
                    )
                    svc.store.snapshot_initial()
                self._arm_journal(dsess, base_doc=base_doc)

    # -- lookup --------------------------------------------------------------

    def get(self, sid: str, touch: bool = True, track: bool = False) -> Session:
        """The session, restored from its snapshot if evicted (the
        transparent-restore contract: eviction is invisible to the next
        request beyond its latency). The restore's disk read + service
        rebuild run under the SESSION's state lock only — other
        tenants' routing never waits on it. `track` registers the caller
        as an in-flight request (same locked window that confirms the
        session live, so eviction can exclude it); pair with `using`."""
        while True:
            with self._lock:
                sess = self._sessions.get(sid)
                if sess is None:
                    raise UnknownSession(sid)
                if sess.state == "live":
                    if touch:
                        sess.last_touch = time.monotonic()
                    if track:
                        sess._active_requests += 1
                    return sess
            with sess._state_lock:
                with self._lock:
                    if self._sessions.get(sid) is not sess:
                        raise UnknownSession(sid)  # raced with delete
                if sess.state == "evicted":
                    self._restore(sess)
            # loop: re-take the fast path for the touch + return

    @contextmanager
    def using(self, sid: str):
        """Route a request into a session: the session is live for the
        duration (restored if needed) and REGISTERED as in use, so the
        idle sweeper cannot snapshot-and-discard the service out from
        under a mutation it is about to acknowledge (eviction is load
        shedding, never data loss — including the race window). The
        exit touch also restarts the idle clock at request completion,
        not arrival."""
        sess = self.get(sid, track=True)
        try:
            yield sess
        finally:
            with self._lock:
                sess._active_requests -= 1
                sess.last_touch = time.monotonic()

    def info(self, sid: str) -> dict:
        """Session info WITHOUT restoring an evicted session (listing
        must not defeat eviction)."""
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None:
                raise UnknownSession(sid)
            return sess.info()

    def list_info(self) -> list[dict]:
        with self._lock:
            return [
                s.info()
                for s in sorted(
                    self._sessions.values(), key=lambda s: s.created_at
                )
            ]

    def live_services(self) -> "list[tuple[str, SimulatorService]]":
        """One consistent cut of every LIVE session's (id, service) —
        the scrape path's accessor: no per-id re-lookup to race with
        DELETE, and no restore (a scrape must never defeat idle
        eviction; an evicted session's counters live in its snapshot
        until the next real touch)."""
        with self._lock:
            return [
                (s.id, s.service)
                for s in sorted(
                    self._sessions.values(), key=lambda s: s.created_at
                )
                if s.state == "live" and s.service is not None
            ]

    def session_ids(self) -> "list[str]":
        """Session ids known to the manager (live + evicted), read
        under the manager lock — the fleet observatory's accessor (the
        census counts them; the exposition drops series for ids no
        longer here)."""
        with self._lock:
            return list(self._sessions)

    def is_draining(self) -> bool:
        """The drain flag, read under the manager lock — `draining` is
        lock-claimed state (KSS6xx): the HTTP layer's shed path and
        readyz go through here, never through a bare attribute read
        (the KSS_RACE_CHECK witness caught exactly that on the live
        serving path)."""
        with self._lock:
            return self.draining

    def begin_draining(self) -> bool:
        """Atomically flip the drain flag; False when a drain was
        already in progress (the first caller wins — `begin_drain`'s
        idempotence, now a real test-and-set instead of a two-step
        read/write on another lock)."""
        with self._lock:
            if self.draining:
                return False
            self.draining = True
            return True

    def drained_sessions(self) -> int:
        """Sessions snapshotted by drains so far, read under the
        manager lock (the metrics route's accessor — `drained` is
        lock-claimed state, KSS6xx)."""
        with self._lock:
            return self.drained

    def stats(self) -> dict:
        with self._lock:
            live = sum(1 for s in self._sessions.values() if s.state == "live")
            return {
                "sessions": len(self._sessions),
                "live": live,
                "evicted": len(self._sessions) - live,
                "evictions": self.evictions,
                "maxSessions": self.max_sessions,
                "maxPendingPodsPerSession": self.pending_pod_quota,
                "maxConcurrentPasses": self.max_concurrent_passes,
                "idleEvictSeconds": self.idle_evict_s,
                "draining": self.draining,
                "drainedSessions": self.drained,
                "drainDeadlineSeconds": self.drain_deadline_s,
                # the continuous-batching plane's config + live windows
                # (server/batchplane.py); {"armed": False} when off
                "batching": self.batch_plane.stats()
                if self.batch_plane is not None
                else {"armed": False},
                # the durability plane (docs/fleet.md): write-ahead
                # journaling + successor replication
                "journal": self.journal_stats(),
                "replication": self.replication.stats()
                if self.replication is not None
                else {"armed": False},
            }

    # -- create / fork / delete ---------------------------------------------

    def create(
        self,
        name: "str | None" = None,
        snapshot: "dict | None" = None,
        fault_inject: "str | None" = None,
        slo: "dict | None" = None,
        session_id: "str | None" = None,
    ) -> "tuple[Session, list[str]]":
        """A fresh session (admission-controlled). `fault_inject` is the
        KSS_FAULT_INJECT grammar scoped to THIS session only — the
        chaos-testing bulkhead; a malformed spec raises ValueError (400).
        `slo` is the PUT /slo body shape (utils/slo.py
        `objectives_from_spec`) applied at birth — a tenant arrives with
        its objectives declared, not defaulted-then-patched.
        `session_id` pins an explicit id instead of a generated one —
        the fleet router pre-computes the id so it can place the session
        on its consistent-hash ring owner (docs/fleet.md); a malformed
        or already-taken id raises ValueError (400). Returns
        (session, import errors) — `snapshot` is applied like
        POST /api/v1/import."""
        plane = (
            faultinject.FaultPlane.parse(fault_inject) if fault_inject else None
        )
        # parse the SLO spec BEFORE any state exists (a malformed spec
        # is a 400, and an admitted session must never half-exist) —
        # the SAME parse the PUT /slo route runs, so the two surfaces
        # honor identical bodies (incl. window/burn/hold overrides and
        # {"enabled": false} meaning explicitly disarmed)
        slo_plane = (
            slo_mod.plane_from_put_spec(slo, None) if slo is not None else None
        )
        # quota-check the boot snapshot BEFORE any state exists: an
        # over-quota create is shed whole, leaving nothing behind
        self.admit_import(None, snapshot)
        with self._lock:
            self._admit_session_locked()
            sid = (
                self._claim_sid_locked(session_id)
                if session_id is not None
                else self._new_sid_locked()
            )
            service = SimulatorService(
                broker=self.broker, session_id=sid, fault_plane=plane
            )
            service.scheduler.batch_plane = self.batch_plane
            sess = Session(sid, name or sid, service)
            sess.fault_spec = fault_inject
            self._sessions[sid] = sess
            if self.journal_enabled:
                # arm BEFORE the session is reachable: the journal sees
                # every acknowledged write from the very first one
                self._arm_journal(sess, fresh=True)
        if slo is not None:
            if slo_plane is not None:
                slo_plane.session_id = sid
            service.scheduler.metrics.set_slo_plane(slo_plane)
        errors = service.import_(snapshot) if snapshot else []
        return sess, errors

    def fork(self, sid: str, name: "str | None" = None) -> Session:
        """Branch a session: the source's checkpoint document (built
        in-memory when live, read from disk when evicted — no restore)
        round-trips into a new session id. The fork inherits the
        source's fault spec; its state diverges independently from the
        moment of the fork. A live source with a pass in flight is
        refused (SessionBusy, 409) — forking mid-pass would tear the
        snapshot: half the pass's bindings with none of its counters."""
        with self._lock:
            src = self._sessions.get(sid)
            if src is None:
                raise UnknownSession(sid)
            self._admit_session_locked()
        with src._state_lock:
            if src.state == "live":
                # the same pass exclusion evict takes, for the same
                # reason: dump_state/metrics must be a consistent cut
                lock = src.service.scheduler._schedule_lock
                if not lock.acquire(blocking=False):
                    raise SessionBusy(f"session {sid!r} has a pass in flight")
                try:
                    doc = self._session_doc(src)
                finally:
                    lock.release()
            else:
                doc = load_checkpoint(
                    src.snapshot_path, SESSION_CHECKPOINT_FORMAT
                )
        sess = Session("", name or f"{src.name}-fork", None)
        sess.fault_spec = doc.get("faultInject")
        sess.state = "evicted"  # materialized by the restore below
        # holding the NEW session's state lock across insert + snapshot
        # write: a concurrent get() of the fresh id blocks until the
        # snapshot it restores from exists
        with sess._state_lock:
            with self._lock:
                self._admit_session_locked()  # re-check: creates may race
                new_sid = self._new_sid_locked()
                sess.id = new_sid
                self._sessions[new_sid] = sess
            doc["id"] = new_sid
            doc["name"] = sess.name
            path = os.path.join(self.snapshot_dir(), f"{new_sid}.json")
            write_checkpoint(doc, path)
            sess.snapshot_path = path
        # eager restore (outside the manager lock): the 201 response
        # carries a live session, exactly like create()
        return self.get(new_sid)

    def delete(self, sid: str) -> None:
        if sid == DEFAULT_SESSION_ID:
            raise ValueError("the default session cannot be deleted")
        with self._lock:
            sess = self._sessions.pop(sid, None)
            if sess is None:
                raise UnknownSession(sid)
            path = sess.snapshot_path
            j = self._journals.pop(sid, None)
            self._journal_stores.pop(sid, None)
            self._repl_base.pop(sid, None)
            if j is not None:
                appended, byts = j.counters()
                self._journal_appends_retired += appended
                self._journal_bytes_retired += byts
        if j is not None:
            j.drop()
        # any passively-held replica of the dead tenant goes too
        with self._lock:
            d = self._snapshot_dir
        if d:
            for rp in durability.replica_paths(d, sid):
                if os.path.exists(rp):
                    os.unlink(rp)
        # purge the dead tenant's namespaced ladder state from the
        # SHARED broker: its leftover cooldowns would otherwise keep
        # /api/v1/readyz degraded forever (nothing re-probes a scope
        # that can no longer issue passes)
        self.broker.drop_scope(sid)
        # and its call attribution from the program ledger — the
        # programs (and their compile cost) outlive the tenant, the
        # per-session labels must not (utils/ledger.py)
        ledger_mod.LEDGER.drop_session(sid)
        # and its pending-age bookkeeping from the fleet observatory
        # (utils/fleetstats.py) — first-seen stamps must not accumulate
        # forever under session churn
        fleetstats.drop_session(sid)
        if path and os.path.exists(path):
            os.unlink(path)

    def _admit_session_locked(self) -> None:
        if len(self._sessions) >= self.max_sessions:
            raise SessionLimitExceeded(
                f"session limit reached ({self.max_sessions}, "
                f"KSS_MAX_SESSIONS); delete a session or retry later"
            )

    def _new_sid_locked(self) -> str:
        while True:
            sid = "s-" + secrets.token_hex(4)
            if sid not in self._sessions:
                return sid

    # explicit-id grammar: the id lands in URLs, snapshot filenames, and
    # Prometheus label values, so it is held to the same conservative
    # charset as generated ids
    _SID_CHARS = frozenset(
        "abcdefghijklmnopqrstuvwxyz" "ABCDEFGHIJKLMNOPQRSTUVWXYZ" "0123456789.-_"
    )

    def _claim_sid_locked(self, session_id: str) -> str:
        sid = str(session_id).strip()
        if not sid or len(sid) > 64 or not set(sid) <= self._SID_CHARS:
            raise ValueError(
                f"session id {session_id!r} must be 1-64 chars of "
                f"[A-Za-z0-9._-]"
            )
        if sid == DEFAULT_SESSION_ID:
            raise ValueError(
                f"session id {DEFAULT_SESSION_ID!r} is reserved"
            )
        if sid in self._sessions:
            raise ValueError(f"session id {sid!r} already exists")
        return sid

    # -- admission (per-request) ----------------------------------------------

    def admit_pod(
        self, service: SimulatorService, obj: dict, *, replace: bool = False
    ) -> None:
        """Per-session pending-pod quota, checked where pods enter the
        store: an operation that would GROW the pending queue (a pod
        with no spec.nodeName) past the quota is shed with the
        structured 503 (quota 0 always passes). Growth, not shape, is
        what admission meters: an update to an already-pending pod is
        always allowed — a tenant at quota must still be able to label
        or correct its own queue. `replace` marks wholesale-replace
        semantics (item PUT), where omitting spec.nodeName UNBINDS a
        bound pod — that transition re-enters the queue and is metered;
        a merge-style apply onto a bound pod cannot unbind and passes."""
        if self.pending_pod_quota <= 0:
            return
        if ((obj or {}).get("spec") or {}).get("nodeName"):
            return
        meta = (obj or {}).get("metadata") or {}
        name = meta.get("name")
        if name:
            existing = service.store.get(
                "pods", name, meta.get("namespace") or "default"
            )
            if existing is not None:
                if not ((existing.get("spec") or {}).get("nodeName")):
                    return  # already pending: the queue does not grow
                if not replace:
                    return  # merge keeps the existing binding: no growth
                # replace drops the binding: bound -> pending, metered
        pending = service.store.count_pending_pods()
        if pending >= self.pending_pod_quota:
            raise SessionQuotaExceeded(
                f"pending-pod quota reached ({pending} >= "
                f"{self.pending_pod_quota}, KSS_MAX_PENDING_PODS_PER_SESSION); "
                f"schedule or delete pods first"
            )

    def admit_import(self, service: "SimulatorService | None", snapshot) -> None:
        """The quota check for BULK entry points (`POST /api/v1/import`,
        session-create snapshots): a snapshot whose pending pods would
        push the session past the quota is shed whole, BEFORE anything
        applies — a tenant must not smuggle an oversized queue past
        admission in one request. `service` None = a brand-new session
        (zero current pending). Controller-expanded pods (Deployments
        fanning out) are deliberately exempt: they are derived objects
        the tenant already paid quota for at the source."""
        if self.pending_pod_quota <= 0 or not isinstance(snapshot, dict):
            return
        incoming = sum(
            1
            for p in snapshot.get("pods") or []
            if isinstance(p, dict)
            and not ((p.get("spec") or {}).get("nodeName"))
        )
        if not incoming:
            return
        pending = service.store.count_pending_pods() if service else 0
        if pending + incoming > self.pending_pod_quota:
            raise SessionQuotaExceeded(
                f"snapshot carries {incoming} pending pods; with {pending} "
                f"already queued that exceeds the quota "
                f"({self.pending_pod_quota}, KSS_MAX_PENDING_PODS_PER_SESSION)"
            )

    @contextmanager
    def pass_slot(self):
        """One bounded concurrent-pass slot for a device-driving request
        (schedule / lifecycle / scenario). Saturation sheds immediately
        — a 503 the client retries beats an unbounded queue stacking up
        behind the device."""
        if not self._pass_sem.acquire(blocking=False):
            raise ServerSaturated(
                f"all {self.max_concurrent_passes} concurrent-pass slots "
                f"are busy (KSS_MAX_CONCURRENT_PASSES); retry later"
            )
        try:
            yield
        finally:
            self._pass_sem.release()

    # -- eviction / restore ---------------------------------------------------

    def snapshot_dir(self) -> str:
        with self._lock:
            if self._snapshot_dir is None:
                self._snapshot_dir = tempfile.mkdtemp(prefix="kss-sessions-")
            os.makedirs(self._snapshot_dir, exist_ok=True)
            return self._snapshot_dir

    def evict(self, sid: str) -> str:
        """Snapshot `sid` to disk and release its in-memory state; the
        next touch restores it. Refused for the default session and for
        a session with a pass OR any request in flight (SessionBusy —
        the sweeper just skips it this round); aborted, rather than
        committed, when a request races in mid-snapshot, because the
        document on disk may predate that request's acknowledged write."""
        if sid == DEFAULT_SESSION_ID:
            raise ValueError("the default session cannot be evicted")
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None:
                raise UnknownSession(sid)
        with sess._state_lock:
            with self._lock:
                if self._sessions.get(sid) is not sess:
                    raise UnknownSession(sid)  # raced with delete
                if sess.state == "evicted":
                    return sess.snapshot_path
            # a request whose response already flushed may still be
            # inside `using`'s exit bookkeeping (the decrement runs
            # AFTER the bytes hit the socket), so an evict issued
            # right after a completed call can observe a stale
            # in-flight count — give it a short grace to drain before
            # refusing, instead of a spurious 409
            # (polling, not a Condition: the manager lock is a witness-
            # wrappable RLock, and Condition's ownership probe misreads
            # re-entrant wrappers. The wait is bounded and exits on the
            # first quiet poll, so a genuinely idle session — the
            # sweeper's only targets — pays one probe, not the grace.)
            grace = time.monotonic() + 0.25
            while True:
                with self._lock:
                    active = sess._active_requests
                if not active:
                    break
                if time.monotonic() >= grace:
                    raise SessionBusy(
                        f"session {sid!r} has requests in flight"
                    )
                time.sleep(0.005)
            # the snapshot build + disk write happen OUTSIDE the manager
            # lock: only this session's transitions (and its passes, via
            # the schedule lock) wait on them
            t0 = time.monotonic()
            path, _ = self._write_session_snapshot(sess, 0.0, force=False)
            with self._lock:
                if sess._active_requests or sess.last_touch >= t0:
                    # a request routed in (or completed) while we were
                    # snapshotting: the doc may miss its write — stay
                    # live, leave the stale file to be overwritten
                    raise SessionBusy(
                        f"session {sid!r} was touched mid-snapshot"
                    )
                sess.snapshot_path = path
                sess.service = None
                sess.state = "evicted"
                self.evictions += 1
            return path

    def _write_session_snapshot(
        self, sess: Session, wait_s: float, *, force: bool
    ) -> "tuple[str, bool]":
        """The ONE quiesce-and-snapshot sequence evict and drain share
        (call under `sess._state_lock`): wait up to `wait_s` for the
        session's pass boundary (the schedule lock), build the
        checkpoint document, atomically persist it, and remember the
        path on the session. `force=False` (eviction) REFUSES when the
        boundary can't be taken — eviction is optional load shedding;
        `force=True` (drain) snapshots anyway — the process is about to
        exit, and a bounded drain beats a hung one; a FORCED snapshot
        may capture a still-resolving pass's partial write-backs (the
        price of the bound — raise KSS_DRAIN_DEADLINE_S where strict
        pass atomicity matters more than drain time). Returns
        (path, got_pass_boundary)."""
        lock = sess.service.scheduler._schedule_lock
        got = (
            lock.acquire(timeout=wait_s)
            if wait_s > 0
            else lock.acquire(blocking=False)
        )
        if not got and not force:
            raise SessionBusy(f"session {sess.id!r} has a pass in flight")
        try:
            doc = self._session_doc(sess)
        finally:
            if got:
                lock.release()
        path = os.path.join(self.snapshot_dir(), f"{sess.id}.json")
        write_checkpoint(doc, path)
        sess.snapshot_path = path
        # the snapshot IS the journal up to its rv: rebase the journal
        # and refresh the replication base to the new document
        with self._lock:
            j = self._journals.get(sess.id)
            if j is not None:
                self._repl_base[sess.id] = doc
        if j is not None:
            j.rebase(int((doc.get("store") or {}).get("rv", 0)))
        return path, got

    def _restore(self, sess: Session) -> None:
        """Under sess._state_lock (NOT the manager lock): disk load +
        service rebuild, then a brief manager-lock window to go live."""
        doc = load_checkpoint(sess.snapshot_path, SESSION_CHECKPOINT_FORMAT)
        live_doc = doc
        if self.journal_enabled:
            tail = durability.read_journal(
                durability.journal_path(self.snapshot_dir(), sess.id),
                int((doc.get("store") or {}).get("rv", 0)),
            )
            if tail:
                # acknowledged writes the snapshot missed (a crash-kill's
                # local journal tail, or a transport-shipped journal):
                # replay BEFORE the service exists, so controllers and
                # the scheduler never re-fire on journaled mutations
                live_doc = dict(doc)
                live_doc["store"] = durability.replay_store_state(
                    doc.get("store") or {}, tail
                )
        service = self._service_from_doc(sess.id, sess, live_doc)
        if self.journal_enabled:
            # subscribe before the session goes live: no unjournaled gap
            # between the restore and the next acknowledged write. The
            # base stays the ON-DISK document (not the replayed state),
            # so base + journal remains the session's exact history.
            self._arm_journal(sess, base_doc=doc, service=service)
        with self._lock:
            sess.service = service
            sess.state = "live"
            sess.restores += 1

    def _session_doc(self, sess: Session) -> dict:
        """The session's checkpoint document — the PR 4 family's
        verbatim-store shape, minus the lifecycle-run bookkeeping a
        serving session doesn't have."""
        svc = sess.service
        try:
            cfg = svc.scheduler.get_config()
        except SchedulerServiceDisabled:
            cfg = None
        return {
            "format": SESSION_CHECKPOINT_FORMAT,
            "id": sess.id,
            "name": sess.name,
            "createdAt": sess.created_at,
            "store": svc.store.dump_state(),
            "schedulerConfig": cfg,
            "metrics": svc.scheduler.metrics.state_dict(),
            "passSeq": svc.scheduler.pass_seq(),
            "faultInject": sess.fault_spec,
        }

    def _service_from_doc(
        self, sid: str, sess: Session, doc: dict
    ) -> SimulatorService:
        plane = (
            faultinject.FaultPlane.parse(sess.fault_spec)
            if sess.fault_spec
            else None
        )
        service = SimulatorService(
            broker=self.broker, session_id=sid, fault_plane=plane
        )
        service.scheduler.batch_plane = self.batch_plane
        service.store.load_state(doc["store"])
        cfg = doc.get("schedulerConfig")
        if cfg:
            service.scheduler.restart(cfg)
        service.scheduler.metrics.load_state(doc.get("metrics") or {})
        service.scheduler.restore_pass_seq(doc.get("passSeq", 0))
        # reset() now returns to the restored state, not an empty store
        service.store.snapshot_initial()
        return service

    # -- graceful drain (docs/resilience.md) ----------------------------------

    def drain(self, deadline_s: "float | None" = None) -> dict:
        """The zero-loss drain path: mark the plane draining (the HTTP
        layer sheds new requests with the structured 503 and `/readyz`
        reports the distinct ``draining`` state), stop the idle
        sweeper, then snapshot EVERY live session — the default
        included — through the ``kss-session-checkpoint/v1`` path.
        In-flight requests AND passes get until `deadline_s` (default
        ``KSS_DRAIN_DEADLINE_S``) to finish — new ones are already shed
        at the HTTP layer, so this drains to quiescence, and an
        acknowledged write is always IN the snapshot (the same
        `_active_requests` guard eviction uses). Past the deadline the
        session is snapshotted anyway (`forced` in the result) — the
        store is internally consistent, and an unresolved pass has
        acknowledged nothing. Finally the shared broker is quiesced
        (speculation off, in-flight background builds out-waited: the
        PR 4 atexit-abort hazard, now handled on the orderly path).
        Idempotent; returns a summary the drain route serves."""
        deadline_total = (
            self.drain_deadline_s if deadline_s is None else float(deadline_s)
        )
        with self._lock:
            self.draining = True
            sessions = [
                s
                for s in sorted(
                    self._sessions.values(), key=lambda s: s.created_at
                )
                if s.state == "live" and s.service is not None
            ]
        self._stop.set()  # the idle sweeper must not race the snapshots
        if self.batch_plane is not None:
            # flush partially-filled collection windows NOW: in-flight
            # passes waiting out a batch window would otherwise pad the
            # drain by up to one window each, and new enrollments shed
            # straight to solo dispatch (server/batchplane.py)
            self.batch_plane.begin_drain()
        deadline = time.monotonic() + deadline_total
        drained: list[str] = []
        forced: list[str] = []
        errors: dict[str, str] = {}
        for sess in sessions:
            # per-session containment: one tenant's failed snapshot (a
            # serialization bug, a transient disk error) must not skip
            # every remaining tenant's snapshot or the broker quiesce —
            # it is recorded, surfaced in the result, and makes the
            # drain read as FAILED to the exit path (server/__main__.py)
            try:
                with sess._state_lock:
                    with self._lock:
                        if self._sessions.get(sess.id) is not sess:
                            continue  # raced with delete
                        if sess.state != "live" or sess.service is None:
                            continue  # evicted meanwhile: already on disk
                    # wait out requests already routed INTO the session
                    # (`using` registrations): their 200s must be in
                    # the snapshot — the same guard eviction enforces,
                    # here bounded by the drain deadline, not refused
                    quiesced = True
                    while True:
                        with self._lock:
                            active = sess._active_requests
                        if not active:
                            break
                        if time.monotonic() >= deadline:
                            quiesced = False
                            break
                        time.sleep(0.01)
                    remaining = max(0.0, deadline - time.monotonic())
                    _, got = self._write_session_snapshot(
                        sess, remaining, force=True
                    )
            except Exception as e:  # noqa: BLE001 — contained per session
                errors[sess.id] = f"{type(e).__name__}: {e}"
                continue
            drained.append(sess.id)
            if not got or not quiesced:
                forced.append(sess.id)
            with self._lock:
                self.drained += 1
        if self.replication is not None:
            # the at-drain ship (docs/fleet.md): successors hold every
            # session's FINAL state before this process exits
            try:
                self.replication.ship_once()
            except Exception:  # noqa: BLE001 — drain must complete
                pass
        self.broker.quiesce(timeout=max(0.0, deadline - time.monotonic()))
        result: dict = {
            "drainedSessions": drained,
            "forced": forced,
            "snapshotDir": self.snapshot_dir(),
        }
        if errors:
            result["errors"] = errors
        return result

    def adopt_snapshots(self) -> list[str]:
        """Register every ``kss-session-checkpoint/v1`` document found
        in the snapshot directory — what a previous process's drain (or
        idle eviction) left behind. The default session's state is
        restored INTO the live default service (and its file consumed);
        every other snapshot becomes an evicted session that restores
        transparently on first touch. Unreadable files are skipped —
        boot must not die on a stray artifact."""
        with self._lock:
            d = self._snapshot_dir
        if not d or not os.path.isdir(d):
            return []
        adopted: list[str] = []
        for fn in sorted(os.listdir(d)):
            if not fn.endswith(".json"):
                continue
            path = os.path.join(d, fn)
            try:
                doc = load_checkpoint(path, SESSION_CHECKPOINT_FORMAT)
            except (ValueError, OSError):
                continue
            sid = doc.get("id") or fn[: -len(".json")]
            with self._lock:
                if sid == DEFAULT_SESSION_ID:
                    svc = self._sessions[DEFAULT_SESSION_ID].service
                    svc.store.load_state(doc["store"])
                    cfg = doc.get("schedulerConfig")
                    if cfg:
                        try:
                            svc.scheduler.restart(cfg)
                        except SchedulerServiceDisabled:
                            pass
                    svc.scheduler.metrics.load_state(doc.get("metrics") or {})
                    svc.scheduler.restore_pass_seq(doc.get("passSeq", 0))
                    svc.store.snapshot_initial()
                    # an armed default journal re-bases onto the adopted
                    # document (its subscription on the live store rides
                    # through load_state unchanged)
                    j = self._journals.get(sid)
                    if j is not None:
                        self._repl_base[sid] = doc
                        j.rebase(int((doc.get("store") or {}).get("rv", 0)))
                    os.unlink(path)  # consumed: the live service IS the state
                else:
                    if sid in self._sessions:
                        continue
                    sess = Session(sid, doc.get("name") or sid, None)
                    sess.state = "evicted"
                    sess.snapshot_path = path
                    sess.fault_spec = doc.get("faultInject")
                    created = doc.get("createdAt")
                    if created is not None:
                        sess.created_at = float(created)
                    self._sessions[sid] = sess
            adopted.append(sid)
        # orphan journals — a crash-killed process's sessions that never
        # reached their first snapshot: synthesize the empty base their
        # journal is relative to, so the replay on first touch brings
        # back every acknowledged write (the default session's orphan
        # tail replays at arm time instead: its live service IS the
        # empty base)
        if self.journal_enabled:
            for fn in sorted(os.listdir(d)):
                if not fn.endswith(durability.JOURNAL_SUFFIX):
                    continue
                sid = fn[: -len(durability.JOURNAL_SUFFIX)]
                if sid == DEFAULT_SESSION_ID:
                    continue
                with self._lock:
                    if sid in self._sessions:
                        continue
                if not durability.read_journal(os.path.join(d, fn), 0):
                    continue
                path = write_checkpoint(
                    {
                        "format": SESSION_CHECKPOINT_FORMAT,
                        "id": sid,
                        "name": sid,
                        "createdAt": time.time(),
                        "store": {"rv": 0, "objects": {}},
                        "schedulerConfig": None,
                        "metrics": {},
                        "passSeq": 0,
                        "faultInject": None,
                    },
                    os.path.join(d, f"{sid}.json"),
                )
                with self._lock:
                    if sid in self._sessions:
                        continue
                    sess = Session(sid, sid, None)
                    sess.state = "evicted"
                    sess.snapshot_path = path
                    self._sessions[sid] = sess
                adopted.append(sid)
        return adopted

    # -- the fleet durability plane (server/durability.py, docs/fleet.md) -----

    def _arm_journal(
        self,
        sess: Session,
        base_doc: "dict | None" = None,
        fresh: bool = False,
        service: "SimulatorService | None" = None,
    ) -> None:
        """Attach the write-ahead journal to a session's store. Re-arming
        onto the SAME store (the default session re-adopting a snapshot)
        rebases the existing journal in place, keeping its subscription;
        a new service gets a new journal over the same FILE — kept,
        because its tail may hold acknowledged writes no snapshot has
        (`fresh=True`, the brand-new-session path, truncates instead).
        `service` overrides `sess.service` for the restore path, which
        arms before the session flips live."""
        svc = service if service is not None else sess.service
        if not self.journal_enabled or svc is None:
            return
        if base_doc is None:
            base_doc = self._session_doc(sess)
        base_rv = int((base_doc.get("store") or {}).get("rv", 0))
        with self._lock:
            old = self._journals.get(sess.id)
            old_store = self._journal_stores.get(sess.id)
        if old is not None and old_store is svc.store:
            old.rebase(base_rv)
            with self._lock:
                self._repl_base[sess.id] = base_doc
            return
        j = durability.SessionJournal(
            durability.journal_path(self.snapshot_dir(), sess.id),
            base_rv=base_rv,
            sync=self.journal_sync,
        )
        if fresh:
            j.rebase(base_rv)  # truncate a stale file from a prior life
        if self.journal_sync:

            def _hook(entry, _sid=sess.id):
                self._ship_entry(_sid, entry)

            j.on_append = _hook
        with self._lock:
            if old is not None:
                appended, byts = old.counters()
                self._journal_appends_retired += appended
                self._journal_bytes_retired += byts
            self._journals[sess.id] = j
            self._journal_stores[sess.id] = svc.store
            self._repl_base[sess.id] = base_doc
        svc.store.subscribe(j.record)

    def _ship_entry(self, sid: str, entry: dict) -> None:
        """The sync-journal hook: ship one acknowledged mutation to the
        ring successors BEFORE the ack returns (server/replication.py).
        A failed ship degrades to the next full-unit round — it never
        fails the acknowledgment."""
        plane = self.replication
        if plane is None:
            return
        try:
            plane.ship_entry(sid, entry)
        except Exception:  # noqa: BLE001 — the ack must not fail
            pass

    def set_replication(self, plane) -> None:
        """Wire the successor shipper (the HTTP server does, right after
        construction — before any fleet traffic arrives)."""
        self.replication = plane

    def journal_stats(self) -> dict:
        with self._lock:
            js = list(self._journals.values())
            appends = self._journal_appends_retired
            byts = self._journal_bytes_retired
            doc = {
                "armed": self.journal_enabled,
                "sync": self.journal_sync,
                "adoptedUnits": self.adopted_units,
                "storedReplicas": self.stored_replicas,
                "rejectedUnits": self.rejected_units,
                "promotedReplicas": self.promoted_replicas,
            }
        for j in js:
            a, b = j.counters()
            appends += a
            byts += b
        doc["journals"] = len(js)
        doc["appends"] = appends
        doc["bytes"] = byts
        return doc

    def replication_unit(self, sid: str) -> "dict | None":
        """The digest-guarded transport unit `sid` travels as: the
        cached base document plus the journal entries past it — no
        quiesce on the hot path (the base is immutable, the journal
        append-only). Sessions without a journal fall back to their
        on-disk snapshot (evicted) or a pass-boundary snapshot (live)."""
        with self._lock:
            sess = self._sessions.get(sid)
            j = self._journals.get(sid)
            base = self._repl_base.get(sid)
            state = sess.state if sess is not None else None
            path = sess.snapshot_path if sess is not None else None
        if sess is None:
            return None
        if j is not None and base is not None:
            return durability.build_unit(sid, base, j.entries())
        if state == "evicted" and path and os.path.exists(path):
            try:
                doc = load_checkpoint(path, SESSION_CHECKPOINT_FORMAT)
            except (ValueError, OSError):
                return None
            entries = durability.read_journal(
                durability.journal_path(self.snapshot_dir(), sid),
                int((doc.get("store") or {}).get("rv", 0)),
            )
            return durability.build_unit(sid, doc, entries)
        # live and unjournaled: a best-effort cut at the pass boundary
        with sess._state_lock:
            with self._lock:
                if self._sessions.get(sid) is not sess:
                    return None
            if sess.state != "live" or sess.service is None:
                return None
            lock = sess.service.scheduler._schedule_lock
            got = lock.acquire(timeout=1.0)
            try:
                doc = self._session_doc(sess)
            finally:
                if got:
                    lock.release()
        return durability.build_unit(sid, doc, [])

    def held_replicas(self) -> "list[str]":
        """Session ids this worker passively holds replicas for."""
        with self._lock:
            d = self._snapshot_dir
        if not d:
            return []
        rd = durability.replica_dir(d)
        if not os.path.isdir(rd):
            return []
        return sorted(
            fn[: -len(".json")]
            for fn in os.listdir(rd)
            if fn.endswith(".json")
        )

    def checkpoint_index(self) -> dict:
        """GET /api/v1/admin/checkpoints: every session this worker can
        hand over (id + payload digest), plus the replicas it holds for
        its ring predecessors — the router's transport inventory."""
        checkpoints = []
        for sid in self.session_ids():
            unit = self.replication_unit(sid)
            if unit is None:
                continue
            checkpoints.append(
                {
                    "id": sid,
                    "sha256": unit["sha256"],
                    "journalEntries": len(unit.get("journal") or []),
                }
            )
        replicas = []
        for sid in self.held_replicas():
            dpath, jpath = durability.replica_paths(self.snapshot_dir(), sid)
            try:
                doc = load_checkpoint(dpath, SESSION_CHECKPOINT_FORMAT)
            except (ValueError, OSError):
                continue
            replicas.append(
                {
                    "id": sid,
                    "sha256": canonical_digest(doc),
                    "journalEntries": len(
                        durability.read_journal(
                            jpath,
                            int((doc.get("store") or {}).get("rv", 0)),
                        )
                    ),
                }
            )
        return {"checkpoints": checkpoints, "replicas": replicas}

    def checkpoint_unit(self, sid: str) -> "dict | None":
        """GET /api/v1/admin/checkpoints/<sid>: the session's transport
        unit, whether held as a session or as a replica."""
        unit = self.replication_unit(sid)
        if unit is not None:
            return unit
        dpath, jpath = durability.replica_paths(self.snapshot_dir(), sid)
        if not os.path.exists(dpath):
            return None
        try:
            doc = load_checkpoint(dpath, SESSION_CHECKPOINT_FORMAT)
        except (ValueError, OSError):
            return None
        return durability.build_unit(
            sid,
            doc,
            durability.read_journal(
                jpath, int((doc.get("store") or {}).get("rv", 0))
            ),
        )

    def receive_checkpoints(self, units, *, replica: bool = False) -> dict:
        """POST /api/v1/admin/adopt with body-carried checkpoints: the
        cross-host transport's receive side. Every unit is digest-
        verified (`durability.verify_unit` — a torn transfer is rejected,
        never adopted) and lands atomically (tmp + rename). `replica`
        stores units passively under ``<dir>/replicas/`` for a later
        promote; otherwise the journal replays into the document and the
        session is adopted. Re-pushing a unit for a session already here
        is an idempotent duplicate, not an error — the router may retry."""
        adopted: list[str] = []
        stored: list[str] = []
        duplicate: list[str] = []
        rejected: "dict[str, str]" = {}
        pending_roots: list[str] = []
        for unit in units if isinstance(units, list) else []:
            label = str(
                (unit.get("id") if isinstance(unit, dict) else None) or "?"
            )
            try:
                doc, entries = durability.verify_unit(unit)
            except ValueError as e:
                rejected[label] = str(e)
                continue
            if doc.get("format") != SESSION_CHECKPOINT_FORMAT or not isinstance(
                doc.get("store"), dict
            ):
                rejected[label] = "not a kss-session-checkpoint/v1 document"
                continue
            sid = str(doc.get("id") or label)
            if sid == DEFAULT_SESSION_ID:
                rejected[label] = "the default session is worker-local"
                continue
            if replica:
                dpath, jpath = durability.replica_paths(
                    self.snapshot_dir(), sid
                )
                # MERGE with what sync-mode `journalAppend` bodies
                # already delivered: this unit's journal was cut on the
                # sender BEFORE it travelled, so a blind overwrite could
                # clobber an inline-shipped entry that raced past it —
                # exactly the acknowledged write a crash-kill must keep
                with self._replica_lock:
                    write_checkpoint(doc, dpath)
                    by_rv = {
                        int(e.get("rv", 0)): e
                        for e in durability.read_journal(jpath)
                    }
                    by_rv.update(
                        (int(e.get("rv", 0)), e) for e in entries
                    )
                    durability.write_journal(
                        jpath, [by_rv[rv] for rv in sorted(by_rv)]
                    )
                stored.append(sid)
                continue
            with self._lock:
                known = sid in self._sessions
            if known:
                duplicate.append(sid)  # idempotent re-push
                continue
            merged = durability.replay_into_doc(doc, entries)
            write_checkpoint(
                merged, os.path.join(self.snapshot_dir(), f"{sid}.json")
            )
            pending_roots.append(sid)
        if pending_roots:
            got = set(self.adopt_snapshots())
            for sid in pending_roots:
                if sid in got:
                    adopted.append(sid)
                else:
                    duplicate.append(sid)  # raced with a concurrent adopt
        # distributed tracing (docs/observability.md): adopt/replica
        # landings record the trace id of the request that caused them
        # (the router's re-home or the peer's ship both propagate one)
        for sid in adopted:
            telemetry.instant("fleet.adopt", session=sid, kind="live")
        for sid in stored:
            telemetry.instant("fleet.adopt", session=sid, kind="replica")
        with self._lock:
            self.adopted_units += len(adopted)
            self.stored_replicas += len(stored)
            self.rejected_units += len(rejected)
        return {
            "adopted": adopted,
            "stored": stored,
            "duplicate": duplicate,
            "rejected": rejected,
        }

    def append_replica_journal(self, body: dict) -> dict:
        """POST /api/v1/admin/adopt ``journalAppend`` bodies: the sync-
        replication inline ship. Entries append to the replica journal,
        digest-verified; they fold into the session at promote time."""
        body = body or {}
        sid = str(body.get("id") or "")
        entries = body.get("entries")
        if not sid or not isinstance(entries, list) or not entries:
            raise ValueError("journalAppend requires id + entries")
        if sid == DEFAULT_SESSION_ID:
            raise ValueError("the default session is worker-local")
        claimed = body.get("sha256")
        if claimed and canonical_digest(entries) != claimed:
            raise ValueError(
                "journalAppend digest mismatch: torn transfer, refused"
            )
        _dpath, jpath = durability.replica_paths(self.snapshot_dir(), sid)
        os.makedirs(os.path.dirname(jpath), exist_ok=True)
        with self._replica_lock:
            with open(jpath, "ab") as f:
                for entry in entries:
                    f.write(
                        json.dumps(
                            entry, separators=(",", ":"), sort_keys=True
                        ).encode()
                        + b"\n"
                    )
                if self.journal_sync:
                    f.flush()
                    os.fsync(f.fileno())
        return {"id": sid, "appended": len(entries)}

    def promote_replicas(self, sids: "list[str] | None" = None) -> dict:
        """POST /api/v1/admin/adopt ``promote`` bodies: fold each held
        replica's journal into its document, move it into the root
        snapshot namespace, and adopt it — the router's dead-worker
        re-home when the primary can no longer be asked (docs/fleet.md).
        `sids` None promotes everything held."""
        held = self.held_replicas()
        want = held if sids is None else [str(s) for s in sids]
        promoted: list[str] = []
        missing: list[str] = []
        skipped: list[str] = []
        for sid in want:
            with self._lock:
                if sid in self._sessions:
                    skipped.append(sid)  # already a session here
                    continue
            dpath, jpath = durability.replica_paths(self.snapshot_dir(), sid)
            with self._replica_lock:
                if not os.path.exists(dpath):
                    missing.append(sid)
                    continue
                try:
                    doc = load_checkpoint(dpath, SESSION_CHECKPOINT_FORMAT)
                except (ValueError, OSError):
                    missing.append(sid)
                    continue
                entries = durability.read_journal(
                    jpath, int((doc.get("store") or {}).get("rv", 0))
                )
                merged = durability.replay_into_doc(doc, entries)
                write_checkpoint(
                    merged, os.path.join(self.snapshot_dir(), f"{sid}.json")
                )
                for rp in (dpath, jpath):
                    if os.path.exists(rp):
                        os.unlink(rp)
            promoted.append(sid)
        adopted = set(self.adopt_snapshots()) if promoted else set()
        for sid in promoted:
            # carries the causing request's trace id (the router's
            # dead-worker re-home propagates its context here)
            telemetry.instant("fleet.promote", session=sid)
        with self._lock:
            self.promoted_replicas += len(promoted)
        return {
            "promoted": promoted,
            "adopted": [s for s in promoted if s in adopted],
            "missing": missing,
            "skipped": skipped,
        }

    def _sweep_loop(self) -> None:
        interval = max(0.05, min(self.idle_evict_s / 4.0, 5.0))
        while not self._stop.wait(interval):
            now = time.monotonic()
            with self._lock:
                idle = [
                    s.id
                    for s in self._sessions.values()
                    if s.state == "live"
                    and s.id != DEFAULT_SESSION_ID
                    and now - s.last_touch >= self.idle_evict_s
                ]
            for sid in idle:
                try:
                    self.evict(sid)
                except (SessionBusy, UnknownSession):
                    pass  # busy or raced with delete: next round

    def shutdown(self) -> None:
        self._stop.set()
        if self.replication is not None:
            self.replication.stop()
        if self.batch_plane is not None:
            self.batch_plane.begin_drain()
        if self._sweeper is not None:
            self._sweeper.join(timeout=2)
