"""Built-in dashboard (L7, reference: web/ — a Nuxt SPA).

The reference ships a full Vue frontend; the same workflows are served
as a single static page straight from the simulator (no build step, no
dependencies), consuming only the public API:

  * watch the cluster live (`/api/v1/listwatchresources` ND-JSON),
  * browse ALL seven resource kinds in tabs (reference
    web/components/ResourceViews/ResourcesViewPanel.vue),
  * author resources: create from the reference's creation templates
    (web/components/lib/templates/*.yaml, embedded below), edit any
    object as YAML, delete — the Monaco-editor workflow collapsed to a
    textarea + the server's YAML body support,
  * pods bucketed per node in the node detail (web/store/pod.ts:12-57),
  * inspect per-pod scheduling results (the per-plugin filter/score
    tables from the result annotations),
  * trigger scheduling, edit the scheduler configuration,
    export / import / reset,
  * watch fleet health live (the Observability tab): sparklines over
    `/api/v1/timeseries` (the fleet & memory observatory's retained
    window, `KSS_FLEET_STATS=1`) fed by the `/api/v1/events` SSE
    stream's `fleet` + `metrics` events — docs/observability.md.

Routes consumed:

    GET  /                    this page
    GET  /api/v1/resources/<kind>[/<ns>/<name>[?format=yaml]]
    POST /api/v1/resources/<kind>          (JSON or YAML body)
    DELETE /api/v1/resources/<kind>/...
    GET  /api/v1/listwatchresources        live updates (ND-JSON stream)
    POST /api/v1/schedule[?mode=gang], PUT /api/v1/reset,
    GET/POST /api/v1/schedulerconfiguration, GET /api/v1/export,
    POST /api/v1/import
"""

from __future__ import annotations

# Creation templates — the reference's web/components/lib/templates/*.yaml
# verbatim in spirit (generateName + a schedulable default shape); the
# store implements the apiserver's generateName suffixing.
TEMPLATES = {
    "nodes": """\
metadata:
  generateName: node-
  labels: {}
spec: {}
status:
  capacity:
    cpu: "4"
    memory: 32Gi
    pods: "110"
  allocatable:
    cpu: "4"
    memory: 32Gi
    pods: "110"
""",
    "pods": """\
metadata:
  generateName: pod-
  namespace: default
  labels: {}
spec:
  containers:
    - name: pause
      image: registry.k8s.io/pause:3.5
      resources:
        requests:
          cpu: 100m
          memory: 128Mi
  restartPolicy: Always
""",
    "pvs": """\
metadata:
  generateName: pv-
  labels: {}
spec:
  capacity:
    storage: 1Gi
  volumeMode: Filesystem
  accessModes:
    - ReadWriteOnce
  persistentVolumeReclaimPolicy: Delete
  hostPath:
    path: /tmp/data
    type: DirectoryOrCreate
""",
    "pvcs": """\
metadata:
  generateName: pvc-
  namespace: default
spec:
  accessModes:
    - ReadWriteOnce
  volumeMode: Filesystem
  resources:
    requests:
      storage: 1Gi
""",
    "storageclasses": """\
metadata:
  generateName: local-storageclass-
provisioner: kubernetes.io/no-provisioner
""",
    "priorityclasses": """\
metadata:
  generateName: priority-class-
value: 1000
globalDefault: false
description: "This is a template priority class for all pods"
""",
    "namespaces": """\
metadata:
  generateName: namespace-
  labels: {}
""",
}

import json as _json

from ..sched.config import default_plugins as _default_plugins

_TEMPLATES_JS = _json.dumps(TEMPLATES)
# the v1.26 default score set seeds the per-plugin weight editor when the
# active config leaves `.score.enabled` empty (defaults implied)
_SCORE_DEFAULTS_JS = _json.dumps(
    [
        {"name": p["name"], "weight": int(p.get("weight") or 1)}
        for p in _default_plugins()["score"]
    ]
)

PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>kube-scheduler-simulator-tpu</title>
<style>
 body{font-family:system-ui,sans-serif;margin:1.2rem;background:#fafafa;color:#222}
 h1{font-size:1.2rem} h2{font-size:1rem;margin:.8rem 0 .3rem}
 table{border-collapse:collapse;width:100%;background:#fff;font-size:.85rem}
 th,td{border:1px solid #ddd;padding:.25rem .5rem;text-align:left}
 th{background:#f0f0f0} tr:hover td{background:#f6f9ff;cursor:pointer}
 #bar button,#tabs button,#editorpane button{margin-right:.4rem}
 #status{color:#666;font-size:.8rem}
 #tabs{margin:.8rem 0 .4rem}
 #tabs button{background:#eee;border:1px solid #ccc;padding:.25rem .6rem;
   border-radius:.3rem;cursor:pointer}
 #tabs button.active{background:#dce8ff;border-color:#88a}
 #detail{white-space:pre-wrap;background:#fff;border:1px solid #ddd;
         padding:.6rem;font-family:monospace;font-size:.75rem;max-height:40vh;
         overflow:auto}
 #editorpane{display:none;border:1px solid #bbb;background:#fff;
   padding:.6rem;margin:.6rem 0}
 #editor{width:100%;height:16rem;font-family:monospace;font-size:.78rem}
 #editerr{color:#b00;font-size:.8rem;white-space:pre-wrap}
 #cfg{width:100%;height:10rem;font-family:monospace;font-size:.75rem}
 .pill{display:inline-block;padding:0 .4rem;border-radius:.6rem;font-size:.75rem}
 .ok{background:#d9f2dd}.bad{background:#f8d7da}.pend{background:#fff3cd}
 .del{color:#b00;cursor:pointer}
 #obspane{display:none}
 .spark{display:inline-block;margin:.3rem .4rem .3rem 0;border:1px solid #ddd;
   background:#fff;padding:.3rem .5rem;vertical-align:top}
 .spark b{font-size:.75rem;display:block}
 .spark .sv{font-size:.8rem;color:#357}
 .hint{color:#888;font-size:.75rem}
</style></head><body>
<h1>kube-scheduler-simulator-tpu</h1>
<div id="bar">
 <button onclick="act('POST','/api/v1/schedule')">Schedule</button>
 <button onclick="act('POST','/api/v1/schedule?mode=gang')">Schedule (gang)</button>
 <button onclick="act('PUT','/api/v1/reset')">Reset</button>
 <button onclick="exportSnap()">Export</button>
 <button onclick="document.getElementById('importfile').click()">Import</button>
 <input type="file" id="importfile" style="display:none"
        onchange="importSnap(this.files[0])">
 <span id="status">connecting…</span>
</div>
<div id="tabs"></div>
<div>
 <button id="newbtn" onclick="newResource()">New</button>
 <span id="count"></span>
</div>
<table id="grid"><thead></thead><tbody></tbody></table>
<div id="obspane">
 <button id="obsbtn" onclick="toggleObs()">Start live telemetry</button>
 <span id="obsstat" class="hint"></span>
 <div id="sparks"></div>
 <div class="hint">sparklines: seeded from /api/v1/timeseries (the fleet
 &amp; memory observatory's retained window, KSS_FLEET_STATS=1), then live
 from the /api/v1/events SSE stream (<code>fleet</code> +
 <code>metrics</code> events)</div>
 <h2>Alerts</h2>
 <table id="alerttable"><thead><tr><th>objective</th><th>session</th>
  <th>state</th><th>burn fast / slow</th><th>last transition</th></tr>
 </thead><tbody></tbody></table>
 <span id="alertstat" class="hint"></span>
 <div class="hint">SLO burn-rate alerts (KSS_SLO=1 or a PUT /api/v1/slo
 override): seeded from /api/v1/alerts, then live from the SSE stream's
 <code>alert</code> events &mdash; pending &rarr; firing &rarr; resolved</div>
 <h2>Recent requests</h2>
 <table id="reqtable"><thead><tr><th>time</th><th>route</th><th>worker</th>
  <th>status</th><th>attempts</th><th>breaker</th>
  <th>total / net / worker / router (ms)</th><th>trace</th></tr>
 </thead><tbody></tbody></table>
 <span id="reqstat" class="hint"></span>
 <div class="hint">the fleet router's per-request ring
 (/api/v1/fleet/requests): attempt counts + the latency split per proxied
 request; trace ids join the merged /api/v1/debug/trace Perfetto export
 when KSS_TRACE=1 (docs/observability.md)</div>
</div>
<div id="editorpane">
 <b id="edtitle"></b><br>
 <textarea id="editor" spellcheck="false"></textarea><br>
 <button onclick="saveResource()">Save</button>
 <button id="delbtn" onclick="deleteResource()">Delete</button>
 <button onclick="closeEditor()">Cancel</button>
 <div id="editerr"></div>
</div>
<h2>Detail</h2>
<div id="detail">click a pod row to inspect its per-plugin results; click a
node row for its pods</div>
<h2>Scheduler configuration</h2>
<div id="weights">
 <b>Score plugin weights</b>
 <table id="wtable"><thead><tr><th>plugin</th><th>weight</th></tr></thead>
 <tbody></tbody></table>
 <button onclick="applyWeights()">Apply weights</button>
 <span class="hint">writes .profiles[0].plugins.score.enabled and
 re-applies the configuration</span>
</div>
<textarea id="cfg"></textarea><br>
<button onclick="applyCfg()">Apply configuration</button>
<script>
const TEMPLATES = __TEMPLATES__;
// kind key -> watch wire name + table spec (reference
// ResourceViews/ResourcesViewPanel.vue covers the same seven kinds)
const KINDS = {
  nodes:{wire:'nodes',title:'Nodes',one:'Node',ns:false,
    cols:['name','cpu','memory','pods bound'],
    row:n=>{const al=(n.status||{}).allocatable||{};
      return [n.metadata.name,al.cpu||'',al.memory||'',
              podsByNode().get(n.metadata.name)?.length||0];}},
  pods:{wire:'pods',title:'Pods',one:'Pod',ns:true,
    cols:['namespace','name','node','result'],
    row:p=>{const node=(p.spec||{}).nodeName||'';
      const ann=(p.metadata||{}).annotations||{};
      const has=Object.keys(ann).some(k=>k.startsWith('scheduler-simulator/'));
      const pill=node?'<span class="pill ok">scheduled</span>'
        :(has?'<span class="pill bad">unschedulable</span>'
              :'<span class="pill pend">pending</span>');
      return [p.metadata.namespace||'default',p.metadata.name,node,
              {html:pill}];}},
  pvs:{wire:'persistentvolumes',title:'PVs',one:'PV',ns:false,
    cols:['name','capacity','phase','claim'],
    row:v=>{const sp=v.spec||{};const cr=sp.claimRef||{};
      return [v.metadata.name,(sp.capacity||{}).storage||'',
              (v.status||{}).phase||'',
              cr.name?((cr.namespace||'default')+'/'+cr.name):''];}},
  pvcs:{wire:'persistentvolumeclaims',title:'PVCs',one:'PVC',ns:true,
    cols:['namespace','name','volume','phase'],
    row:c=>[c.metadata.namespace||'default',c.metadata.name,
            (c.spec||{}).volumeName||'',(c.status||{}).phase||'']},
  storageclasses:{wire:'storageclasses',title:'StorageClasses',one:'StorageClass',ns:false,
    cols:['name','provisioner','bindingMode'],
    row:s=>[s.metadata.name,s.provisioner||'',s.volumeBindingMode||'']},
  priorityclasses:{wire:'priorityclasses',title:'PriorityClasses',one:'PriorityClass',ns:false,
    cols:['name','value','globalDefault'],
    row:p=>[p.metadata.name,String(p.value??''),String(p.globalDefault??'')]},
  namespaces:{wire:'namespaces',title:'Namespaces',one:'Namespace',ns:false,
    cols:['name'],row:n=>[n.metadata.name]},
};
const state = {}; for (const k in KINDS) state[k]=new Map();
const wireToKind = {}; for (const k in KINDS) wireToKind[KINDS[k].wire]=k;
let activeKind='nodes';
let editing=null;   // {kind, ns, name} | {kind} for new
const key = o => (o.metadata.namespace||'default')+'/'+o.metadata.name;
const esc = s => String(s??'').replace(/[&<>"']/g,
  c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
const MAX_ROWS = 500;  // full rebuild per tick: cap rendered rows so a
                       // 50k-pod import stays responsive (counts stay exact)
let bucketCache=null;
function podsByNode(){
  if(bucketCache) return bucketCache;
  const m=new Map();  // reference web/store/pod.ts:12-57 bucketing
  for (const p of state.pods.values()){
    const n=(p.spec||{}).nodeName; if(!n) continue;
    if(!m.has(n)) m.set(n,[]); m.get(n).push(p);
  }
  bucketCache=m; return m;
}
function renderTabs(){
  const t=document.getElementById('tabs'); t.innerHTML='';
  for (const k in KINDS){
    const b=document.createElement('button');
    b.textContent=`${KINDS[k].title} (${state[k].size})`;
    if(k===activeKind) b.className='active';
    b.onclick=()=>{activeKind=k; render();};
    t.appendChild(b);
  }
  const ob=document.createElement('button');
  ob.textContent='Observability';
  if(activeKind==='__obs__') ob.className='active';
  ob.onclick=()=>{activeKind='__obs__'; render();};
  t.appendChild(ob);
}
function render(){
  bucketCache=null;
  renderTabs();
  const obsActive=activeKind==='__obs__';
  document.getElementById('obspane').style.display=obsActive?'block':'none';
  document.getElementById('grid').style.display=obsActive?'none':'';
  document.getElementById('newbtn').style.display=obsActive?'none':'';
  if(obsActive){
    document.getElementById('count').textContent='';
    if(!obsSource) startObs();
    drawSparks();
    return;
  }
  const spec=KINDS[activeKind];
  document.querySelector('#grid thead').innerHTML=
    '<tr>'+spec.cols.map(c=>`<th>${esc(c)}</th>`).join('')+'<th></th></tr>';
  const tb=document.querySelector('#grid tbody'); tb.innerHTML='';
  const objs=[...state[activeKind].values()].sort((a,b)=>key(a)<key(b)?-1:1);
  for (const o of objs.slice(0,MAX_ROWS)){
    const tr=document.createElement('tr');
    tr.innerHTML=spec.row(o).map(c=>
      c&&c.html!==undefined?`<td>${c.html}</td>`:`<td>${esc(c)}</td>`
    ).join('')+'<td><span class="del">delete</span></td>';
    tr.onclick=(ev)=>{
      if(ev.target.classList.contains('del')){deleteRow(activeKind,o);return;}
      if(activeKind==='pods') showPodDetail(o);
      else if(activeKind==='nodes') showNodeDetail(o);
      editResource(activeKind,o);
    };
    tb.appendChild(tr);
  }
  const over=state[activeKind].size>MAX_ROWS?` (showing first ${MAX_ROWS})`:'';
  document.getElementById('count').textContent=
    `${state[activeKind].size} ${spec.title}${over}`;
  document.getElementById('newbtn').textContent=`New ${spec.one}`;
}
function showPodDetail(p){
  const ann=(p.metadata||{}).annotations||{};
  const out={};
  for (const [k,v] of Object.entries(ann)){
    if(!k.startsWith('scheduler-simulator/')) continue;
    try{out[k]=JSON.parse(v);}catch(e){out[k]=v;}
  }
  document.getElementById('detail').textContent=
    key(p)+'\\n'+JSON.stringify(out,null,2);
}
function showNodeDetail(n){
  const pods=podsByNode().get(n.metadata.name)||[];
  document.getElementById('detail').textContent=
    `node ${n.metadata.name}: ${pods.length} pod(s)\\n`+
    pods.map(p=>'  '+key(p)).join('\\n');
}
function resourcePath(kind,o){
  const ns=(o.metadata.namespace||'default');
  return KINDS[kind].ns
    ?`/api/v1/resources/${kind}/${ns}/${o.metadata.name}`
    :`/api/v1/resources/${kind}/${o.metadata.name}`;
}
function newResource(){
  editing={kind:activeKind};
  document.getElementById('edtitle').textContent=
    `New ${KINDS[activeKind].one} (YAML)`;
  document.getElementById('editor').value=TEMPLATES[activeKind]||'metadata:\\n  name: \\n';
  document.getElementById('delbtn').style.display='none';
  document.getElementById('editerr').textContent='';
  document.getElementById('editorpane').style.display='block';
}
async function editResource(kind,o){
  editing={kind, ns:o.metadata.namespace||'default', name:o.metadata.name};
  document.getElementById('edtitle').textContent=
    `${kind}/${o.metadata.name} (YAML)`;
  document.getElementById('editerr').textContent='';
  try{
    const r=await fetch(resourcePath(kind,o)+'?format=yaml');
    document.getElementById('editor').value=await r.text();
  }catch(e){
    document.getElementById('editor').value='';
    document.getElementById('editerr').textContent='load failed: '+e;
  }
  document.getElementById('delbtn').style.display='';
  document.getElementById('editorpane').style.display='block';
}
async function saveResource(){
  if(!editing) return;
  const body=document.getElementById('editor').value;
  // edits of an existing object REPLACE it (item-path PUT: fields
  // removed in the editor are removed from the object); creation goes
  // through the collection's apply
  const r=editing.name
    ?await fetch(resourcePath(editing.kind,
        {metadata:{namespace:editing.ns,name:editing.name}}),
        {method:'PUT',headers:{'Content-Type':'application/yaml'},body})
    :await fetch(`/api/v1/resources/${editing.kind}`,
        {method:'POST',headers:{'Content-Type':'application/yaml'},body});
  if(r.ok){closeEditor(); setStatus('saved');}
  else{document.getElementById('editerr').textContent=
    `save → ${r.status} `+await r.text();}
}
async function deleteResource(){
  if(!editing||!editing.name) return;
  await deleteRow(editing.kind,
    {metadata:{namespace:editing.ns,name:editing.name}});
  closeEditor();
}
async function deleteRow(kind,o){
  const r=await fetch(resourcePath(kind,o),{method:'DELETE'});
  setStatus(`delete ${kind}/${o.metadata.name} → ${r.status}`);
}
function closeEditor(){
  editing=null;
  document.getElementById('editorpane').style.display='none';
}
async function act(method,path){
  try{
    const r=await fetch(path,{method});
    setStatus(`${method} ${path} → ${r.status}`);
  }catch(e){setStatus(`${method} ${path} failed: ${e}`);}
}
async function exportSnap(){
  try{
    const r=await fetch('/api/v1/export'); const blob=await r.blob();
    const a=document.createElement('a');
    a.href=URL.createObjectURL(blob); a.download='snapshot.json'; a.click();
  }catch(e){setStatus('export failed: '+e);}
}
async function importSnap(file){
  if(!file) return;
  const r=await fetch('/api/v1/import',{method:'POST',body:await file.text()});
  setStatus('import → '+r.status+(r.ok?'':' '+await r.text()));
}
const SCORE_DEFAULTS = __SCORE_DEFAULTS__;
function effectiveScoreSet(cfg){
  // the active enabled list when present, else the v1.26 defaults
  // (an empty enabled list means "defaults implied", the reference's
  // own conversion semantics)
  try{
    const en=((((cfg.profiles||[])[0]||{}).plugins||{}).score||{}).enabled||[];
    if(en.length) return en.map(p=>({name:p.name,weight:p.weight||1}));
  }catch(e){}
  return SCORE_DEFAULTS.map(p=>({name:p.name,weight:p.weight}));
}
function renderWeights(cfg){
  const tb=document.querySelector('#wtable tbody'); tb.innerHTML='';
  for(const p of effectiveScoreSet(cfg)){
    const tr=document.createElement('tr');
    tr.innerHTML='<td>'+esc(p.name)+'</td><td><input type="number" '+
      'min="0" max="100" data-plugin="'+esc(p.name)+'" value="'+
      esc(p.weight)+'"></td>';
    tb.appendChild(tr);
  }
}
async function applyWeights(){
  let cfg;
  try{ cfg=JSON.parse(document.getElementById('cfg').value); }
  catch(e){ setStatus('apply weights: config box is not valid JSON — '+e);
            return; }
  cfg.profiles=cfg.profiles&&cfg.profiles.length?cfg.profiles:[{}];
  const prof=cfg.profiles[0];
  prof.plugins=prof.plugins||{};
  prof.plugins.score=prof.plugins.score||{};
  prof.plugins.score.disabled=[{name:'*'}];
  // weight 0 REMOVES the plugin from scoring (the min="0" affordance)
  prof.plugins.score.enabled=[...document.querySelectorAll(
    '#wtable input')].map(i=>{const w=parseInt(i.value,10);
      return {name:i.dataset.plugin,weight:isNaN(w)?1:w};})
    .filter(p=>p.weight>0);
  document.getElementById('cfg').value=JSON.stringify(cfg,null,2);
  await applyCfg();
}
async function loadCfg(){
  try{
    const r=await fetch('/api/v1/schedulerconfiguration');
    const cfg=await r.json();
    document.getElementById('cfg').value=JSON.stringify(cfg,null,2);
    renderWeights(cfg);
  }catch(e){setStatus('config load failed: '+e);}
}
async function applyCfg(){
  const r=await fetch('/api/v1/schedulerconfiguration',
    {method:'POST',body:document.getElementById('cfg').value});
  setStatus('apply config → '+r.status+(r.ok?'':' '+await r.text()));
  if(r.ok) loadCfg();
}
function setStatus(s){document.getElementById('status').textContent=s;}
// --- the Observability tab (docs/observability.md): sparklines seeded
// from GET /api/v1/timeseries (the fleet & memory observatory's ring)
// and fed live by the /api/v1/events SSE stream's `fleet` + `metrics`
// events — cluster health as a time-series, not an end-of-run snapshot
const OBS_SERIES={
  pendingPods:{title:'pending pods'},
  utilizationMax:{title:'node utilization (max)'},
  utilizationMean:{title:'node utilization (mean)'},
  fragmentationIndex:{title:'fragmentation index'},
  hbmBytesInUse:{title:'device memory in use'},
  decisionsPerSecond:{title:'decisions/s'},
};
const obsData={}; for(const k in OBS_SERIES) obsData[k]=[];
const OBS_POINTS=120;
let obsSource=null;
let obsLastSeq=-1;  // dedupe: seed fetch vs live events may overlap
function obsPush(k,v){
  if(v===null||v===undefined||isNaN(v)) return;
  const a=obsData[k]; a.push(Number(v)); if(a.length>OBS_POINTS) a.shift();
}
function obsFromFleet(s){
  if(s.seq!==undefined){
    if(s.seq<=obsLastSeq) return;
    obsLastSeq=s.seq;
  }
  const f=s.fleet||{};
  obsPush('pendingPods',f.pendingPods);
  obsPush('utilizationMax',(f.utilization||{}).max);
  obsPush('utilizationMean',(f.utilization||{}).mean);
  obsPush('fragmentationIndex',f.fragmentationIndex);
  const hbm=(s.hbm||{}).bytesInUse;
  obsPush('hbmBytesInUse',hbm!==undefined?hbm:(s.buffers||{}).liveBytes);
}
function obsFromMetrics(m){obsPush('decisionsPerSecond',m.decisionsPerSecond);}
function fmtVal(v){
  if(Math.abs(v)>=1073741824) return (v/1073741824).toFixed(2)+' GiB';
  if(Math.abs(v)>=1048576) return (v/1048576).toFixed(1)+' MiB';
  if(Math.abs(v)<10&&v!==Math.round(v)) return v.toFixed(3);
  return String(Math.round(v*100)/100);
}
function drawSparks(){
  const host=document.getElementById('sparks');
  for(const k in OBS_SERIES){
    let box=document.getElementById('spark-'+k);
    if(!box){
      box=document.createElement('div'); box.className='spark';
      box.id='spark-'+k;
      box.innerHTML='<b></b><span class="sv"></span>'+
        '<canvas width="180" height="42"></canvas>';
      box.querySelector('b').textContent=OBS_SERIES[k].title;
      host.appendChild(box);
    }
    const data=obsData[k];
    box.querySelector('.sv').textContent=
      data.length?fmtVal(data[data.length-1]):'\\u2013';
    const c=box.querySelector('canvas'),g=c.getContext('2d');
    g.clearRect(0,0,c.width,c.height);
    if(data.length<2) continue;
    const min=Math.min(...data),max=Math.max(...data),span=(max-min)||1;
    g.strokeStyle='#47a'; g.lineWidth=1.2; g.beginPath();
    data.forEach((v,i)=>{
      const x=i*(c.width-2)/(OBS_POINTS-1)+1;
      const y=c.height-3-((v-min)/span)*(c.height-6);
      i?g.lineTo(x,y):g.moveTo(x,y);
    });
    g.stroke();
  }
}
// --- the Alerts panel: one row per (objective, session), updated by
// the latest transition — seeded from /api/v1/alerts, live from the
// SSE stream's `alert` events (docs/observability.md)
const alertRows=new Map();
function onAlert(ev){
  if(!ev||!ev.objective) return;
  alertRows.set(ev.objective+'|'+(ev.session||'default'),ev);
  drawAlerts();
}
function drawAlerts(){
  const tb=document.querySelector('#alerttable tbody'); tb.innerHTML='';
  const rows=[...alertRows.values()].sort((a,b)=>
    (a.objective+a.session)<(b.objective+b.session)?-1:1);
  for(const ev of rows){
    const cls=ev.state==='firing'?'bad':(ev.state==='pending'?'pend':'ok');
    const bf=Number(ev.burnFast??0), bs=Number(ev.burnSlow??0);
    const tr=document.createElement('tr');
    tr.innerHTML='<td>'+esc(ev.objective)+'</td>'+
      '<td>'+esc(ev.session||'default')+'</td>'+
      '<td><span class="pill '+cls+'">'+esc(ev.state)+'</span></td>'+
      '<td>'+esc(isNaN(bf)?'?':bf.toFixed(1))
      +' / '+esc(isNaN(bs)?'?':bs.toFixed(1))+'</td>'+
      '<td>'+esc(ev.wallTime?new Date(ev.wallTime*1000)
        .toLocaleTimeString():'')+'</td>';
    tb.appendChild(tr);
  }
}
// --- the Recent requests panel: the fleet router's bounded request
// ring (/api/v1/fleet/requests), seeded at start and re-fetched (rate-
// limited) on SSE activity — a worker serving this page directly (no
// router in front) answers 404 and the panel says so
let reqFetchAt=0;
async function fetchRequests(force){
  const now=Date.now();
  if(!force&&now-reqFetchAt<2000) return;
  reqFetchAt=now;
  try{
    const r=await fetch('/api/v1/fleet/requests');
    if(!r.ok){document.getElementById('reqstat').textContent=
      'not behind a fleet router (the ring lives at the router edge)';
      return;}
    const doc=await r.json();
    drawRequests(doc.requests||[]);
    document.getElementById('reqstat').textContent=
      (doc.requests||[]).length+' request(s) in ring'+
      (doc.tracing?' \u00b7 tracing armed'
                  :' \u00b7 KSS_TRACE off: no trace ids');
  }catch(e){document.getElementById('reqstat').textContent='requests: '+e;}
}
function drawRequests(rows){
  const tb=document.querySelector('#reqtable tbody'); tb.innerHTML='';
  const ms=v=>(Number(v||0)*1000).toFixed(1);
  for(const q of rows.slice(-25).reverse()){
    const tr=document.createElement('tr');
    tr.innerHTML='<td>'+esc(q.ts?new Date(q.ts*1000)
        .toLocaleTimeString():'')+'</td>'+
      '<td>'+esc((q.method||'')+' '+(q.route||''))+'</td>'+
      '<td>'+esc(q.worker||'\u2013')+'</td>'+
      '<td>'+esc(q.status==null?'?':q.status)+'</td>'+
      '<td>'+esc(q.attempts)+'</td>'+
      '<td>'+esc(q.breaker||'\u2013')+'</td>'+
      '<td>'+ms(q.totalSeconds)+' / '+ms(q.netSeconds)+' / '+
        ms(q.workerSeconds)+' / '+ms(q.routerSeconds)+'</td>'+
      '<td class="hint">'+esc(q.trace?q.trace.slice(0,8):'\u2013')+'</td>';
    tb.appendChild(tr);
  }
}
async function startObs(){
  if(obsSource) return;
  // connect FIRST, synchronously: the obsSource guard must hold before
  // any await, or a re-click during the seed fetch leaks a second
  // EventSource (one SSE subscriber slot each) and Stop is a no-op
  obsSource=new EventSource('/api/v1/events');
  obsSource.addEventListener('fleet',
    ev=>{obsFromFleet(JSON.parse(ev.data)); drawSparks();
         fetchRequests(false);});
  obsSource.addEventListener('metrics',
    ev=>{obsFromMetrics(JSON.parse(ev.data)); drawSparks();
         fetchRequests(false);});
  obsSource.addEventListener('alert',
    ev=>{onAlert(JSON.parse(ev.data));});
  document.getElementById('obsbtn').textContent='Stop live telemetry';
  try{  // seed history; the seq dedupe keeps live/seed points ordered
    const r=await fetch('/api/v1/timeseries?limit='+OBS_POINTS);
    const doc=await r.json();
    (doc.samples||[]).forEach(obsFromFleet);
    document.getElementById('obsstat').textContent=doc.enabled
      ?`observatory armed \\u00b7 ${doc.emitted} samples recorded`
      :'KSS_FLEET_STATS is off: fleet series idle, metrics series live';
  }catch(e){document.getElementById('obsstat').textContent='timeseries: '+e;}
  try{  // seed the alert table from the history ring
    const r=await fetch('/api/v1/alerts');
    const doc=await r.json();
    (doc.history||[]).forEach(onAlert);
    document.getElementById('alertstat').textContent=doc.enabled
      ?`SLO plane armed \\u00b7 ${doc.counters.fired} alert(s) fired`
      :'SLO plane is off (KSS_SLO=1 or PUT /api/v1/slo to arm)';
  }catch(e){document.getElementById('alertstat').textContent='alerts: '+e;}
  fetchRequests(true);
  drawSparks(); drawAlerts();
}
function stopObs(){
  if(obsSource){obsSource.close(); obsSource=null;}
  document.getElementById('obsbtn').textContent='Start live telemetry';
}
function toggleObs(){obsSource?stopObs():startObs();}
async function watch(){
  while(true){
    try{
      const r=await fetch('/api/v1/listwatchresources');
      const reader=r.body.getReader(); const dec=new TextDecoder();
      let buf=''; setStatus('live');
      for (const k in KINDS) state[k].clear();
      render();  // an empty cluster sends no replay events
      let pending=null;
      for(;;){
        const {done,value}=await reader.read(); if(done) break;
        buf+=dec.decode(value,{stream:true});
        let i;
        while((i=buf.indexOf('\\n'))>=0){
          const line=buf.slice(0,i).trim(); buf=buf.slice(i+1);
          if(!line) continue;
          const ev=JSON.parse(line);
          const kind=wireToKind[ev.Kind]; if(!kind) continue;
          const m=state[kind];
          if(ev.EventType==='DELETED') m.delete(key(ev.Obj));
          else m.set(key(ev.Obj),ev.Obj);
        }
        if(!pending){pending=setTimeout(()=>{pending=null;render();},100);}
      }
    }catch(e){setStatus('stream lost, reconnecting… '+e);}
    await new Promise(res=>setTimeout(res,2000));
  }
}
loadCfg(); watch();
</script></body></html>
""".replace("__TEMPLATES__", _TEMPLATES_JS).replace(
    "__SCORE_DEFAULTS__", _SCORE_DEFAULTS_JS
)
