"""Minimal built-in dashboard (L7, reference: web/ — a Nuxt SPA).

The reference ships a full Vue frontend talking to the simulator API and
the embedded kube-apiserver. Here the same core workflows — watch the
cluster live, inspect per-pod scheduling results (the per-plugin
filter/score tables from the result annotations), trigger scheduling,
edit the scheduler configuration, export/import/reset — are served as a
single static page straight from the simulator (no build step, no
dependencies), consuming only the public API:

    GET  /                    this page
    GET  /api/v1/resources/*  tables
    GET  /api/v1/listwatchresources   live updates (ND-JSON stream)
    POST /api/v1/schedule[?mode=gang], PUT /api/v1/reset,
    GET/POST /api/v1/schedulerconfiguration, GET /api/v1/export
"""

from __future__ import annotations

PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>kube-scheduler-simulator-tpu</title>
<style>
 body{font-family:system-ui,sans-serif;margin:1.2rem;background:#fafafa;color:#222}
 h1{font-size:1.2rem} h2{font-size:1rem;margin:.8rem 0 .3rem}
 table{border-collapse:collapse;width:100%;background:#fff;font-size:.85rem}
 th,td{border:1px solid #ddd;padding:.25rem .5rem;text-align:left}
 th{background:#f0f0f0} tr:hover td{background:#f6f9ff;cursor:pointer}
 #bar button{margin-right:.4rem} #status{color:#666;font-size:.8rem}
 #detail{white-space:pre-wrap;background:#fff;border:1px solid #ddd;
         padding:.6rem;font-family:monospace;font-size:.75rem;max-height:40vh;
         overflow:auto}
 #cfg{width:100%;height:10rem;font-family:monospace;font-size:.75rem}
 .pill{display:inline-block;padding:0 .4rem;border-radius:.6rem;font-size:.75rem}
 .ok{background:#d9f2dd}.bad{background:#f8d7da}.pend{background:#fff3cd}
</style></head><body>
<h1>kube-scheduler-simulator-tpu</h1>
<div id="bar">
 <button onclick="act('POST','/api/v1/schedule')">Schedule</button>
 <button onclick="act('POST','/api/v1/schedule?mode=gang')">Schedule (gang)</button>
 <button onclick="act('PUT','/api/v1/reset')">Reset</button>
 <button onclick="exportSnap()">Export</button>
 <span id="status">connecting…</span>
</div>
<h2>Nodes (<span id="nnodes">0</span>)</h2>
<table id="nodes"><thead><tr><th>name</th><th>cpu</th><th>memory</th>
<th>pods bound</th></tr></thead><tbody></tbody></table>
<h2>Pods (<span id="npods">0</span>)</h2>
<table id="pods"><thead><tr><th>namespace</th><th>name</th><th>node</th>
<th>result</th></tr></thead><tbody></tbody></table>
<h2>Pod scheduling detail</h2>
<div id="detail">click a pod row to inspect its per-plugin results</div>
<h2>Scheduler configuration</h2>
<textarea id="cfg"></textarea><br>
<button onclick="applyCfg()">Apply configuration</button>
<script>
const state = {nodes:new Map(), pods:new Map()};
const key = o => (o.metadata.namespace||'default')+'/'+o.metadata.name;
const esc = s => String(s??'').replace(/[&<>"']/g,
  c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
const MAX_ROWS = 500;  // full rebuild per tick: cap rendered rows so a
                       // 50k-pod import stays responsive (counts stay exact)
function render(){
  const nb = document.querySelector('#nodes tbody'); nb.innerHTML='';
  const counts = {};
  for (const p of state.pods.values()){
    const n = (p.spec||{}).nodeName; if(n) counts[n]=(counts[n]||0)+1;
  }
  const nodesSorted=[...state.nodes.values()].sort((a,b)=>key(a)<key(b)?-1:1);
  for (const n of nodesSorted.slice(0,MAX_ROWS)){
    const al=(n.status||{}).allocatable||{};
    nb.insertAdjacentHTML('beforeend',`<tr><td>${esc(n.metadata.name)}</td>
      <td>${esc(al.cpu||'')}</td><td>${esc(al.memory||'')}</td>
      <td>${counts[n.metadata.name]||0}</td></tr>`);
  }
  document.getElementById('nnodes').textContent=state.nodes.size;
  const pb = document.querySelector('#pods tbody'); pb.innerHTML='';
  const podsSorted=[...state.pods.values()].sort((a,b)=>key(a)<key(b)?-1:1);
  for (const p of podsSorted.slice(0,MAX_ROWS)){
    const node=(p.spec||{}).nodeName||'';
    const ann=(p.metadata||{}).annotations||{};
    const has=Object.keys(ann).some(k=>k.startsWith('scheduler-simulator/'));
    const pill=node?'<span class="pill ok">scheduled</span>'
      :(has?'<span class="pill bad">unschedulable</span>'
            :'<span class="pill pend">pending</span>');
    const row=document.createElement('tr');
    row.innerHTML=`<td>${esc(p.metadata.namespace||'default')}</td>
      <td>${esc(p.metadata.name)}</td><td>${esc(node)}</td><td>${pill}</td>`;
    row.onclick=()=>showDetail(p);
    pb.appendChild(row);
  }
  const over=state.pods.size>MAX_ROWS?` (showing first ${MAX_ROWS})`:'';
  document.getElementById('npods').textContent=state.pods.size+over;
}
function showDetail(p){
  const ann=(p.metadata||{}).annotations||{};
  const out={};
  for (const [k,v] of Object.entries(ann)){
    if(!k.startsWith('scheduler-simulator/')) continue;
    try{out[k]=JSON.parse(v);}catch(e){out[k]=v;}
  }
  document.getElementById('detail').textContent=
    key(p)+'\\n'+JSON.stringify(out,null,2);
}
async function act(method,path){
  try{
    const r=await fetch(path,{method});
    setStatus(`${method} ${path} → ${r.status}`);
  }catch(e){setStatus(`${method} ${path} failed: ${e}`);}
}
async function exportSnap(){
  try{
    const r=await fetch('/api/v1/export'); const blob=await r.blob();
    const a=document.createElement('a');
    a.href=URL.createObjectURL(blob); a.download='snapshot.json'; a.click();
  }catch(e){setStatus('export failed: '+e);}
}
async function loadCfg(){
  try{
    const r=await fetch('/api/v1/schedulerconfiguration');
    document.getElementById('cfg').value=JSON.stringify(await r.json(),null,2);
  }catch(e){setStatus('config load failed: '+e);}
}
async function applyCfg(){
  const r=await fetch('/api/v1/schedulerconfiguration',
    {method:'POST',body:document.getElementById('cfg').value});
  setStatus('apply config → '+r.status+(r.ok?'':' '+await r.text()));
  if(r.ok) loadCfg();
}
function setStatus(s){document.getElementById('status').textContent=s;}
async function watch(){
  while(true){
    try{
      const r=await fetch('/api/v1/listwatchresources');
      const reader=r.body.getReader(); const dec=new TextDecoder();
      let buf=''; setStatus('live');
      state.nodes.clear(); state.pods.clear();
      render();  // an empty cluster sends no replay events
      let pending=null;
      for(;;){
        const {done,value}=await reader.read(); if(done) break;
        buf+=dec.decode(value,{stream:true});
        let i;
        while((i=buf.indexOf('\\n'))>=0){
          const line=buf.slice(0,i).trim(); buf=buf.slice(i+1);
          if(!line) continue;
          const ev=JSON.parse(line);
          const m=ev.Kind==='nodes'?state.nodes:
                  ev.Kind==='pods'?state.pods:null;
          if(!m) continue;
          if(ev.EventType==='DELETED') m.delete(key(ev.Obj));
          else m.set(key(ev.Obj),ev.Obj);
        }
        if(!pending){pending=setTimeout(()=>{pending=null;render();},100);}
      }
    }catch(e){setStatus('stream lost, reconnecting… '+e);}
    await new Promise(res=>setTimeout(res,2000));
  }
}
loadCfg(); watch();
</script></body></html>
"""
