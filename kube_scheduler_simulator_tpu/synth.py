"""Synthetic cluster generators for benchmarks and the graft entry point.

Mirrors the workload shapes in BASELINE.json's configs (100 pods × 10
nodes … 100k pods × 10k nodes): heterogeneous node capacities, mixed pod
sizes, optional priorities/taints — all Mi-granular so the 32-bit TPU
dtype policy is exact (engine/encode.py TPU32).
"""

from __future__ import annotations

import random


def synthetic_cluster(
    n_nodes: int,
    n_pods: int,
    seed: int = 0,
    *,
    priorities: bool = False,
) -> tuple[list[dict], list[dict]]:
    rng = random.Random(seed)
    nodes = []
    for i in range(n_nodes):
        cores = rng.choice([4, 8, 16, 32, 64])
        nodes.append(
            {
                "metadata": {"name": f"node-{i}"},
                "status": {
                    "allocatable": {
                        "cpu": str(cores),
                        "memory": f"{cores * 4}Gi",
                        "pods": "110",
                    }
                },
            }
        )
    pods = []
    for i in range(n_pods):
        cpu_m = rng.choice([100, 250, 500, 1000, 2000])
        mem_mi = rng.choice([128, 256, 512, 1024, 2048])
        spec: dict = {
            "containers": [
                {
                    "name": "c",
                    "resources": {
                        "requests": {"cpu": f"{cpu_m}m", "memory": f"{mem_mi}Mi"}
                    },
                }
            ]
        }
        if priorities and rng.random() < 0.3:
            spec["priority"] = rng.randint(0, 100)
        pods.append(
            {
                "metadata": {"name": f"pod-{i}", "namespace": "default"},
                "spec": spec,
            }
        )
    return nodes, pods


def synthetic_affinity_cluster(
    n_nodes: int,
    n_pods: int,
    seed: int = 0,
    *,
    replicas_per_service: int = 10,
) -> tuple[list[dict], list[dict]]:
    """InterPodAffinity-heavy workload (BASELINE config #3): pods grouped
    into services whose replicas carry required ANTI-affinity to their own
    service on the hostname topology (the classic spread-replicas rule —
    an anti-affinity chain per service), and a third of services carry
    required affinity to the previous service on the zone topology
    (co-location chains across services)."""
    rng = random.Random(seed)
    nodes = []
    for i in range(n_nodes):
        cores = rng.choice([8, 16, 32])
        nodes.append(
            {
                "metadata": {
                    "name": f"node-{i}",
                    "labels": {
                        "kubernetes.io/hostname": f"node-{i}",
                        "topology.kubernetes.io/zone": f"z{i % 8}",
                    },
                },
                "status": {
                    "allocatable": {
                        "cpu": str(cores),
                        "memory": f"{cores * 4}Gi",
                        "pods": "110",
                    }
                },
            }
        )
    pods = []
    n_services = max(1, n_pods // replicas_per_service)
    for i in range(n_pods):
        svc = i % n_services
        labels = {"app": f"svc-{svc}"}
        anti = {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {
                    "labelSelector": {"matchLabels": {"app": f"svc-{svc}"}},
                    "topologyKey": "kubernetes.io/hostname",
                }
            ]
        }
        affinity: dict = {"podAntiAffinity": anti}
        if svc % 3 == 0 and svc > 0:
            # co-locate with the previous service's zone (chain)
            affinity["podAffinity"] = {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "labelSelector": {
                            "matchLabels": {"app": f"svc-{svc - 1}"}
                        },
                        "topologyKey": "topology.kubernetes.io/zone",
                    }
                ]
            }
        pods.append(
            {
                "metadata": {
                    "name": f"pod-{i}",
                    "namespace": "default",
                    "labels": labels,
                },
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "resources": {
                                "requests": {"cpu": "250m", "memory": "256Mi"}
                            },
                        }
                    ],
                    "affinity": affinity,
                },
            }
        )
    return nodes, pods
