"""Synthetic cluster generators for benchmarks and the graft entry point.

Mirrors the workload shapes in BASELINE.json's configs (100 pods × 10
nodes … 100k pods × 10k nodes): heterogeneous node capacities, mixed pod
sizes, optional priorities/taints — all Mi-granular so the 32-bit TPU
dtype policy is exact (engine/encode.py TPU32).
"""

from __future__ import annotations

import random


def synthetic_cluster(
    n_nodes: int,
    n_pods: int,
    seed: int = 0,
    *,
    priorities: bool = False,
) -> tuple[list[dict], list[dict]]:
    rng = random.Random(seed)
    nodes = []
    for i in range(n_nodes):
        cores = rng.choice([4, 8, 16, 32, 64])
        nodes.append(
            {
                "metadata": {"name": f"node-{i}"},
                "status": {
                    "allocatable": {
                        "cpu": str(cores),
                        "memory": f"{cores * 4}Gi",
                        "pods": "110",
                    }
                },
            }
        )
    pods = []
    for i in range(n_pods):
        cpu_m = rng.choice([100, 250, 500, 1000, 2000])
        mem_mi = rng.choice([128, 256, 512, 1024, 2048])
        spec: dict = {
            "containers": [
                {
                    "name": "c",
                    "resources": {
                        "requests": {"cpu": f"{cpu_m}m", "memory": f"{mem_mi}Mi"}
                    },
                }
            ]
        }
        if priorities and rng.random() < 0.3:
            spec["priority"] = rng.randint(0, 100)
        pods.append(
            {
                "metadata": {"name": f"pod-{i}", "namespace": "default"},
                "spec": spec,
            }
        )
    return nodes, pods
