from .quantity import parse_quantity, format_quantity, Quantity

__all__ = ["parse_quantity", "format_quantity", "Quantity"]
