"""Subprocess environment for escaping the single-chip TPU shim.

This build image pins every Python process to one real TPU chip through a
sitecustomize shim (env: AXON*/PALLAS_AXON* + a PYTHONPATH site dir).
Plain ``JAX_PLATFORMS=cpu`` does NOT escape it — backend init hangs — so
anything that needs a real CPU backend in a subprocess (the multi-chip
virtual-mesh dryrun, the benchmark's wedged-tunnel fallback) must scrub
the shim env first. This is the single definition of that scrub; keep the
shim's env contract knowledge here only.
"""

from __future__ import annotations

import os
import threading

# default device-init watchdog window, shared by every entry point
# (bench, __graft_entry__, library callers)
PROBE_TIMEOUT_S = 180.0


def probe_devices(
    timeout_s: float = PROBE_TIMEOUT_S,
    get_devices=None,
) -> tuple[list, "BaseException | None"]:
    """Discover jax.devices() under a watchdog (a wedged TPU tunnel hangs
    even device enumeration — the observed failure mode this guards).

    Returns (devices, error): a non-empty device list on success; an
    empty list with the probe's exception when backend init *failed*; an
    empty list and None when it *hung* past the timeout (the daemon
    thread is abandoned — it must not block process exit).

    `get_devices` overrides the enumeration (default: import jax and
    call jax.devices()) so the hang/fail paths are unit-testable against
    a fake wedged backend without a real one (tests/test_axonenv.py)."""
    out: list = []
    err: list = []

    def probe():
        try:
            if get_devices is not None:
                out.extend(get_devices())
            else:
                import jax

                out.extend(jax.devices())
        except BaseException as e:  # noqa: BLE001 — reported to caller
            err.append(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    return list(out), (err[0] if err else None)


def probe_why(error: "BaseException | None", timeout_s: float) -> str:
    """The shared wording for an unusable accelerator backend."""
    if error is not None:
        return f"device init failed: {error!r}"
    return f"device init hung >{timeout_s:.0f}s"


def reexec_on_cpu(label: str, marker_env: str, argv: list[str], why: str):
    """Replace this process with the same program on the scrubbed CPU
    backend (the shared probe-failed response of bench and the serving
    shell). The notice goes to stderr AND is flushed first — execve
    replaces the image without flushing stdio, so a block-buffered
    stdout (docker/systemd pipes) would silently eat the only signal
    that the process degraded to the CPU backend. `marker_env` guards
    against re-exec loops: callers skip the probe when they see it, and
    this function REFUSES to re-exec when the marker is already present
    in the current environment — a probe that fails even on the
    scrubbed CPU backend must surface as an error, not an execve storm
    (the documented contract; previously only the caller-side half
    existed)."""
    import sys

    if os.environ.get(marker_env):
        raise RuntimeError(
            f"{label}: probe failed on the CPU-fallback re-exec too "
            f"({why}); refusing a re-exec loop ({marker_env} is set)"
        )
    sys.stderr.write(f"{label}: {why}; re-exec on CPU backend\n")
    sys.stderr.flush()
    env = scrubbed_cpu_env()
    env[marker_env] = "1"
    os.execve(sys.executable, argv, env)


def scrubbed_cpu_env(
    base: "dict[str, str] | None" = None,
    *,
    virtual_devices: "int | None" = None,
) -> dict[str, str]:
    """A copy of `base` (default os.environ) with the TPU shim removed and
    JAX pinned to the CPU backend. `virtual_devices` adds the
    xla_force_host_platform_device_count flag for an n-device virtual
    mesh."""
    env = {
        k: v
        for k, v in (os.environ if base is None else base).items()
        if not k.startswith(("AXON", "PALLAS_AXON", "_AXON"))
    }
    env["PYTHONPATH"] = os.pathsep.join(
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and ".axon_site" not in p
    )
    env["JAX_PLATFORMS"] = "cpu"
    if virtual_devices is not None:
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")
        ]
        flags.append(
            f"--xla_force_host_platform_device_count={virtual_devices}"
        )
        env["XLA_FLAGS"] = " ".join(flags)
    return env
