"""CompileBroker — the one owner of engine compilation on the serving path.

BENCH_r05 put the steady-state cost where the kernels no longer are: a
full-default-set compile is ~30 s against a ~0.03 s warm pass, and every
shape-bucket crossing in a churn run re-paid that compile *synchronously
on the request thread*. The broker turns compilation into a managed,
predictable resource with three jobs:

  1. **Dedupe** — concurrent requests for the same (program, bucket) key
     resolve to ONE build: the first caller compiles, everyone else
     blocks on the in-flight build and shares the result (unit-tested:
     two threads, one compile).
  2. **Persistent-cache routing** — every engine jit in the repo goes
     through `broker.jit`, which arms the repo-local persistent XLA
     compile cache (utils/compilecache.py) before the first lowering, so
     repeat compiles of identical programs are disk hits across
     processes and sessions.
  3. **Prediction** — `speculate()` runs compile work on a background
     worker thread. The serving layer arms it when live object counts
     drift past a watermark of the current shape bucket
     (`adjacent_bucket_targets`, default 80%), so a bucket crossing
     finds a warm executable in the broker instead of stalling the
     request thread for the full XLA compile.

Accounting (surfaced through `SchedulingMetrics.record_compile` into the
`/api/v1/metrics` phases block and the bench headline):

  * ``compileHits``           — requests served from the warm-engine map
                                (including waits on an in-flight build:
                                the caller did not compile);
  * ``compileMisses``         — request-thread builds (the synchronous
                                compile the tentpole eliminates from the
                                steady state);
  * ``speculativeCompiles``   — background builds completed;
  * ``stallSeconds``          — request-thread seconds blocked on any
                                compile (own miss builds + in-flight
                                waits).

``KSS_NO_SPECULATIVE_COMPILE=1`` disables the background worker for
deterministic profiling (docs/performance.md); dedupe and the warm-engine
map stay on.
"""

from __future__ import annotations

import os
import threading
import time

from .compilecache import enable_compile_cache, shape_bucket

_jit_cache_armed = False


def jit(fn, **kw):
    """`jax.jit` with the persistent compile cache armed first — the
    single jit entry point for the engines (engine/engine.py,
    engine/gang.py, parallel/sweep.py, engine/extender_loop.py), so every
    program they lower is eligible for cross-process disk-cache hits."""
    global _jit_cache_armed
    import jax

    if not _jit_cache_armed:
        # respect an entry point that already armed the cache (conftest,
        # bench) — re-arming would reset its min-compile-time threshold
        if not jax.config.jax_compilation_cache_dir:
            enable_compile_cache()
        _jit_cache_armed = True
    return jax.jit(fn, **kw)


def speculation_enabled_default() -> bool:
    """Speculative background compilation default: on, unless the
    profiling kill switch KSS_NO_SPECULATIVE_COMPILE is set."""
    return os.environ.get("KSS_NO_SPECULATIVE_COMPILE", "").lower() not in (
        "1", "true", "yes",
    )


def adjacent_bucket_targets(
    live: int, bucket: int, *, lo: int = 8, up_frac: float = 0.8
) -> list[int]:
    """The shape buckets worth pre-compiling for, given `live` objects in
    the current `bucket`: the next power-of-two UP once occupancy passes
    the watermark (default 80% — arrivals will cross soon), and the next
    bucket DOWN once the live count would fit it with the same headroom
    (shrink passes re-encode at the smaller bucket). Empty when the count
    sits comfortably inside its bucket — the steady state arms nothing."""
    if bucket <= 0 or live < 0:
        return []
    out: list[int] = []
    if live >= up_frac * bucket:
        out.append(bucket * 2)
    half = bucket // 2
    if half >= lo and live <= up_frac * half:
        out.append(half)
    return out


class _Inflight:
    """One in-progress build: waiters block on `ev`. When it fires,
    `engine` is the built engine — or None, meaning the builder failed
    and the waiter should retry the build itself (`get`'s loop)."""

    __slots__ = ("ev", "engine")

    def __init__(self):
        self.ev = threading.Event()
        self.engine = None


class CompileBroker:
    """Warm-engine map + in-flight dedupe + background speculation.

    Keys are opaque tuples (the serving layer uses
    ``(kind, compile_signature, ...)``); values are compiled engine
    instances the caller `retarget`s onto fresh encodings. STRICTLY one
    broker per `SchedulerService`: engines are stateful (`retarget`
    mutates them), and only the owning service's pass lock serializes
    their use — sharing a broker across services would let one service's
    retarget corrupt another's in-flight pass.
    """

    def __init__(
        self,
        metrics=None,
        capacity: int = 8,
        speculative: "bool | None" = None,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.metrics = metrics
        self.capacity = capacity
        self.speculative = (
            speculation_enabled_default() if speculative is None else bool(speculative)
        )
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._engines: "dict[tuple, object]" = {}  # LRU via dict order
        self._inflight: "dict[tuple, _Inflight]" = {}
        self._tokens: set = set()  # speculation dedupe (queued/running)
        self._tasks: list = []
        self._worker: "threading.Thread | None" = None
        self._busy = 0  # speculation tasks queued or running
        # local counters (mirrored into self.metrics when present)
        self.compile_hits = 0
        self.compile_misses = 0
        self.speculative_compiles = 0
        self.stall_seconds = 0.0

    # -- accounting ---------------------------------------------------------

    def _note(self, hits=0, misses=0, speculative=0, stall_s=0.0) -> None:
        with self._lock:
            self.compile_hits += hits
            self.compile_misses += misses
            self.speculative_compiles += speculative
            self.stall_seconds += stall_s
        if self.metrics is not None:
            self.metrics.record_compile(
                hits=hits, misses=misses, speculative=speculative, stall_s=stall_s
            )

    def stats(self) -> dict:
        with self._lock:
            return {
                "compileHits": self.compile_hits,
                "compileMisses": self.compile_misses,
                "speculativeCompiles": self.speculative_compiles,
                "stallSeconds": round(self.stall_seconds, 6),
            }

    # -- warm-engine map ----------------------------------------------------

    def _store_locked(self, key: tuple, engine) -> None:
        self._engines.pop(key, None)
        self._engines[key] = engine
        while len(self._engines) > self.capacity:
            self._engines.pop(next(iter(self._engines)))

    def peek(self, key: tuple):
        """The cached engine for `key` (no build, no counters), or None."""
        with self._lock:
            return self._engines.get(key)

    def get(self, key: tuple, build, info: "dict | None" = None):
        """The engine for `key`: warm from the map (hit), shared from an
        in-flight build (hit + stall), or built by THIS caller via
        `build()` (miss + stall). `build` must return the engine fully
        compiled — its wall time IS the stall being accounted.

        `info`, when given, is filled with ``{"source": "hit" | "wait" |
        "miss", "wait_s": seconds}`` — `wait_s` is the time THIS caller
        spent blocked on someone else's in-flight compile, which callers
        must exclude from their own execute-phase accounting (it is
        already booked as stallSeconds)."""
        while True:
            with self._lock:
                eng = self._engines.get(key)
                if eng is not None:
                    self._engines[key] = self._engines.pop(key)  # recency
                    mine = None
                else:
                    fl = self._inflight.get(key)
                    if fl is None:
                        fl = _Inflight()
                        self._inflight[key] = fl
                        mine = True
                    else:
                        mine = False
            if mine is None:
                if info is not None:
                    info.update(source="hit", wait_s=0.0)
                self._note(hits=1)
                return eng
            if mine:
                t0 = time.perf_counter()
                try:
                    eng = build()
                except BaseException:
                    with self._lock:
                        self._inflight.pop(key, None)
                    fl.ev.set()  # engine stays None: waiters retry
                    raise
                with self._lock:
                    self._store_locked(key, eng)
                    self._inflight.pop(key, None)
                fl.engine = eng
                fl.ev.set()
                if info is not None:
                    info.update(source="miss", wait_s=0.0)
                self._note(misses=1, stall_s=time.perf_counter() - t0)
                return eng
            # someone else (request thread or speculation worker) is
            # compiling this key: wait and share — no second compile
            t0 = time.perf_counter()
            fl.ev.wait()
            if fl.engine is not None:
                wait_s = time.perf_counter() - t0
                if info is not None:
                    info.update(source="wait", wait_s=wait_s)
                self._note(hits=1, stall_s=wait_s)
                return fl.engine
            # the builder failed; loop — this caller may build it now

    # -- speculation --------------------------------------------------------

    def speculate(self, token, task) -> bool:
        """Queue `task` for the background worker. `task()` runs off the
        request thread and returns ``(key, build)`` — or None to skip —
        after which the worker builds and stores the engine (skipping
        keys already warm or in flight). `token` dedupes while the task
        is queued/running. Returns False when speculation is disabled or
        the token is already pending."""
        if not self.speculative:
            return False
        with self._lock:
            if token in self._tokens:
                return False
            self._tokens.add(token)
            self._tasks.append((token, task))
            self._busy += 1
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._work, name="kss-compile-broker", daemon=True
                )
                self._worker.start()
        return True

    def _work(self) -> None:
        while True:
            with self._lock:
                if not self._tasks:
                    self._worker = None
                    return
                token, task = self._tasks.pop(0)
            try:
                res = task()
                if res is not None:
                    key, build = res
                    self._background_build(key, build)
            except BaseException:  # noqa: BLE001 — speculation never fails a run
                pass
            finally:
                with self._lock:
                    self._tokens.discard(token)
                    self._busy -= 1
                    self._idle.notify_all()

    def _background_build(self, key: tuple, build) -> None:
        with self._lock:
            if key in self._engines or key in self._inflight:
                return  # already warm / being compiled — nothing to do
            fl = _Inflight()
            self._inflight[key] = fl
        try:
            eng = build()
        except BaseException:  # noqa: BLE001
            with self._lock:
                self._inflight.pop(key, None)
            fl.ev.set()  # engine stays None: any waiter retries
            return
        with self._lock:
            self._store_locked(key, eng)
            self._inflight.pop(key, None)
        fl.engine = eng
        fl.ev.set()
        self._note(speculative=1)

    def drain(self, timeout: "float | None" = None) -> bool:
        """Block until the speculation queue is empty and no task is
        running; True on success, False on timeout. The 'after warm-up'
        fence the perf-smoke crossing gate stands on."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while self._busy:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._idle.wait(remaining)
        return True
