"""CompileBroker — the one owner of engine compilation on the serving path.

BENCH_r05 put the steady-state cost where the kernels no longer are: a
full-default-set compile is ~30 s against a ~0.03 s warm pass, and every
shape-bucket crossing in a churn run re-paid that compile *synchronously
on the request thread*. The broker turns compilation into a managed,
predictable resource with three jobs:

  1. **Dedupe** — concurrent requests for the same (program, bucket) key
     resolve to ONE build: the first caller compiles, everyone else
     blocks on the in-flight build and shares the result (unit-tested:
     two threads, one compile).
  2. **Persistent-cache routing** — every engine jit in the repo goes
     through `broker.jit`, which arms the repo-local persistent XLA
     compile cache (utils/compilecache.py) before the first lowering, so
     repeat compiles of identical programs are disk hits across
     processes and sessions.
  3. **Prediction** — `speculate()` runs compile work on a background
     worker thread. The serving layer arms it when live object counts
     drift past a watermark of the current shape bucket
     (`adjacent_bucket_targets`, default 80%), so a bucket crossing
     finds a warm executable in the broker instead of stalling the
     request thread for the full XLA compile.

Accounting (surfaced through `SchedulingMetrics.record_compile` into the
`/api/v1/metrics` phases block and the bench headline):

  * ``compileHits``           — requests served from the warm-engine map
                                (including waits on an in-flight build:
                                the caller did not compile);
  * ``compileMisses``         — request-thread builds (the synchronous
                                compile the tentpole eliminates from the
                                steady state);
  * ``speculativeCompiles``   — background builds completed;
  * ``stallSeconds``          — request-thread seconds blocked on any
                                compile (own miss builds + in-flight
                                waits).

``KSS_NO_SPECULATIVE_COMPILE=1`` disables the background worker for
deterministic profiling (docs/performance.md); dedupe and the warm-engine
map stay on.

Run supervision (the robustness PR, docs/resilience.md): the serving
layer reaches the broker through `get_resilient`, which adds the compile
WATCHDOG + DEGRADATION LADDER on top of `get`'s dedupe:

  * each build attempt runs under a deadline (``KSS_COMPILE_DEADLINE_S``;
    0/unset = no watchdog) — a wedged XLA compile can't be interrupted
    from Python, so the watchdog abandons the compile thread and treats
    the attempt as failed (the detached thread's late result is
    discarded);
  * failed/timed-out attempts retry with exponential backoff
    (``KSS_COMPILE_RETRIES`` more attempts, base ``KSS_COMPILE_BACKOFF_S``),
    each retry counted as ``compileRetries``;
  * a key whose ladder is exhausted enters a COOLDOWN
    (``KSS_COMPILE_COOLDOWN_PASSES`` calls served degraded without
    re-paying the deadline+retry storm), and `CompileUnavailable` tells
    the caller to run the pass eagerly (`eager_execution` makes
    `broker.jit` a pass-through, so the same engine pass executes
    un-jitted — slow, but it completes);
  * the fault plane (utils/faultinject.py) wires into the build attempt
    (``compile_slow`` / ``compile_fail``) and the speculative worker
    (``worker_crash``) so every rung is testable on CPU.

A crashed speculative worker no longer dies silently: the crash is
logged once, counted (``brokerWorkerCrashes``), and speculation
self-disables for the broker.
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
import time
import weakref
from contextlib import contextmanager, nullcontext as _null_context

from . import bundles as bundles_mod
from . import envcheck, faultinject, locking, telemetry
from . import ledger as ledger_mod
from .compilecache import enable_compile_cache

_log = logging.getLogger("kube_scheduler_simulator_tpu.broker")

_jit_cache_armed = False

# thread-local eager-execution switch: inside `eager_execution()`, `jit`
# returns the raw function — the degradation ladder's last rung builds
# engines whose every "compiled" program is plain eager JAX
_eager = threading.local()


@contextmanager
def eager_execution():
    """Make `broker.jit` a pass-through on THIS thread for the block:
    engines constructed inside run un-jitted (no XLA compile to fail or
    wedge). Thread-local, so a degraded request never leaks eagerness
    into concurrent passes or the speculation worker."""
    prev = getattr(_eager, "on", False)
    _eager.on = True
    try:
        yield
    finally:
        _eager.on = prev


def eager_active() -> bool:
    return getattr(_eager, "on", False)


def jaxpr_audit_enabled() -> bool:
    """The KSS7xx runtime-audit switch (analysis/jaxpr_audit.py), read
    at JIT-WRAP time — engine construction — like the lock witness's
    creation-time contract."""
    return envcheck.env_truthy(os.environ.get("KSS_JAXPR_AUDIT"))


def jit(fn, audit=None, **kw):
    """`jax.jit` with the persistent compile cache armed first — the
    single jit entry point for the engines (engine/engine.py,
    engine/gang.py, parallel/sweep.py, engine/extender_loop.py), so every
    program they lower is eligible for cross-process disk-cache hits.

    Inside `eager_execution()` this returns `fn` itself (jit kwargs like
    donate_argnums are compile-time hints with no eager meaning): the
    degradation ladder's eager rung.

    `audit` (a dict — keys documented atop analysis/jaxpr_audit.py:
    label/enc/extra_dims/exempt/allow_f64) names and scopes the site
    for the KSS7xx jaxpr auditor; under ``KSS_JAXPR_AUDIT=1`` the
    returned callable audits each new argument signature's ClosedJaxpr
    before executing (docs/static-analysis.md)."""
    global _jit_cache_armed
    if eager_active():
        return fn
    import jax

    if not _jit_cache_armed:
        # respect an entry point that already armed the cache (conftest,
        # bench) — re-arming would reset its min-compile-time threshold
        if not jax.config.jax_compilation_cache_dir:
            enable_compile_cache()
        _jit_cache_armed = True
    jitted = jax.jit(fn, **kw)
    audit_on = jaxpr_audit_enabled()
    ledger_on = ledger_mod.ledger_enabled()
    bundles_on = bundles_mod.bundles_enabled()
    if bundles_on:
        # the AOT bundle store (utils/bundles.py): the first call of
        # each signature deserializes a persisted executable — or
        # AOT-compiles and persists one — instead of letting jit
        # re-lower. When the ledger is also armed, the bundle wrapper
        # IS its dispatch path (it already splits resolve cost into
        # deserialize vs lowering/backend), so the AuditedJit below
        # runs audit-only — two AOT dispatch caches would double-pay
        # every first call.
        jitted = bundles_mod.BundledJit(
            jitted, kw, audit,
            ledger=ledger_mod.LEDGER if ledger_on else None,
        )
    if audit_on or (ledger_on and not bundles_on):
        from ..analysis.jaxpr_audit import AuditedJit

        # ONE wrapper serves both program observers: the KSS7xx audit
        # and the performance ledger (utils/ledger.py) share the
        # first-signature hook and the per-site audit labels
        return AuditedJit(
            jitted,
            kw,
            audit,
            audit_enabled=audit_on,
            ledger=(
                ledger_mod.LEDGER if (ledger_on and not bundles_on) else None
            ),
        )
    return jitted


class CompileDeadlineExceeded(RuntimeError):
    """One build attempt overran KSS_COMPILE_DEADLINE_S (the compile
    thread is abandoned; its late result, if any, is discarded).
    `thread` is the abandoned builder, so the broker can refuse to
    re-probe a key while a previous probe is still stuck in XLA."""

    def __init__(self, msg: str, thread: "threading.Thread | None" = None):
        super().__init__(msg)
        self.thread = thread


class CompileUnavailable(RuntimeError):
    """The compile ladder is exhausted (retries spent or cooldown
    active): the caller must serve the pass another way — the serving
    layer's eager fallback (server/service.py)."""


def _coerce_env_number(raw: str, default, convert, minimum):
    """The shared lenient-knob coercion: malformed or out-of-range
    values fall back to the default — a typo must never disarm a
    ladder. Env READS stay module-local (`_env_number` here, its twin
    in utils/devices.py) so the KSS1xx env-registry analyzer can tie
    each KSS_* name to its reader; only the coercion is shared."""
    try:
        v = convert(raw) if raw else default
    except ValueError:
        return default
    return v if v >= minimum else default


def _env_number(name: str, default, convert, minimum):
    """A ladder knob from the environment (lenient, see
    `_coerce_env_number`)."""
    return _coerce_env_number(os.environ.get(name, ""), default, convert, minimum)


def compile_deadline_s() -> float:
    """Per-attempt compile deadline from KSS_COMPILE_DEADLINE_S; 0 (the
    default) disables the watchdog — no extra thread per compile."""
    return _env_number("KSS_COMPILE_DEADLINE_S", 0.0, float, 0.0)


def compile_retry_limit() -> int:
    """Extra build attempts after the first failure
    (KSS_COMPILE_RETRIES, default 2)."""
    return _env_number("KSS_COMPILE_RETRIES", 2, int, 0)


def compile_backoff_s() -> float:
    """Base of the exponential retry backoff (KSS_COMPILE_BACKOFF_S,
    default 0.05): retry i sleeps base * 2**(i-1)."""
    return _env_number("KSS_COMPILE_BACKOFF_S", 0.05, float, 0.0)


def compile_cooldown_passes() -> int:
    """How many `get_resilient` calls a ladder-exhausted key serves
    degraded before re-probing compilation (KSS_COMPILE_COOLDOWN_PASSES,
    default 3)."""
    return _env_number("KSS_COMPILE_COOLDOWN_PASSES", 3, int, 1)


def cooldown_ttl_s() -> float:
    """Wall-clock bound on how long a cooldown entry may linger UNTOUCHED
    before it expires (KSS_COMPILE_COOLDOWN_TTL_S, default 300, 0 = never).
    Cooldowns drain per `get_resilient` call of their own scope, so a
    tenant that simply stops sending traffic (idle, evicted, abandoned)
    would otherwise pin `health()` — and with it `/api/v1/readyz` — in
    the degraded state forever. An expired entry is pruned; the scope's
    next pass re-probes compilation exactly as a spent cooldown would."""
    return _env_number("KSS_COMPILE_COOLDOWN_TTL_S", 300.0, float, 0.0)


def _call_with_deadline(build, deadline_s: float, make_exc=None,
                        thread_name: str = "kss-compile-attempt"):
    """Run `build()` with a watchdog: on timeout the builder thread is
    abandoned (a wedged XLA compile cannot be interrupted from Python)
    and the timeout exception raises on the caller. The abandoned
    thread's result — engine or exception — is discarded. `make_exc`
    maps the abandoned thread to the exception to raise (default:
    `CompileDeadlineExceeded` carrying the thread); the execution
    ladder's dispatch watchdog (utils/devices.run_with_deadline) reuses
    this machinery with its own exception type."""
    if deadline_s <= 0:
        return build()
    box: dict = {}
    done = threading.Event()

    def runner():
        try:
            box["engine"] = build()
        except BaseException as e:  # noqa: BLE001 — relayed to the caller
            box["error"] = e
        done.set()

    th = threading.Thread(target=runner, name=thread_name, daemon=True)
    th.start()
    if not done.wait(deadline_s):
        if make_exc is not None:
            raise make_exc(th)
        raise CompileDeadlineExceeded(
            f"compile exceeded KSS_COMPILE_DEADLINE_S={deadline_s}s",
            thread=th,
        )
    if "error" in box:
        raise box["error"]
    return box["engine"]


def speculation_enabled_default() -> bool:
    """Speculative background compilation default: on, unless the
    profiling kill switch KSS_NO_SPECULATIVE_COMPILE is set (any truthy
    spelling `envcheck` validates — the two must agree, or a 'validated'
    kill switch silently does nothing)."""
    return not envcheck.env_truthy(
        os.environ.get("KSS_NO_SPECULATIVE_COMPILE")
    )


def adjacent_bucket_targets(
    live: int, bucket: int, *, lo: int = 8, up_frac: float = 0.8
) -> list[int]:
    """The shape buckets worth pre-compiling for, given `live` objects in
    the current `bucket`: the next power-of-two UP once occupancy passes
    the watermark (default 80% — arrivals will cross soon), and the next
    bucket DOWN once the live count would fit it with the same headroom
    (shrink passes re-encode at the smaller bucket). Empty when the count
    sits comfortably inside its bucket — the steady state arms nothing."""
    if bucket <= 0 or live < 0:
        return []
    out: list[int] = []
    if live >= up_frac * bucket:
        out.append(bucket * 2)
    half = bucket // 2
    if half >= lo and live <= up_frac * half:
        out.append(half)
    return out


class _Inflight:
    """One in-progress build: waiters block on `ev`. When it fires,
    `engine` is the built engine — or None, meaning the builder failed
    and the waiter should retry the build itself (`get`'s loop)."""

    __slots__ = ("ev", "engine")

    def __init__(self):
        self.ev = threading.Event()
        self.engine = None


@locking.guard_inferred
class CompileBroker:
    """Warm-engine map + in-flight dedupe + background speculation.

    Keys are opaque tuples (the serving layer uses
    ``(kind, compile_signature, ...)``); values are compiled engine
    instances the caller `retarget`s onto fresh encodings. STRICTLY one
    broker per `SchedulerService`: engines are stateful (`retarget`
    mutates them), and only the owning service's pass lock serializes
    their use — sharing a broker across services would let one service's
    retarget corrupt another's in-flight pass.
    """

    def __init__(
        self,
        metrics=None,
        capacity: int = 8,
        speculative: "bool | None" = None,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.metrics = metrics
        self.capacity = capacity
        self.speculative = (
            speculation_enabled_default() if speculative is None else bool(speculative)
        )
        self._lock = locking.make_lock("broker.lock")
        self._idle = threading.Condition(self._lock)
        self._engines: "dict[tuple, object]" = {}  # LRU via dict order
        self._inflight: "dict[tuple, _Inflight]" = {}
        self._tokens: set = set()  # speculation dedupe (queued/running)
        self._tasks: list = []
        self._worker: "threading.Thread | None" = None
        self._busy = 0  # speculation tasks queued or running
        # degradation ladder: keys whose compile ladder is exhausted →
        # remaining get_resilient calls served degraded without retrying.
        # Keyed (scope, key): with a SHARED broker (the session plane),
        # each session's ladder exhaustion cools down that session only
        # — the bulkhead that keeps one tenant's storm from degrading
        # its neighbors' identical-shape compiles (docs/sessions.md).
        # Values are (remaining passes, last-touch monotonic stamp): the
        # stamp lets health() expire entries whose scope stopped issuing
        # passes (cooldown_ttl_s) instead of reporting not-ready forever
        self._cooldown: "dict[tuple, tuple[int, float]]" = {}
        # watchdog-abandoned builder threads per (scope, key): while any
        # is still alive (a truly wedged XLA compile), re-probing would
        # leak ANOTHER stuck thread every cooldown cycle — the probe is
        # refused instead, bounding the leak at one batch per key
        self._abandoned: "dict[tuple, list[threading.Thread]]" = {}
        # per-key engine leases: warm engines are STATEFUL (retarget
        # mutates them), so callers sharing this broker across services
        # hold the key's lease for the whole dispatch→finish window of a
        # pass (server/service.py). Bounded by shape diversity, like the
        # warm map's keyspace.
        self._leases: "dict[tuple, threading.RLock]" = {}
        # speculative crashes drawn from a SESSION-scoped fault plane,
        # per scope: contained (worker survives, health stays ready) but
        # visible — one tenant's chaos must not read as replica sickness
        self._scoped_crashes: "dict[object, int]" = {}
        self._crash_logged = False
        # local counters (mirrored into self.metrics when present)
        self.compile_hits = 0
        self.compile_misses = 0
        self.speculative_compiles = 0
        self.stall_seconds = 0.0
        self.compile_retries = 0
        self.worker_crashes = 0
        # speculations skipped by the HBM headroom gate
        # (KSS_SPEC_MEM_HEADROOM_BYTES, utils/fleetstats.py)
        self.spec_mem_skips = 0
        _live_brokers.add(self)

    # -- accounting ---------------------------------------------------------

    def _note(
        self, hits=0, misses=0, speculative=0, stall_s=0.0,
        retries=0, worker_crashes=0, metrics=None,
    ) -> None:
        """Count into the broker-local aggregates, mirroring into
        `metrics` when given (per-session attribution on a shared
        broker) or `self.metrics` otherwise."""
        with self._lock:
            self.compile_hits += hits
            self.compile_misses += misses
            self.speculative_compiles += speculative
            self.stall_seconds += stall_s
            self.compile_retries += retries
            self.worker_crashes += worker_crashes
            total_stall = self.stall_seconds
        if misses or speculative:
            # cold-start accounting (utils/ledger.py): the process's
            # first engine compile just completed on this broker
            ledger_mod.COLD_START.mark("firstCompile")
        if stall_s:
            # Perfetto counter track: cumulative request-thread stall
            # alongside the compile spans (no-op when tracing is off)
            telemetry.counter("stallSeconds", total_stall)
        sink = metrics if metrics is not None else self.metrics
        if sink is not None:
            if hits or misses or speculative or stall_s:
                sink.record_compile(
                    hits=hits, misses=misses, speculative=speculative,
                    stall_s=stall_s,
                )
            if retries or worker_crashes:
                sink.record_resilience(
                    retries=retries, worker_crashes=worker_crashes
                )

    def stats(self) -> dict:
        with self._lock:
            return {
                "compileHits": self.compile_hits,
                "compileMisses": self.compile_misses,
                "speculativeCompiles": self.speculative_compiles,
                "stallSeconds": round(self.stall_seconds, 6),
                "compileRetries": self.compile_retries,
                "brokerWorkerCrashes": self.worker_crashes,
                "scopedWorkerCrashes": sum(self._scoped_crashes.values()),
                "speculationMemSkips": self.spec_mem_skips,
            }

    @staticmethod
    def _cooldown_expired(entry: "tuple[int, float]") -> bool:
        ttl = cooldown_ttl_s()
        return ttl > 0 and (time.monotonic() - entry[1]) > ttl

    def _prune_cooldowns_locked(self) -> None:
        """Under self._lock: drop cooldown entries untouched past the
        TTL — their scope stopped issuing passes, so nothing else would
        ever drain them (the next pass of that scope, if one ever comes,
        re-probes compilation like a spent cooldown)."""
        for ck in [
            k for k, e in self._cooldown.items() if self._cooldown_expired(e)
        ]:
            del self._cooldown[ck]

    def health(self) -> dict:
        """The readiness view (`GET /api/v1/readyz`): a broker with any
        key in an active compile cooldown, or whose speculative worker
        has crashed (speculation self-disabled), is DEGRADED — an
        external load balancer should drain the replica rather than
        route fresh tenants at a sick compile plane. Stale cooldowns
        (scope went quiet, `cooldown_ttl_s`) are pruned first: an idle or
        evicted tenant's exhausted ladder must not drain the replica
        forever."""
        with self._lock:
            self._prune_cooldowns_locked()
            cooling = len(self._cooldown)
            stuck = sum(
                1
                for threads in self._abandoned.values()
                if any(t.is_alive() for t in threads)
            )
            return {
                "cooldownKeys": cooling,
                "stuckCompiles": stuck,
                "workerCrashed": self.worker_crashes > 0,
                "speculative": self.speculative,
                "warmEngines": len(self._engines),
            }

    def drop_scope(self, scope) -> None:
        """Purge a deleted session's namespaced ladder state so a dead
        tenant cannot keep health() degraded forever: its cooldown
        entries, its per-scope crash tally, and its dead
        abandoned-builder bookkeeping. A STILL-ALIVE wedged builder
        thread stays visible — that compile is burning a real CPU
        whatever happened to the tenant that started it (health()
        self-clears when the thread finally dies)."""
        with self._lock:
            for ck in [k for k in self._cooldown if k[0] == scope]:
                del self._cooldown[ck]
            self._scoped_crashes.pop(scope, None)
            for ck in list(self._abandoned):
                if ck[0] != scope:
                    continue
                alive = [t for t in self._abandoned[ck] if t.is_alive()]
                if alive:
                    self._abandoned[ck] = alive
                else:
                    del self._abandoned[ck]

    # -- AOT bundle scope ----------------------------------------------------

    def _scoped_build(self, key: tuple, build, metrics=None):
        """Wrap `build` so the engine key and the building service's
        metrics registry ride the AOT-bundle thread-local while it runs
        (utils/bundles.py): every program jit-WRAPPED inside the build
        keys its bundle on the broker key — (kind, compile signature,
        window) + the device-epoch suffix — and every bundle event
        attributes to the right tenant. The wrap is a closure (not a
        with-block here) so the scope follows the build onto whatever
        thread actually runs it (the watchdog's builder thread, the
        speculation worker)."""
        sink = metrics if metrics is not None else self.metrics

        def scoped():
            with bundles_mod.build_scope(key, sink):
                return build()

        return scoped

    # -- warm-engine map ----------------------------------------------------

    def _store_locked(self, key: tuple, engine) -> None:
        self._engines.pop(key, None)
        self._engines[key] = engine
        while len(self._engines) > self.capacity:
            old = next(iter(self._engines))
            self._engines.pop(old)
            # retire the evicted key's lease with it (unless a pass is
            # mid-flight holding it — then the entry stays until the key
            # is rebuilt and evicted again), keeping _leases bounded by
            # the warm map's keyspace instead of lifetime shape diversity
            lk = self._leases.get(old)
            if lk is not None and lk.acquire(blocking=False):
                lk.release()
                del self._leases[old]

    def peek(self, key: tuple):
        """The cached engine for `key` (no build, no counters), or None."""
        with self._lock:
            return self._engines.get(key)

    def lease(self, key: tuple) -> "threading.RLock":
        """The per-key engine lease. Warm engines are stateful (`retarget`
        mutates them in place), so when several services share one broker
        (the session plane), each holds the key's lease across its pass's
        dispatch→finish window — two bucket-compatible tenants share the
        executable, never a concurrent mutation of it. Re-entrant, so a
        single-service broker's uncontended pass costs one lock probe."""
        with self._lock:
            lk = self._leases.get(key)
            if lk is None:
                lk = self._leases[key] = locking.make_rlock("broker.lease")
            return lk

    def get(self, key: tuple, build, info: "dict | None" = None, metrics=None):
        """The engine for `key`: warm from the map (hit), shared from an
        in-flight build (hit + stall), or built by THIS caller via
        `build()` (miss + stall). `build` must return the engine fully
        compiled — its wall time IS the stall being accounted.

        `info`, when given, is filled with ``{"source": "hit" | "wait" |
        "miss", "wait_s": seconds}`` — `wait_s` is the time THIS caller
        spent blocked on someone else's in-flight compile, which callers
        must exclude from their own execute-phase accounting (it is
        already booked as stallSeconds)."""
        build = self._scoped_build(key, build, metrics)
        while True:
            with self._lock:
                eng = self._engines.get(key)
                if eng is not None:
                    self._engines[key] = self._engines.pop(key)  # recency
                    mine = None
                else:
                    fl = self._inflight.get(key)
                    if fl is None:
                        fl = _Inflight()
                        self._inflight[key] = fl
                        mine = True
                    else:
                        mine = False
            if mine is None:
                if info is not None:
                    info.update(source="hit", wait_s=0.0)
                self._note(hits=1, metrics=metrics)
                return eng
            if mine:
                t0 = time.perf_counter()
                try:
                    with telemetry.span("compile.build", key=str(key)):
                        eng = build()
                except BaseException:
                    with self._lock:
                        self._inflight.pop(key, None)
                    fl.ev.set()  # engine stays None: waiters retry
                    raise
                with self._lock:
                    self._store_locked(key, eng)
                    self._inflight.pop(key, None)
                fl.engine = eng
                fl.ev.set()
                if info is not None:
                    info.update(source="miss", wait_s=0.0)
                self._note(
                    misses=1, stall_s=time.perf_counter() - t0, metrics=metrics
                )
                return eng
            # someone else (request thread or speculation worker) is
            # compiling this key: wait and share — no second compile
            t0 = time.perf_counter()
            with telemetry.span("compile.wait", key=str(key)):
                fl.ev.wait()
            if fl.engine is not None:
                wait_s = time.perf_counter() - t0
                if info is not None:
                    info.update(source="wait", wait_s=wait_s)
                self._note(hits=1, stall_s=wait_s, metrics=metrics)
                return fl.engine
            # the builder failed; loop — this caller may build it now

    # -- run supervision (watchdog + degradation ladder) --------------------

    def _attempt_build(self, build):
        """One supervised build attempt: the fault plane's compile sites
        fire inside the watchdog window (an injected compile_slow must
        be able to trip the deadline, exactly like a wedged XLA compile)."""

        def attempt():
            plane = faultinject.active()
            if plane is not None:
                plane.delay("compile_slow")
                plane.maybe_raise("compile_fail")
            return build()

        return _call_with_deadline(attempt, compile_deadline_s())

    def get_resilient(
        self,
        key: tuple,
        build,
        info: "dict | None" = None,
        *,
        metrics=None,
        scope=None,
    ):
        """`get` under run supervision — the serving path's entry point
        (docs/resilience.md). Semantics on top of `get`:

          * each request-thread build attempt runs under the
            KSS_COMPILE_DEADLINE_S watchdog and the fault plane's
            compile sites;
          * a failed/timed-out attempt retries with exponential backoff,
            up to KSS_COMPILE_RETRIES extra attempts (each counted as a
            compileRetry);
          * when the ladder is exhausted the key enters a cooldown of
            KSS_COMPILE_COOLDOWN_PASSES calls and `CompileUnavailable`
            raises — the caller serves the pass eagerly instead
            (`eager_execution`). A speculative background build landing
            the key warm ends the cooldown early.

        `metrics` attributes the hit/miss/stall/retry counters to the
        calling service's registry (defaults to the broker's own);
        `scope` namespaces the cooldown + abandoned-builder state — on a
        SHARED broker each session's ladder exhaustion degrades that
        session only (the bulkhead, docs/sessions.md), while the warm
        map and in-flight dedupe stay cross-scope (the shared-executable
        win). Without a deadline, retries, faults, or failures this is
        exactly `get` (same dedupe, same counters)."""
        build = self._scoped_build(key, build, metrics)
        ck = (scope, key)
        while True:
            cooled = False
            with self._lock:
                eng = self._engines.get(key)
                if eng is not None:
                    self._engines[key] = self._engines.pop(key)  # recency
                    self._cooldown.pop(ck, None)  # warm ends the cooldown
                    mine = None
                else:
                    entry = self._cooldown.get(ck)
                    if entry is not None and self._cooldown_expired(entry):
                        # untouched past the TTL: expire — this scope's
                        # return after a quiet spell re-probes compile
                        del self._cooldown[ck]
                        entry = None
                    if entry is not None:
                        remaining = entry[0]
                        if remaining > 1:
                            self._cooldown[ck] = (
                                remaining - 1, time.monotonic()
                            )
                        else:
                            # cooldown spent: the NEXT call re-probes
                            self._cooldown.pop(ck, None)
                        cooled = True
                        mine = False
                    elif self._stuck_locked(ck):
                        # an abandoned builder is STILL inside XLA: a
                        # re-probe would leak another thread — stay
                        # degraded until the stuck compile dies
                        self._cooldown[ck] = (
                            compile_cooldown_passes(), time.monotonic()
                        )
                        cooled = True
                        mine = False
                    else:
                        fl = self._inflight.get(key)
                        if fl is None:
                            fl = _Inflight()
                            self._inflight[key] = fl
                            mine = True
                        else:
                            mine = False
            if mine is None:
                if info is not None:
                    info.update(source="hit", wait_s=0.0)
                self._note(hits=1, metrics=metrics)
                return eng
            if cooled:
                raise CompileUnavailable(
                    f"compile for {key!r} is cooling down after ladder "
                    f"exhaustion; serve degraded"
                )
            if mine:
                return self._build_resilient(
                    key, fl, build, info, metrics=metrics, ck=ck
                )
            # share someone else's in-flight build, like `get`
            t0 = time.perf_counter()
            with telemetry.span("compile.wait", key=str(key)):
                fl.ev.wait()
            if fl.engine is not None:
                wait_s = time.perf_counter() - t0
                if info is not None:
                    info.update(source="wait", wait_s=wait_s)
                self._note(hits=1, stall_s=wait_s, metrics=metrics)
                return fl.engine
            # builder failed: loop — the cooldown it set (or a free
            # slot) decides this caller's fate

    def _stuck_locked(self, ck: tuple) -> bool:
        """Under self._lock: prune dead abandoned builders for the
        (scope, key) pair; True when one is still running (the wedged
        compile persists)."""
        alive = [t for t in self._abandoned.get(ck, ()) if t.is_alive()]
        if alive:
            self._abandoned[ck] = alive
            return True
        self._abandoned.pop(ck, None)
        return False

    def _build_resilient(
        self, key: tuple, fl: _Inflight, build, info, metrics=None, ck=None
    ):
        """The retry ladder for the caller that owns the in-flight slot."""
        if ck is None:
            ck = (None, key)
        t0 = time.perf_counter()
        attempts = 1 + compile_retry_limit()
        backoff = compile_backoff_s()
        eng = None
        err: "Exception | None" = None
        try:
            for i in range(attempts):
                if i:
                    self._note(retries=1, metrics=metrics)
                    telemetry.instant(
                        "compile.retry", key=str(key), attempt=i + 1
                    )
                    if backoff > 0:
                        time.sleep(backoff * (2 ** (i - 1)))
                try:
                    with telemetry.span(
                        "compile.build", key=str(key), attempt=i + 1
                    ):
                        eng = self._attempt_build(build)
                    break
                except Exception as e:  # noqa: BLE001 — each rung retries
                    err = e
                    th = getattr(e, "thread", None)
                    if th is not None:
                        with self._lock:
                            self._abandoned.setdefault(ck, []).append(th)
                        telemetry.instant(
                            "compile.deadline_abandoned", key=str(key)
                        )
        except BaseException:
            # non-Exception escape (KeyboardInterrupt, SystemExit):
            # release the slot exactly like `get`'s miss path
            with self._lock:
                self._inflight.pop(key, None)
            fl.ev.set()
            raise
        if eng is None:
            with self._lock:
                self._inflight.pop(key, None)
                self._cooldown[ck] = (
                    compile_cooldown_passes(), time.monotonic()
                )
            fl.ev.set()  # engine stays None: waiters re-enter the ladder
            self._note(stall_s=time.perf_counter() - t0, metrics=metrics)
            telemetry.instant("compile.ladder_exhausted", key=str(key))
            raise CompileUnavailable(
                f"compile ladder exhausted for {key!r} after {attempts} "
                f"attempts: {type(err).__name__}: {err}"
            ) from err
        with self._lock:
            self._store_locked(key, eng)
            self._inflight.pop(key, None)
        fl.engine = eng
        fl.ev.set()
        if info is not None:
            info.update(source="miss", wait_s=0.0)
        self._note(misses=1, stall_s=time.perf_counter() - t0, metrics=metrics)
        return eng

    # -- speculation --------------------------------------------------------

    def speculate(self, token, task, metrics=None) -> bool:
        """Queue `task` for the background worker. `task()` runs off the
        request thread and returns ``(key, build)`` — or None to skip —
        after which the worker builds and stores the engine (skipping
        keys already warm or in flight). `token` dedupes while the task
        is queued/running. Returns False when speculation is disabled or
        the token is already pending. `metrics` attributes the eventual
        speculativeCompiles count to the ARMING service's registry (on a
        shared broker, the session that armed the build)."""
        if not self.speculative:
            return False
        # the HBM headroom gate (utils/fleetstats.py, docs/
        # observability.md): with KSS_SPEC_MEM_HEADROOM_BYTES set, a
        # device whose free HBM is below the floor SKIPS speculation —
        # a background build's XLA workspace must never be the
        # allocation that OOMs a serving process. Counted + marked so
        # memory-shed speculation is visible, never silent.
        from . import fleetstats

        if not fleetstats.speculation_memory_ok():
            with self._lock:
                self.spec_mem_skips += 1
            telemetry.instant(
                "compile.speculation_skipped", reason="hbm-headroom",
                token=str(token),
            )
            return False
        # the causal pass id + session + distributed-trace id of the
        # ARMING request thread (and its thread-locally scoped fault
        # plane, the session bulkhead) travel with the task: the worker
        # re-enters them all, so a speculative build's telemetry spans
        # name the pass/session/trace that armed it and its faults draw
        # from the arming session's plane
        armed_by = telemetry.current_pass_id()
        armed_session = telemetry.current_session_id()
        armed_trace = telemetry.current_trace_id()
        armed_plane = faultinject.scoped_active()
        with self._lock:
            if token in self._tokens:
                return False
            self._tokens.add(token)
            self._tasks.append(
                (
                    token, task, armed_by, armed_session, armed_trace,
                    armed_plane, metrics,
                )
            )
            self._busy += 1
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._work, name="kss-compile-broker", daemon=True
                )
                self._worker.start()
        return True

    def _work(self) -> None:
        while True:
            with self._lock:
                if not self._tasks:
                    self._worker = None
                    return
                (
                    token, task, armed_by, armed_session, armed_trace,
                    armed_plane, armed_metrics,
                ) = self._tasks.pop(0)
            try:
                scope = (
                    faultinject.scoped(armed_plane)
                    if armed_plane is not None
                    else _null_context()
                )
                with scope, telemetry.pass_context(
                    armed_by
                ), telemetry.session_context(
                    armed_session
                ), telemetry.trace_context(armed_trace), telemetry.span(
                    "compile.speculative", token=str(token)
                ):
                    plane = faultinject.active()
                    if plane is not None:
                        plane.maybe_raise("worker_crash")
                    res = task()
                    if res is not None:
                        key, build = res
                        self._background_build(key, build, metrics=armed_metrics)
            except BaseException as e:  # noqa: BLE001 — speculation never fails a run
                if armed_plane is not None:
                    # the crash came from a SESSION-scoped fault plane
                    # (the arming tenant's private chaos spec): contain
                    # it to that tenant — the shared worker stays up and
                    # the broker stays ready for every other session
                    # (docs/sessions.md bulkheads)
                    self._contain_scoped_crash(e, armed_session)
                else:
                    self._contain_worker_crash(e)
            finally:
                with self._lock:
                    self._tokens.discard(token)
                    self._busy -= 1
                    self._idle.notify_all()

    def _contain_worker_crash(self, exc: BaseException) -> None:
        """A crashed speculative task/worker: logged ONCE per broker,
        counted (brokerWorkerCrashes), and speculation self-disables —
        the worker must degrade visibly, never die silently. Dedupe and
        the warm-engine map stay on; already-queued tasks still drain
        (their tokens must clear) but no new speculation is accepted."""
        if not self._crash_logged:
            self._crash_logged = True
            _log.warning(
                "speculative compile worker crashed (%s: %s); "
                "disabling speculation for this broker",
                type(exc).__name__, exc,
            )
        self.speculative = False
        self._note(worker_crashes=1)

    def _contain_scoped_crash(self, exc: BaseException, scope) -> None:
        """A speculative task crashed under a SESSION's private fault
        plane: counted per scope (visible in stats), logged once per
        scope, but the shared worker keeps running, broker-level
        `worker_crashes` stays 0, and health() stays ready — one
        tenant's chaos spec must not drain the replica or cost its
        neighbors speculation."""
        with self._lock:
            first = scope not in self._scoped_crashes
            self._scoped_crashes[scope] = self._scoped_crashes.get(scope, 0) + 1
        if first:
            _log.warning(
                "speculative build crashed under session %r's fault plane "
                "(%s: %s); contained to that session",
                scope, type(exc).__name__, exc,
            )

    def _background_build(self, key: tuple, build, metrics=None) -> None:
        build = self._scoped_build(key, build, metrics)
        with self._lock:
            if key in self._engines or key in self._inflight:
                return  # already warm / being compiled — nothing to do
            fl = _Inflight()
            self._inflight[key] = fl
        try:
            # the fault plane's compile sites cover background builds
            # too (a failed speculative compile is a NORMAL outcome —
            # contained here, not a worker crash)
            plane = faultinject.active()
            if plane is not None:
                plane.delay("compile_slow")
                plane.maybe_raise("compile_fail")
            eng = build()
        except BaseException:  # noqa: BLE001
            with self._lock:
                self._inflight.pop(key, None)
            fl.ev.set()  # engine stays None: any waiter retries
            return
        with self._lock:
            self._store_locked(key, eng)
            self._inflight.pop(key, None)
        fl.engine = eng
        fl.ev.set()
        self._note(speculative=1, metrics=metrics)

    def quiesce(self, timeout: "float | None" = None) -> bool:
        """The ORDERLY-exit drain (server drain / graceful shutdown,
        docs/resilience.md): stop accepting new speculation, then
        out-wait any background build still inside XLA — the same
        teardown hazard the atexit hook bounds as a last resort
        (`_drain_live_brokers`), handled here on the graceful path so a
        drained process exits 0 instead of racing the C++ compiler
        threads at interpreter teardown. True when fully quiesced."""
        self.speculative = False
        return self.drain(timeout=timeout)

    def drain(self, timeout: "float | None" = None) -> bool:
        """Block until the speculation queue is empty and no task is
        running — then flush any in-flight AOT bundle writes
        (utils/bundles.py): a drained process must not abandon a
        serialized executable mid-save, and flushing AFTER the worker
        settles covers the bundles its last build enqueued. True on
        success, False on timeout. The 'after warm-up' fence the
        perf-smoke crossing gate stands on."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while self._busy:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._idle.wait(remaining)
        remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
        return bundles_mod.flush(timeout=remaining)


# Every live broker, so interpreter exit can quiesce speculation first:
# a speculative compile still inside XLA when Python tears down dies as
# std::terminate / a segfault from the C++ compiler threads — the
# process "crashes" on a run that SUCCEEDED. Exit must out-wait any
# in-flight background build (bounded: a truly wedged compile must not
# turn exit into a hang — past the timeout we accept the teardown race
# rather than never exiting).
_live_brokers: "weakref.WeakSet" = weakref.WeakSet()
_ATEXIT_DRAIN_TIMEOUT_S = 30.0


@atexit.register
def _drain_live_brokers() -> None:
    for broker in list(_live_brokers):
        broker.speculative = False  # no new work while exiting
        broker.drain(timeout=_ATEXIT_DRAIN_TIMEOUT_S)
