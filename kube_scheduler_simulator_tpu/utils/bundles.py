"""Persistent AOT engine bundles: kill the compile wall at process boot.

BENCH_r05_chip put the frontier on compilation, not execution: warm
passes run in 0.9-15 s while every engine shape pays 80-162 s of XLA
compilation, and a cold process restart re-pays ALL of it before the
first pod schedules. The persistent XLA compile cache
(utils/compilecache.py) already amortizes the *backend* compile across
processes, but a cold boot still pays the full Python trace + MLIR
lowering per program — roughly half the CPU cold wall and all of the
request-thread latency a disk hit cannot remove.

With ``KSS_AOT_BUNDLES=1``, every program jitted through
``utils/broker.jit`` (seq.run / seq.segment / seq.attempt / seq.bind /
the gang programs / extender segments / delta scatters / sweeps) is
ahead-of-time compiled on its first call — ``jitted.trace(*args)
.lower().compile()`` — and the compiled executable is SERIALIZED
(``jax.experimental.serialize_executable``: the PJRT executable bytes
plus the pickled in/out treedefs) into an on-disk bundle under
``KSS_BUNDLE_DIR``. A later process (a cold restart, or a speculative
bucket-crossing warm-up on the broker's worker thread) finds the bundle
and **deserializes the executable instead of re-tracing, re-lowering,
or re-compiling** — the whole compile wall collapses to a file read
plus a PJRT deserialize. ``bench.py --cold-start`` with a warmed bundle
dir is the gate (docs/performance.md).

Bundle identity — the KEY (sha256 over a canonical JSON doc, every
component a mismatch-means-miss guard):

  * the site label (the KSS7xx audit label naming the program);
  * the BROKER scope: the serving layer's engine key
    ``(kind, compile signature, window)`` including the PR 8
    device-epoch suffix — captured thread-locally while
    ``CompileBroker`` runs a build, so a mesh change (epoch bump) can
    never resurrect a dead device's executable;
  * the jit kwargs (donations are baked into the executable);
  * the full argument-leaf signature (shape / dtype / weak-type);
  * jax + jaxlib versions, backend platform, device count and kind,
    the x64 switch;
  * a digest of the package's own source tree — any code change
    invalidates every bundle, the honest answer to "the avals didn't
    change but the program body did".

The HEADER (a JSON line prefixed to the payload) repeats the identity
fields plus the program's KSS715 compile fingerprint and a payload
checksum. Loads re-verify all of it: a truncated or corrupt file, a
foreign jax/jaxlib version, a platform mismatch, or a fingerprint that
the persisted KSS715 baseline (``kss-fingerprints.json``) does not
recognize for the site all count as a BYPASS — the caller falls back
silently to the normal compile path. A bundle can make a pass faster;
it can never make one wrong (placements byte-identical bundled vs
unbundled, parity-pinned in tests/test_aot_bundles.py).

Writes are ASYNC and ATOMIC: the serialized blob is enqueued to a
writer thread that writes ``<name>.tmp.<pid>`` and ``os.replace``s it
into place — the same discipline as the checkpoint writer — and
``CompileBroker.quiesce``/``drain`` flush the queue, so a SIGTERM
mid-save can never leave a torn bundle for the next boot to load (and
the loader's checksum catches one anyway).

Trust model: the bundle payload is a pickle (the PJRT executable bytes
ride inside one), so loading a bundle executes its pickle. The default
directory therefore lives next to the persistent compile cache —
per-checkout (or per-user) isolation, the same argument
utils/compilecache.py makes: a world-shared directory would let another
local user plant crafted entries that deserialize into in-process code.
Point ``KSS_BUNDLE_DIR`` only at directories you'd trust as code.

Accounting: ``bundleLoads`` / ``bundleSaves`` / ``bundleBypasses`` /
``aotDeserializeSeconds`` — store-global in ``STORE.stats()``, mirrored
into the building service's ``SchedulingMetrics`` (the broker arms the
sink around each build), and recorded DISTINCTLY from the compile wall
in the program ledger (``deserializeSeconds`` per program, never
conflated with lowering/backend seconds).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import threading
import time
from contextlib import contextmanager
from typing import Any

from . import locking, telemetry
from .envcheck import env_truthy

BUNDLE_FORMAT = "kss-aot-bundle/v1"
BUNDLE_SUFFIX = ".kssbundle"

ENV_VAR = "KSS_AOT_BUNDLES"
DIR_VAR = "KSS_BUNDLE_DIR"

_SAFE_LABEL_RE = re.compile(r"[^A-Za-z0-9_.-]+")


def bundles_enabled() -> bool:
    """The AOT-bundle switch (``KSS_AOT_BUNDLES``), read at jit-WRAP
    time by ``utils/broker.jit`` — engine construction — exactly like
    the KSS7xx audit and ledger switches."""
    return env_truthy(os.environ.get(ENV_VAR))


def bundle_dir() -> str:
    """The bundle directory: ``KSS_BUNDLE_DIR``, defaulting to a
    sibling of ``kss-fingerprints.json`` in the persistent compile
    cache dir (same ``KSS_JAX_CACHE_DIR`` override, same per-checkout
    isolation — see the trust model in the module docstring)."""
    override = os.environ.get(DIR_VAR)
    if override:
        return override
    from .compilecache import default_cache_dir

    cache_dir = os.environ.get("KSS_JAX_CACHE_DIR") or default_cache_dir()
    return os.path.join(cache_dir, "kss-bundles")


# -- the broker build scope ----------------------------------------------------

# While a CompileBroker runs a build, the engine key it is building —
# (kind, compile signature, window) + the device-epoch suffix — and the
# building service's metrics registry ride thread-locally, so every
# program jit-WRAPPED inside the build keys its bundle on the broker
# key (scope) and every bundle event attributes to the right tenant
# (sink). Builds outside a broker (direct engine construction in tests
# and bench probes) key without a scope — still valid, less qualified.
_ctx = threading.local()


@contextmanager
def build_scope(key: "tuple | None", metrics: "Any | None" = None):
    prev_scope = getattr(_ctx, "scope", None)
    prev_metrics = getattr(_ctx, "metrics", None)
    _ctx.scope = key
    _ctx.metrics = metrics
    try:
        yield
    finally:
        _ctx.scope = prev_scope
        _ctx.metrics = prev_metrics


def current_scope() -> "tuple | None":
    return getattr(_ctx, "scope", None)


def current_metrics() -> "Any | None":
    return getattr(_ctx, "metrics", None)


# -- bundle identity -----------------------------------------------------------

_env_digest_cache: "dict | None" = None


def _environment_identity() -> dict:
    """The environment half of every bundle key: serialized executables
    are only valid on the jax/jaxlib build, backend, and device
    topology that produced them — and only for the source tree whose
    programs they compiled. Computed once per process."""
    global _env_digest_cache
    if _env_digest_cache is not None:
        return _env_digest_cache
    import jax
    import jaxlib

    devs = jax.devices()
    _env_digest_cache = {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": devs[0].platform,
        "nDevices": len(devs),
        "deviceKind": getattr(devs[0], "device_kind", ""),
        "x64": bool(jax.config.jax_enable_x64),
        "source": _source_digest(),
    }
    return _env_digest_cache


_source_digest_cache: "str | None" = None


def _source_digest() -> str:
    """sha256 over the package's own .py sources (sorted relpaths +
    contents): any code change invalidates every bundle. Aval-based
    fingerprints cannot see a program-body change; the source tree
    can."""
    global _source_digest_cache
    if _source_digest_cache is not None:
        return _source_digest_cache
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(pkg_root)):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fn), pkg_root)
            h.update(rel.encode())
            try:
                with open(os.path.join(dirpath, fn), "rb") as f:
                    h.update(f.read())
            except OSError:
                h.update(b"<unreadable>")
    _source_digest_cache = h.hexdigest()[:16]
    return _source_digest_cache


def _leaf_sig(x: Any) -> "tuple[Any, ...]":
    shape = tuple(int(d) for d in getattr(x, "shape", ()))
    dtype = str(getattr(x, "dtype", type(x).__name__))
    weak = bool(getattr(x, "weak_type", False))
    return (shape, dtype, weak)


def bundle_key(
    label: str,
    scope: "tuple | None",
    jit_kw: "dict[str, Any]",
    args: tuple,
    kwargs: dict,
) -> "tuple[str, dict]":
    """(digest, identity doc) for one (site, scope, signature). The doc
    is what the header records and the loader re-verifies; the digest
    names the file."""
    import jax

    leaves = jax.tree_util.tree_leaves((args, kwargs))
    doc = {
        "format": BUNDLE_FORMAT,
        "label": label,
        "scope": repr(scope) if scope is not None else "",
        "jitKw": {k: repr(v) for k, v in sorted(jit_kw.items())},
        "argSig": [_leaf_sig(a) for a in leaves],
        "env": _environment_identity(),
    }
    # canonicalize through JSON so the in-memory doc compares equal to
    # a header that round-tripped through a file (tuples become lists)
    canonical = json.dumps(doc, sort_keys=True)
    digest = hashlib.sha256(canonical.encode()).hexdigest()[:24]
    return digest, json.loads(canonical)


def _bundle_basename(label: str, digest: str) -> str:
    safe = _SAFE_LABEL_RE.sub("_", label) or "program"
    return f"{safe}-{digest}{BUNDLE_SUFFIX}"


# -- the KSS715 fingerprint gate ----------------------------------------------

_baseline_cache: "dict[str, tuple[float, dict]]" = {}


def _fingerprint_baseline() -> "tuple[float, dict[str, list[str]]]":
    """(file mtime, fingerprint sets) of the persisted KSS715 baseline
    (``kss-fingerprints.json``, analysis/jaxpr_audit.py), mtime-cached.

    The drift gate is DIRECTIONAL: only a baseline persisted AFTER a
    bundle was written can invalidate it — "the auditor re-measured
    this site and no longer recognizes the bundled program" is drift;
    "an old baseline from a different config never saw this program"
    is not (labels legitimately carry many fingerprints across configs
    and shapes, and most serving runs never arm the auditor at all).
    Bundles newer than the baseline fall back to the source-digest
    component of the key, which already invalidates on any code
    change."""
    from ..analysis.jaxpr_audit import fingerprint_path, load_fingerprints

    path = fingerprint_path()
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return (0.0, {})
    cached = _baseline_cache.get(path)
    if cached is not None and cached[0] == mtime:
        return cached
    entry = (mtime, load_fingerprints(path))
    _baseline_cache[path] = entry
    return entry


# -- the store -----------------------------------------------------------------


class BundleBypass(Exception):
    """A bundle exists but must not be loaded (header mismatch, torn
    payload, fingerprint drift): the caller compiles fresh."""


@locking.guard_inferred
class BundleStore:
    """On-disk AOT bundle store: load on miss, save on build, async
    atomic writes (module docstring)."""

    def __init__(self, directory: "str | None" = None):
        self._dir = directory
        self._lock = locking.make_lock("bundles.lock")
        self._idle = threading.Condition(self._lock)
        self._queue: "list[tuple[str, bytes, Any]]" = []
        self._writer: "threading.Thread | None" = None
        self._busy = 0  # queued or mid-write
        self.loads = 0
        self.saves = 0
        self.bypasses = 0
        self.misses = 0
        self.deserialize_s = 0.0

    @property
    def directory(self) -> str:
        return self._dir if self._dir is not None else bundle_dir()

    # -- accounting ----------------------------------------------------------

    def _note(
        self,
        loads: int = 0,
        saves: int = 0,
        bypasses: int = 0,
        misses: int = 0,
        deserialize_s: float = 0.0,
        metrics: "Any | None" = None,
    ) -> None:
        with self._lock:
            self.loads += loads
            self.saves += saves
            self.bypasses += bypasses
            self.misses += misses
            self.deserialize_s += deserialize_s
        sink = metrics if metrics is not None else current_metrics()
        if sink is not None and (loads or saves or bypasses or deserialize_s):
            sink.record_bundles(
                loads=loads,
                saves=saves,
                bypasses=bypasses,
                deserialize_s=deserialize_s,
            )

    def stats(self) -> dict:
        with self._lock:
            return {
                "bundleLoads": self.loads,
                "bundleSaves": self.saves,
                "bundleBypasses": self.bypasses,
                "bundleMisses": self.misses,
                "aotDeserializeSeconds": round(self.deserialize_s, 6),
                "pendingWrites": self._busy,
            }

    def reset_stats(self) -> None:
        with self._lock:
            self.loads = 0
            self.saves = 0
            self.bypasses = 0
            self.misses = 0
            self.deserialize_s = 0.0

    # -- load ----------------------------------------------------------------

    def load(
        self,
        label: str,
        digest: str,
        doc: dict,
        metrics: "Any | None" = None,
    ):
        """``(Compiled, deserialize_seconds, fingerprint)`` for
        (label, digest) — the fingerprint is the KSS715 identity the
        header carries, so a bundled boot's ledger rows key exactly
        like a compiled boot's — or None: a plain MISS (no file) or a
        counted BYPASS (file present but unloadable/mismatched/
        drifted; the compile path takes over, never an error)."""
        path = os.path.join(self.directory, _bundle_basename(label, digest))
        try:
            # any read error (absent, unreadable) is a MISS, not a
            # bypass: there is nothing present to distrust
            with open(path, "rb") as f:
                blob = f.read()
            bundle_mtime = os.stat(path).st_mtime
        except OSError:
            self._note(misses=1, metrics=metrics)
            return None
        t0 = time.perf_counter()
        try:
            compiled, fingerprint = self._deserialize(
                blob, label, digest, doc, bundle_mtime
            )
        except Exception as e:  # noqa: BLE001 — bypass, never a crashed pass
            self._note(bypasses=1, metrics=metrics)
            telemetry.instant(
                "bundle.bypass",
                label=label,
                reason=f"{type(e).__name__}: {e}"[:200],
            )
            return None
        dt = time.perf_counter() - t0
        self._note(loads=1, deserialize_s=dt, metrics=metrics)
        telemetry.instant(
            "bundle.load", label=label, seconds=round(dt, 6)
        )
        return compiled, dt, fingerprint

    def _deserialize(
        self,
        blob: bytes,
        label: str,
        digest: str,
        doc: dict,
        bundle_mtime: float = 0.0,
    ):
        """Verify header + checksum + fingerprint baseline, then load
        the executable; returns ``(Compiled, header fingerprint)``.
        Raises (BundleBypass or anything the unpickler throws) —
        ``load`` converts every raise into a counted bypass."""
        nl = blob.find(b"\n")
        if nl < 0:
            raise BundleBypass("no header line (truncated?)")
        try:
            header = json.loads(blob[:nl].decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise BundleBypass(f"unparseable header: {e}") from e
        if not isinstance(header, dict):
            raise BundleBypass("header is not an object")
        if header.get("format") != BUNDLE_FORMAT:
            raise BundleBypass(f"foreign format {header.get('format')!r}")
        if header.get("key") != digest:
            raise BundleBypass("key digest mismatch")
        # the environment identity must match EXACTLY — a bundle from
        # another jax/jaxlib build, backend, topology, or source tree
        # is a bypass even under a colliding digest
        if header.get("identity") != doc:
            raise BundleBypass("identity mismatch (jax version / "
                               "platform / source drift)")
        payload = blob[nl + 1:]
        want = header.get("payloadSha256")
        if hashlib.sha256(payload).hexdigest() != want:
            raise BundleBypass("payload checksum mismatch (torn write?)")
        # KSS715 gate (directional — see _fingerprint_baseline): a
        # baseline persisted AFTER this bundle that knows the site but
        # not this fingerprint means the site's program set drifted
        # since the bundle was written — compile fresh and let the
        # auditor flag it
        baseline_mtime, baseline = _fingerprint_baseline()
        site_fps = baseline.get(label)
        fp = header.get("fingerprint")
        if (
            baseline_mtime > bundle_mtime
            and site_fps
            and fp
            and fp not in site_fps
        ):
            raise BundleBypass(
                f"fingerprint {fp} drifted from the KSS715 baseline"
            )
        from jax.experimental import serialize_executable as se

        se_payload, in_tree, out_tree = pickle.loads(payload)
        compiled = se.deserialize_and_load(se_payload, in_tree, out_tree)
        return compiled, str(fp or "")

    # -- save ----------------------------------------------------------------

    def save(
        self,
        label: str,
        digest: str,
        doc: dict,
        compiled: Any,
        fingerprint: str,
        metrics: "Any | None" = None,
    ) -> bool:
        """Serialize `compiled`, VERIFY the payload deserializes, and
        enqueue the atomic write. False when the executable does not
        produce a loadable payload (the compile still served the pass;
        only persistence is skipped) — notably, an executable that XLA
        served from its own persistent disk cache re-serializes into a
        blob that cannot load ('Symbols not found' on XLA:CPU), so the
        verification here is what keeps the store free of bundles that
        would bypass on every future boot. `BundledJit` reacts to a
        False by MINTING: one re-compile with the disk cache disarmed."""
        from jax.experimental import serialize_executable as se

        try:
            se_payload, in_tree, out_tree = se.serialize(compiled)
            payload = pickle.dumps((se_payload, in_tree, out_tree))
            # the round-trip proof: a payload that cannot load must
            # never be persisted (the deserialized probe is dropped)
            se.deserialize_and_load(se_payload, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 — persistence is optional
            telemetry.instant(
                "bundle.save_skipped",
                label=label,
                reason=f"{type(e).__name__}: {e}"[:200],
            )
            return False
        header = {
            "format": BUNDLE_FORMAT,
            "key": digest,
            "identity": doc,
            "fingerprint": fingerprint,
            "payloadSha256": hashlib.sha256(payload).hexdigest(),
        }
        blob = (
            json.dumps(header, sort_keys=True).encode("utf-8")
            + b"\n"
            + payload
        )
        path = os.path.join(self.directory, _bundle_basename(label, digest))
        sink = metrics if metrics is not None else current_metrics()
        with self._lock:
            self._queue.append((path, blob, sink))
            self._busy += 1
            if self._writer is None:
                self._writer = threading.Thread(
                    target=self._write_loop, name="kss-bundle-writer",
                    daemon=True,
                )
                self._writer.start()
        return True

    def _write_loop(self) -> None:
        while True:
            with self._lock:
                if not self._queue:
                    self._writer = None
                    return
                path, blob, sink = self._queue.pop(0)
            try:
                self._write_atomic(path, blob)
            except OSError:
                # an unwritable bundle dir costs persistence, never a pass
                pass
            else:
                self._note(saves=1, metrics=sink)
                telemetry.instant(
                    "bundle.save", path=os.path.basename(path)
                )
            with self._lock:
                self._busy -= 1
                self._idle.notify_all()

    @staticmethod
    def _write_atomic(path: str, blob: bytes) -> None:
        """tmp-file + rename, the checkpoint writer's discipline: a
        reader can see the old file or the new file, never a torn one."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)

    def flush(self, timeout: "float | None" = None) -> bool:
        """Block until every queued bundle write has landed; True on
        success, False on timeout. ``CompileBroker.quiesce``/``drain``
        call this so a graceful exit never abandons an in-flight save
        (and a SIGTERM mid-save tears only the tmp file, which no
        loader ever opens)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while self._busy:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._idle.wait(remaining)
        return True


STORE = BundleStore()


def flush(timeout: "float | None" = None) -> bool:
    """Flush the process-global store's pending writes (the broker's
    quiesce/drain hook)."""
    return STORE.flush(timeout=timeout)


# -- the dispatch wrapper ------------------------------------------------------

# marks "no AOT result": None is a legal program output
_SENTINEL = object()


class BundledJit:
    """The broker's AOT-bundle wrapper around one ``jax.jit`` object.

    The first call of each argument signature resolves the program:

      * bundle HIT — the executable deserializes from disk (no trace,
        no lowering, no backend compile) and serves every later call;
      * bundle MISS — the program is AOT-compiled
        (``trace().lower().compile()``) on this thread, serves the
        call, and is serialized to the store for the next process;
      * anything else (static argnums the flat signature cannot key,
        serialization unsupported, a loaded executable rejecting the
        call) degrades to plain jit dispatch — correctness over reuse.

    With the program ledger armed, loads record ``deserializeSeconds``
    and misses record the lowering/backend split — the two walls stay
    distinct (utils/ledger.py). Everything else (``trace``/``lower``/
    attributes) delegates to the jitted object, so the KSS7xx auditor
    wrapping THIS wrapper still traces the raw program."""

    def __init__(
        self,
        jitted: Any,
        jit_kw: "dict[str, Any]",
        sp: "dict[str, Any] | None",
        *,
        store: "BundleStore | None" = None,
        ledger: Any = None,
    ):
        self._jitted = jitted
        self._jit_kw = dict(jit_kw)
        self._label = (sp or {}).get("label") or getattr(
            getattr(jitted, "__wrapped__", None), "__qualname__", None
        ) or "<unlabeled>"
        # wrap time IS engine-construction time, inside the broker's
        # build: the engine key rides the thread-local scope, and the
        # building service's metrics registry is captured as the sink —
        # first-call load/save events fire later on whatever thread
        # dispatches first (often a request thread outside any build
        # scope), and must still mirror into the tenant's counters
        self._scope = current_scope()
        self._metrics = current_metrics()
        self._store = STORE if store is None else store
        self._ledger = ledger
        self._programs: "dict[tuple, tuple[Any, Any]]" = {}
        # first-call resolution pays the full AOT wall: serialize it,
        # as jax.jit itself does, so two sessions sharing one warm-map
        # engine can never duplicate a compile (or double-save a bundle)
        self._resolve_lock = threading.Lock()
        if ledger is not None:
            from .ledger import timing_sample_every

            self._sample_every = timing_sample_every()
        else:
            self._sample_every = 0
        # static argnums/argnames change the calling convention of the
        # compiled object; no broker site uses them today — bail to
        # plain dispatch if one ever does rather than mis-key
        self._unbundleable = bool(
            jit_kw.get("static_argnums") or jit_kw.get("static_argnames")
        )

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        if self._unbundleable:
            return self._jitted(*args, **kwargs)
        import jax

        sig = tuple(
            _leaf_sig(a)
            for a in jax.tree_util.tree_leaves((args, kwargs))
        )
        entry = self._programs.get(sig)
        if entry is None:
            with self._resolve_lock:
                entry = self._programs.get(sig)
                if entry is None:
                    entry = self._first_call(sig, args, kwargs)
        compiled, record = entry
        calls_before = record.calls if record is not None else 0
        degraded = False
        t0 = time.perf_counter()
        out = _SENTINEL
        if compiled is not None:
            try:
                out = compiled(*args, **kwargs)
            except Exception:  # noqa: BLE001 — degrade, never fail the pass
                # an aval/static mismatch the flat signature missed:
                # this signature falls back to plain jit for good
                self._programs[sig] = (None, record)
                degraded = True
        if out is _SENTINEL:
            out = self._jitted(*args, **kwargs)
        if record is not None:
            dispatch_s = time.perf_counter() - t0
            warm_s = None
            if (
                self._sample_every
                and calls_before > 0
                and calls_before % self._sample_every == 0
            ):
                # the sampled warm device wall: block on THIS call's
                # result (the first, resolve-bearing call never samples)
                try:
                    jax.block_until_ready(out)
                    warm_s = time.perf_counter() - t0
                except Exception:  # noqa: BLE001 — sampling never fails a pass
                    pass
            self._record_ledger_call(record, dispatch_s, warm_s, degraded)
        return out

    # -- first-call resolution ----------------------------------------------

    def _first_call(self, sig: tuple, args: tuple, kwargs: dict):
        digest, doc = bundle_key(
            self._label, self._scope, self._jit_kw, args, kwargs
        )
        loaded = self._store.load(
            self._label, digest, doc, metrics=self._metrics
        )
        if loaded is not None:
            compiled, deserialize_s, fingerprint = loaded
            record = self._open_ledger_row(
                args,
                kwargs,
                deserialize_s=deserialize_s,
                loaded=True,
                fingerprint=fingerprint,
            )
            entry = (compiled, record)
            self._programs[sig] = entry
            return entry
        # miss: AOT-compile here (the same wall jit's first call would
        # pay — the shared timed probe splits lowering vs backend and
        # reads the cost model), serve the pass from the compiled
        # object, and persist it for the next process. A failed probe
        # pins the signature to plain jit dispatch, whose own first
        # call surfaces the compile error to the broker's retry ladder.
        from . import ledger as ledger_mod

        probe = ledger_mod.aot_probe(self._jitted, args, kwargs)
        if probe is None:
            entry = (None, None)
            self._programs[sig] = entry
            return entry
        compiled, info, traced = probe
        fingerprint = self._fingerprint(traced, args)
        if not self._store.save(
            self._label, digest, doc, compiled, fingerprint,
            metrics=self._metrics,
        ):
            # the executable came out unserializable — almost always an
            # XLA persistent-disk-cache HIT (those re-serialize into
            # blobs that cannot load). MINT a persistable one: a single
            # re-compile with the disk cache disarmed; identical
            # program, and the verified executable serves the dispatch.
            minted = self._mint_fresh(args, kwargs)
            if minted is not None and self._store.save(
                self._label, digest, doc, minted, fingerprint,
                metrics=self._metrics,
            ):
                compiled = minted
        record = self._open_ledger_row(
            args,
            kwargs,
            lowering_s=info["lowering_s"],
            backend_s=info["backend_s"],
            cost=(
                {"flops": info["flops"], "bytes": info["bytes"]}
                if info.get("flops") is not None
                else None
            ),
            memory=info.get("memory"),
            traced=traced,
            fingerprint=fingerprint,
        )
        entry = (compiled, record)
        self._programs[sig] = entry
        return entry

    def _mint_fresh(self, args: tuple, kwargs: dict):
        """One re-compile with the XLA persistent compile cache
        disarmed, to mint a serializable executable (see `save`'s
        verification). Two caches must be sidestepped, or the
        "recompile" silently hands back the same poisoned executable:

          * the persistent disk cache — and flipping
            ``jax_compilation_cache_dir`` alone is NOT enough, because
            jax memoizes "is the cache used" after the first compile
            (``compilation_cache._cache_checked``): the
            ``jax_enable_compilation_cache`` flag must be lowered AND
            the memo reset, then both restored;
          * jax's in-memory compilation LRU, which would return the
            disk-loaded executable in ~1 ms without ever reaching the
            backend — busted by passing an explicitly-default
            ``compiler_options`` (part of the LRU key, no effect on
            the program).

        The toggle is global config, restored in finally; a concurrent
        compile on another thread may skip the disk cache for its one
        build — a slower compile, never a wrong one. None when the
        fresh compile fails (the pass keeps the original executable;
        only persistence is skipped)."""
        import jax

        try:
            from jax._src import compilation_cache as _cc
        except ImportError:  # pragma: no cover — private-module drift
            _cc = None
        prev = jax.config.jax_enable_compilation_cache
        try:
            jax.config.update("jax_enable_compilation_cache", False)
            if _cc is not None:
                _cc.reset_cache()
            compiled = (
                self._jitted.trace(*args, **kwargs)
                .lower()
                .compile(
                    compiler_options={"xla_embed_ir_in_executable": False}
                )
            )
        except Exception:  # noqa: BLE001 — minting is optional
            return None
        finally:
            try:
                jax.config.update("jax_enable_compilation_cache", prev)
                if _cc is not None:
                    _cc.reset_cache()  # re-evaluate with the restored flag
            except Exception:  # noqa: BLE001 — never leave config torn
                pass
        telemetry.instant("bundle.mint_recompile", label=self._label)
        return compiled

    def _fingerprint(self, traced: Any, args: tuple) -> str:
        """The program's KSS715 compile fingerprint — the same function
        the auditor and ledger use, so the bundle header, the
        fingerprint baseline, and the ledger all name one identity."""
        try:
            from ..analysis.jaxpr_audit import JaxprAuditor, _aval_sig

            closed = traced.jaxpr
            in_avals = tuple(_aval_sig(v.aval) for v in closed.jaxpr.invars)
            out_avals = tuple(_aval_sig(v.aval) for v in closed.jaxpr.outvars)
            return JaxprAuditor._fingerprint(
                self._label, self._jit_kw, args, in_avals, out_avals
            )
        except Exception:  # noqa: BLE001 — identity beats precision here
            return ""

    # -- ledger integration (KSS_PROGRAM_LEDGER) -----------------------------

    def _open_ledger_row(
        self,
        args: tuple,
        kwargs: dict,
        *,
        lowering_s: float = 0.0,
        backend_s: float = 0.0,
        deserialize_s: float = 0.0,
        loaded: bool = False,
        cost: "dict | None" = None,
        memory: "dict | None" = None,
        traced: Any = None,
        fingerprint: str = "",
    ):
        if self._ledger is None:
            return None
        in_avals: tuple = ()
        out_avals: tuple = ()
        try:
            if traced is not None:
                from ..analysis.jaxpr_audit import _aval_sig

                closed = traced.jaxpr
                in_avals = tuple(
                    _aval_sig(v.aval) for v in closed.jaxpr.invars
                )
                out_avals = tuple(
                    _aval_sig(v.aval) for v in closed.jaxpr.outvars
                )
        except Exception:  # noqa: BLE001 — observability never fails a pass
            pass
        if not fingerprint:
            import jax

            sig = tuple(
                _leaf_sig(a)
                for a in jax.tree_util.tree_leaves((args, kwargs))
            )
            fingerprint = hashlib.sha256(
                json.dumps([self._label, sig], sort_keys=True).encode()
            ).hexdigest()[:16]
        try:
            return self._ledger.open_program(
                self._label,
                fingerprint,
                in_avals=in_avals,
                out_avals=out_avals,
                lowering_s=lowering_s,
                backend_s=backend_s,
                deserialize_s=deserialize_s,
                loaded=loaded,
                cost=cost,
                memory=memory,
            )
        except Exception:  # noqa: BLE001 — the never-raise contract
            return None

    def _record_ledger_call(self, record, dispatch_s, warm_s, degraded) -> None:
        try:
            self._ledger.record_call(
                record,
                dispatch_s,
                session=telemetry.current_session_id(),
                warm_s=warm_s,
                degraded=degraded,
            )
        except Exception:  # noqa: BLE001 — the never-raise contract
            pass

    def __getattr__(self, name: str) -> Any:
        return getattr(self._jitted, name)
