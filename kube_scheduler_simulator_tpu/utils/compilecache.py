"""The one definition of the repo-local persistent XLA compile cache.

bench.py, tests/conftest.py, and tools/config5_e2e.py all want the same
thing: repeat compiles of an identical program (across processes AND
across judge re-runs) are disk hits, not fresh XLA compiles. Before this
helper each carried its own copy and they drifted (different thresholds,
only conftest honoring the KSS_JAX_CACHE_DIR override — code-review r5).

The default directory is `.jax_cache` at the repo root (gitignored):
per-checkout isolation — a world-shared /tmp dir would break on
multi-user hosts and let another local user plant crafted cache entries
that deserialize into in-process executables. When the `__file__`-derived
root is NOT a writable checkout (a site-packages install run by an
unprivileged user — ADVICE r5), the default falls back to the per-user
`~/.cache/kss-jax`, which keeps the same single-user isolation property.
"""

from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def shape_bucket(n: int, lo: int = 8) -> int:
    """Geometric (power-of-two) shape bucket for a live object count.

    Every padded-axis length that reaches XLA — node capacity, pod
    capacity, the sequential scan's queue length — is rounded up to the
    next power of two at or above `lo`, so churn that adds or removes a
    few objects keeps reusing the program compiled for the current
    bucket instead of recompiling per exact count. A bucket is crossed
    (and one recompile paid, amortized by the persistent disk cache
    below) only when the live count doubles past it or shrink passes
    re-encode at a smaller bucket. `n <= 0` maps to 0: an empty axis is
    its own (trivial) shape class, not an 8-wide one.
    """
    if n <= 0:
        return 0
    c = lo
    while c < n:
        c *= 2
    return c


def capacity_buckets(
    n_nodes: int, n_pods: int, *, node_lo: int = 8, pod_lo: int = 8
) -> tuple[int, int]:
    """(node_capacity, pod_capacity) for a cluster of live counts — THE
    bucket policy encode_cluster callers share (server/service.py, the
    delta encoder, benchmarks). Also the bucket component of encoding /
    compiled-program cache keys: two stores whose counts land in the
    same buckets produce shape-identical programs."""
    return (
        max(shape_bucket(n_nodes, node_lo), 1),
        max(shape_bucket(n_pods, pod_lo), 1),
    )


def default_cache_dir(repo_root: "str | None" = None) -> str:
    """The cache directory `enable_compile_cache` uses absent the
    KSS_JAX_CACHE_DIR override: `<repo_root>/.jax_cache` when the root
    is a writable directory, else the per-user `~/.cache/kss-jax` (the
    package may live in a read-only site-packages tree)."""
    root = _REPO_ROOT if repo_root is None else repo_root
    if os.path.isdir(root) and os.access(root, os.W_OK):
        return os.path.join(root, ".jax_cache")
    return os.path.join(os.path.expanduser("~"), ".cache", "kss-jax")


def enable_compile_cache(min_compile_time_secs: float = 0.1) -> None:
    """Point JAX at the persistent compile cache. Honors the
    KSS_JAX_CACHE_DIR env override (what conftest always did). Safe to
    call repeatedly; failures are swallowed — the cache is an
    optimization, never a correctness dependency."""
    try:
        import jax

        jax.config.update(  # type: ignore[no-untyped-call]
            "jax_compilation_cache_dir",
            os.environ.get("KSS_JAX_CACHE_DIR", default_cache_dir()),
        )
        jax.config.update(  # type: ignore[no-untyped-call]
            "jax_persistent_cache_min_compile_time_secs",
            min_compile_time_secs,
        )
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass
