"""Runtime device-fault supervision — the EXECUTION half of the ladder.

PR 4's run supervision hardened *compile time* (watchdog, retry,
cooldown, eager fallback); this module supplies the shared pieces for
the *execution-time* ladder (docs/resilience.md) that
`server/service.py` walks when the device plane misbehaves AFTER a
successful compile:

  rung 0  dispatch as usual (warm engine, current device);
  rung 1  bounded retry — ``KSS_DISPATCH_RETRIES`` more attempts on a
          transient ``XlaRuntimeError`` / injected device fault /
          dispatch-watchdog timeout (``KSS_DISPATCH_DEADLINE_S``);
  rung 2  mesh shrink — drop the faulted device, rebuild the mesh over
          the survivors (`parallel/mesh.surviving_mesh`: the replicas
          axis absorbs the loss) and rebuild the engine through the
          CompileBroker under a bumped device epoch;
  rung 3  CPU failover — the mid-process generalization of the
          boot-time CPU re-exec (`utils/axonenv.reexec_on_cpu`):
          re-encode on the CPU backend and re-run the SAME pass there.
          Same placements, same trace bytes; only latency degrades.

Classification lives here (`is_device_fault`) so the service's ladder
and the tests agree on exactly which exceptions escalate: real XLA
runtime errors (matched by type NAME — jaxlib's exception types are not
importable on every build), the fault plane's two device sites, and the
dispatch watchdog's timeout. Everything else propagates untouched —
a bug must never be retried into silence.
"""

from __future__ import annotations

import os

from . import broker as broker_mod
from . import faultinject

# device-fault sites of the fault-injection grammar (utils/faultinject.py)
DEVICE_FAULT_SITES = ("device_error", "device_lost")

# exception type NAMES treated as device-plane failures when they appear
# anywhere in the exception's MRO (jaxlib moves these between modules
# across versions; the name is the stable part)
_DEVICE_ERROR_TYPE_NAMES = ("XlaRuntimeError",)


class DispatchDeadlineExceeded(RuntimeError):
    """One device dispatch overran KSS_DISPATCH_DEADLINE_S (the probe
    thread is abandoned — a wedged dispatch cannot be interrupted from
    Python; its late result is discarded). Classified as a device fault:
    the execution ladder escalates instead of hanging the pass."""


def _env_number(name: str, default, convert, minimum):
    """A ladder knob from the environment — the env READ lives here so
    the KSS1xx env-registry analyzer ties the names to this module;
    coercion leniency is the broker's shared `_coerce_env_number` (a
    typo must never disarm the execution ladder)."""
    return broker_mod._coerce_env_number(
        os.environ.get(name, ""), default, convert, minimum
    )


def dispatch_deadline_s() -> float:
    """Per-attempt dispatch-probe deadline from KSS_DISPATCH_DEADLINE_S;
    0 (the default) disables the watchdog — no extra thread per pass.
    The window covers the fault plane's dispatch sites (the injected
    ``dispatch_hang`` wedged-dispatch stand-in); a hang deep inside a
    running XLA program is out of its reach — that cannot be abandoned
    without tearing the engine out from under a live pass."""
    return _env_number("KSS_DISPATCH_DEADLINE_S", 0.0, float, 0.0)


def dispatch_retries() -> int:
    """Extra dispatch attempts after the first device fault
    (KSS_DISPATCH_RETRIES, default 2) before the ladder escalates to
    the mesh-shrink rung."""
    return _env_number("KSS_DISPATCH_RETRIES", 2, int, 0)


def run_with_deadline(fn, deadline_s: float):
    """Run `fn()` under a dispatch watchdog: on timeout the runner
    thread is abandoned (its late result or exception is discarded) and
    `DispatchDeadlineExceeded` raises on the caller. With no deadline,
    `fn()` runs inline — zero thread cost on the healthy path. The
    watchdog machinery is the broker's (`_call_with_deadline`) with the
    dispatch exception swapped in — one implementation to fix."""

    def timed_out(_thread) -> DispatchDeadlineExceeded:
        return DispatchDeadlineExceeded(
            f"device dispatch exceeded KSS_DISPATCH_DEADLINE_S="
            f"{deadline_s}s"
        )

    return broker_mod._call_with_deadline(
        fn, deadline_s, make_exc=timed_out,
        thread_name="kss-dispatch-attempt",
    )


def is_device_fault(exc: BaseException) -> bool:
    """True when `exc` is a device-plane failure the execution ladder
    owns: a dispatch-watchdog timeout, an injected device site, or a
    real XLA runtime error. Anything else (encode bugs, value errors,
    the compile ladder's own terminal failures) must propagate —
    retrying it would hide a bug behind a mesh shrink."""
    if isinstance(exc, DispatchDeadlineExceeded):
        return True
    if isinstance(exc, faultinject.InjectedFault):
        return exc.site in DEVICE_FAULT_SITES
    return any(
        cls.__name__ in _DEVICE_ERROR_TYPE_NAMES
        for cls in type(exc).__mro__
    )


def cpu_devices() -> list:
    """The CPU backend's devices, or [] when that backend is unusable —
    the CPU-failover rung's precondition. Never raises: a process whose
    accelerator died AND whose CPU backend won't initialize reports
    EngineDegraded through the caller, not a secondary crash here."""
    try:
        import jax

        return list(jax.devices("cpu"))
    except Exception:  # noqa: BLE001 — absence of a backend, not a bug
        return []
