"""Strict startup validation of the KSS_* environment surface.

The serving-stack knobs are deliberately LENIENT at their point of use —
a malformed `KSS_ENCODING_CACHE_CAP` must not take a long-lived library
caller down, so the runtime parsers fall back to defaults (or, for the
fault plane, raise at the first fire point deep inside a request
handler). That leniency is exactly wrong at process startup: an operator
who typo'd a knob should learn it from a clear boot-time error, not from
a silently-defaulted cache size or a 500 mid-request. The entry points
(`python -m ...server`, `python -m ...lifecycle`) call `fail_fast()`
before doing anything else.

The registry below is the single catalogue of KSS_* variables
(docs/environment-variables.md mirrors it); unknown `KSS_`-prefixed
names are flagged too, catching the `KSS_ENCODNG_CACHE_CAP` class of
typo that otherwise configures nothing.
"""

from __future__ import annotations

import os
import sys
from typing import Callable, Mapping

# a validator takes the raw env string and answers an error message, or
# None when the value parses (the registry's value type)
Validator = Callable[[str], "str | None"]

# The ONE boolean vocabulary: every spelling `check_env` accepts is a
# spelling the runtime parsers honor (broker speculation kill switch,
# telemetry KSS_TRACE). Validation blessing a value the runtime would
# silently ignore is exactly the misconfiguration class this module
# exists to catch.
TRUTHY: "tuple[str, ...]" = ("1", "true", "yes", "on", "t")
FALSY: "tuple[str, ...]" = ("", "0", "false", "no", "off", "f")
_BOOLISH = TRUTHY + FALSY


def env_truthy(raw: "str | None") -> bool:
    """Shared boolean env parse: True for any TRUTHY spelling (case- and
    whitespace-insensitive), False otherwise."""
    return (raw or "").strip().lower() in TRUTHY


def _int_validator(minimum: "int | None" = None) -> Validator:
    def check(raw: str) -> "str | None":
        try:
            v = int(raw)
        except ValueError:
            return f"expected an integer, got {raw!r}"
        if minimum is not None and v < minimum:
            return f"must be >= {minimum}, got {v}"
        return None

    return check


def _float_validator(minimum: "float | None" = None) -> Validator:
    def check(raw: str) -> "str | None":
        try:
            v = float(raw)
        except ValueError:
            return f"expected a number, got {raw!r}"
        if minimum is not None and v < minimum:
            return f"must be >= {minimum}, got {v}"
        return None

    return check


def _bool_validator(raw: str) -> "str | None":
    if raw.strip().lower() not in _BOOLISH:
        return f"expected a boolean (0/1/true/false/yes/no/on/off), got {raw!r}"
    return None


def _choice_validator(*choices: str) -> Validator:
    def check(raw: str) -> "str | None":
        if raw.strip().lower() not in choices:
            return f"expected one of {'/'.join(choices)}, got {raw!r}"
        return None

    return check


def _fault_spec_validator(raw: str) -> "str | None":
    from . import faultinject

    try:
        faultinject.FaultPlane.parse(raw)  # type: ignore[no-untyped-call]
    except ValueError as e:
        return str(e)
    return None


def _path_validator(raw: str) -> "str | None":
    return None  # any string is a path; existence is created on demand


def _label_value_validator(raw: str) -> "str | None":
    # the value lands verbatim inside Prometheus label bodies and file
    # paths (the fleet's per-worker session namespace): keep it to a
    # conservative identifier charset
    import re

    if not re.fullmatch(r"[A-Za-z0-9._-]{1,64}", raw.strip()):
        return (
            "expected a label-safe identifier "
            "([A-Za-z0-9._-], at most 64 chars), got " + repr(raw)
        )
    return None


def _slo_objectives_validator(raw: str) -> "str | None":
    from . import slo

    try:
        slo.parse_objectives(raw)
    except ValueError as e:
        return str(e)
    return None


# name -> validator(raw) returning an error string or None. The ONE
# catalogue of KSS_* configuration (docs/environment-variables.md).
KNOWN: "dict[str, Validator]" = {
    # serving stack
    "KSS_ENCODING_CACHE_CAP": _int_validator(1),
    # the encoded-cluster dtype policy (engine/encode.py policy_from_env,
    # docs/performance.md "Encoding widths"): "packed" stores the cluster
    # tensors bitpacked/narrowed with in-kernel unpack; placements stay
    # byte-identical to the default int32 plane. Empty = tpu32.
    "KSS_DTYPE_POLICY": _choice_validator("", "exact", "i32", "tpu32", "packed"),
    # the gang engine's serving-path evaluation chunk (server/service.py
    # gang_chunk): compact mode's skip-settled granularity on the fused
    # fixpoint AND the record path's replay evaluation; placements are
    # chunk-invariant, so this is a pure performance knob (default 64)
    "KSS_GANG_CHUNK": _int_validator(1),
    "KSS_NO_SPECULATIVE_COMPILE": _bool_validator,
    "KSS_JAX_CACHE_DIR": _path_validator,
    # the persistent AOT bundle store (utils/bundles.py): serialize
    # every broker-jitted program's compiled executable to disk and
    # deserialize it on the next boot instead of re-lowering; the
    # directory defaults to a sibling of kss-fingerprints.json in the
    # compile cache dir
    "KSS_AOT_BUNDLES": _bool_validator,
    "KSS_BUNDLE_DIR": _path_validator,
    # telemetry plane
    "KSS_TRACE": _bool_validator,
    "KSS_TRACE_RING_CAP": _int_validator(1),
    # cross-process trace-context propagation (defaults on whenever a
    # recorder is active; =0 keeps spans local to each process)
    "KSS_TRACE_PROPAGATE": _bool_validator,
    # the fleet & memory observatory (utils/fleetstats.py): per-pass
    # device-HBM + cluster-quality sampling into a bounded ring, served
    # by GET /api/v1/timeseries / Prometheus gauges / the dashboard;
    # SAMPLE records every Nth pass; HEADROOM_BYTES gates speculative
    # compiles on free device memory
    "KSS_FLEET_STATS": _bool_validator,
    "KSS_FLEET_RING_CAP": _int_validator(1),
    "KSS_FLEET_SAMPLE": _int_validator(1),
    "KSS_SPEC_MEM_HEADROOM_BYTES": _int_validator(0),
    # the SLO plane (utils/slo.py, docs/observability.md): per-tenant
    # objectives over already-recorded signals, multi-window burn-rate
    # alerts (pending -> firing -> resolved), and the alert history
    # ring served by GET /api/v1/alerts; OBJECTIVES is a strict grammar
    # over the default set (e.g. "passLatency:target=0.999,threshold=0.5")
    "KSS_SLO": _bool_validator,
    "KSS_SLO_OBJECTIVES": _slo_objectives_validator,
    "KSS_SLO_WINDOW_FAST_S": _float_validator(1.0),
    "KSS_SLO_WINDOW_SLOW_S": _float_validator(1.0),
    "KSS_SLO_BURN_FAST": _float_validator(0.0),
    "KSS_SLO_BURN_SLOW": _float_validator(0.0),
    "KSS_SLO_ALERT_FOR_S": _float_validator(0.0),
    "KSS_SLO_ALERT_RING_CAP": _int_validator(1),
    # histogram exemplar capture (utils/metrics.py): on by default —
    # any FALSY spelling disables attaching the causal pass id to
    # histogram buckets (the ?format=openmetrics exemplar source)
    "KSS_EXEMPLARS": _bool_validator,
    # run supervision
    "KSS_COMPILE_DEADLINE_S": _float_validator(0.0),
    "KSS_COMPILE_RETRIES": _int_validator(0),
    "KSS_COMPILE_BACKOFF_S": _float_validator(0.0),
    "KSS_COMPILE_COOLDOWN_PASSES": _int_validator(1),
    "KSS_COMPILE_COOLDOWN_TTL_S": _float_validator(0.0),
    # execution ladder + graceful drain (docs/resilience.md)
    "KSS_DISPATCH_DEADLINE_S": _float_validator(0.0),
    "KSS_DISPATCH_RETRIES": _int_validator(0),
    "KSS_DRAIN_DEADLINE_S": _float_validator(0.0),
    "KSS_FAULT_INJECT": _fault_spec_validator,
    "KSS_FAULT_INJECT_SEED": _int_validator(),
    # static analysis / debug tooling (docs/static-analysis.md): wrap
    # the serving stack's known locks in the runtime lock-order witness
    # (utils/locking.py) — raises on an acquisition-order inversion
    "KSS_LOCK_CHECK": _bool_validator,
    # the guarded-state witness (KSS6xx, utils/locking.py): wrap the
    # statically-inferred lock-claimed attributes in descriptors that
    # raise UnguardedAccess; SAMPLE checks every Nth access (default 1)
    "KSS_RACE_CHECK": _bool_validator,
    "KSS_RACE_CHECK_SAMPLE": _int_validator(1),
    # the jaxpr auditor (KSS7xx, analysis/jaxpr_audit.py): audit every
    # broker-jitted program's ClosedJaxpr on first trace
    "KSS_JAXPR_AUDIT": _bool_validator,
    # the program performance ledger (utils/ledger.py): record every
    # broker-jitted program's compile wall split, cost-model FLOPs/
    # bytes, memory bytes, calls, and dispatch seconds; SAMPLE blocks
    # on every Nth call for a warm device wall (0 = never block)
    "KSS_PROGRAM_LEDGER": _bool_validator,
    "KSS_PROGRAM_TIMING_SAMPLE": _int_validator(0),
    # `make lint` / the analysis CLI: missing ruff/mypy and a non-empty
    # allowlist become hard failures instead of notes (CI honesty)
    "KSS_LINT_STRICT": _bool_validator,
    # cross-tenant continuous batching (server/batchplane.py,
    # docs/sessions.md): stack bucket-compatible concurrent sessions'
    # passes onto ONE device dispatch; WINDOW_MS is the collection
    # window, MAX_WAIT_MS bounds any enrollee's added latency (default:
    # one window), MAX_SESSIONS caps the batch axis
    "KSS_BATCH": _bool_validator,
    "KSS_BATCH_WINDOW_MS": _float_validator(0.0),
    "KSS_BATCH_MAX_WAIT_MS": _float_validator(0.0),
    "KSS_BATCH_MAX_SESSIONS": _int_validator(1),
    # the horizontal serving fleet (kube_scheduler_simulator_tpu/fleet,
    # docs/fleet.md): WORKER_ID stamps every exposition sample with a
    # `worker` label (and the metrics JSON with `workerId`); the router
    # reads WORKERS (how many workers to spawn), DIR (the fleet state
    # root: per-worker session namespaces, logs), BASE_PORT (first
    # worker port; 0 = ephemeral), and PROBE_INTERVAL_S (the readyz
    # health-probe cadence)
    "KSS_WORKER_ID": _label_value_validator,
    "KSS_FLEET_WORKERS": _int_validator(1),
    "KSS_FLEET_DIR": _path_validator,
    "KSS_FLEET_BASE_PORT": _int_validator(0),
    "KSS_FLEET_PROBE_INTERVAL_S": _float_validator(0.05),
    # the fleet durability plane (server/durability.py,
    # server/replication.py, docs/fleet.md): JOURNAL arms per-session
    # write-ahead journaling of acknowledged store mutations;
    # JOURNAL_SYNC fsyncs each append and ships it inline to the ring
    # successors before the HTTP ack (zero-loss crash-kill); REPLICAS is
    # the successor count each session replicates to (0 = off);
    # REPLICATE_EVERY_S the full-unit ship cadence
    "KSS_FLEET_JOURNAL": _bool_validator,
    "KSS_FLEET_JOURNAL_SYNC": _bool_validator,
    "KSS_FLEET_REPLICAS": _int_validator(0),
    "KSS_FLEET_REPLICATE_EVERY_S": _float_validator(0.05),
    # router resilience (fleet/router.py, docs/resilience.md):
    # per-call deadline budgets, bounded idempotent retry with
    # exponential backoff, the per-worker circuit breaker, and the
    # re-home transport selector ("" / "auto" = file move when the
    # namespaces share a filesystem, "http" forces the cross-host
    # checkpoint transport)
    "KSS_FLEET_REQUEST_TIMEOUT_S": _float_validator(0.05),
    "KSS_FLEET_ADOPT_TIMEOUT_S": _float_validator(0.05),
    "KSS_FLEET_RETRIES": _int_validator(0),
    "KSS_FLEET_RETRY_BACKOFF_S": _float_validator(0.0),
    "KSS_FLEET_BREAKER_FAILURES": _int_validator(1),
    "KSS_FLEET_BREAKER_OPEN_S": _float_validator(0.0),
    "KSS_FLEET_TRANSPORT": _choice_validator("", "auto", "http"),
    # the router's bounded per-request ring (GET /api/v1/fleet/requests)
    "KSS_FLEET_REQUEST_RING_CAP": _int_validator(1),
    # session plane (docs/sessions.md)
    "KSS_MAX_SESSIONS": _int_validator(1),
    "KSS_MAX_PENDING_PODS_PER_SESSION": _int_validator(0),
    "KSS_MAX_CONCURRENT_PASSES": _int_validator(1),
    "KSS_SESSION_IDLE_EVICT_S": _float_validator(0.0),
    "KSS_SESSION_DIR": _path_validator,
    "KSS_SSE_MAX_SUBSCRIBERS": _int_validator(1),
}


def check_env(env: "Mapping[str, str] | None" = None) -> list[str]:
    """Validate every KSS_* variable in `env` (default: os.environ).
    Returns a list of human-readable problems — empty means the
    environment parses cleanly. Unset variables are never errors."""
    env = os.environ if env is None else env
    problems: list[str] = []
    for name, validator in KNOWN.items():
        raw = env.get(name)
        if raw is None or raw == "":
            continue
        err = validator(raw)
        if err:
            problems.append(f"{name}={raw!r}: {err}")
    for name in sorted(env):
        if name.startswith("KSS_") and name not in KNOWN:
            problems.append(
                f"{name}: unknown KSS_* variable (typo? see "
                f"docs/environment-variables.md)"
            )
    return problems


def fail_fast(env: "Mapping[str, str] | None" = None) -> None:
    """Entry-point gate: print every env problem and exit 2. A clear
    refusal at boot beats a silently-defaulted knob or a ValueError deep
    inside the first request handler."""
    problems = check_env(env)
    if not problems:
        return
    for p in problems:
        print(f"environment: {p}", file=sys.stderr)
    raise SystemExit(2)
