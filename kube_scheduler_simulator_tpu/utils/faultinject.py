"""Engine-internal fault injection — the deterministic fault plane.

PR 1 injects faults *into the cluster* (node fail/drain/cordon timelines,
scenario/chaos.py); this module injects faults *into the engine itself*,
so the run-supervision ladder (docs/resilience.md) — compile retry with
backoff, the compile watchdog, eager fallback, speculative-worker crash
containment, checkpoint/resume after a kill — is exercisable by ordinary
CPU pytest instead of waiting for a real wedged XLA compile or a dying
background thread.

Grammar (env ``KSS_FAULT_INJECT``, comma-separated ``site:value``):

    KSS_FAULT_INJECT=compile_fail:0.3,compile_slow:5s,device_error:0.1

  * probability sites — ``value`` is a float in [0, 1]: each time the
    site fires, a seeded draw decides whether to raise `InjectedFault`:
      - ``compile_fail``  — the broker's compile point (request-thread
        builds in `CompileBroker.get_resilient` AND background
        speculative builds);
      - ``device_error``  — the serving layer's device-dispatch point
        (the top of a scheduling pass dispatch); classified as a device
        fault by the EXECUTION ladder (docs/resilience.md): retried,
        then mesh-shrunk, then failed over to CPU — never fatal;
      - ``device_lost``   — the same dispatch point, modeling outright
        device loss (the accelerator vanished, not a transient error);
        walks the same execution ladder. Both device sites stop firing
        once the service is on the CPU-failover rung — they model the
        accelerator, and that rung no longer touches it;
      - ``worker_crash``  — the broker's speculative worker loop (the
        crash the hardened worker must contain);
      - ``net_drop``      — the fleet router's `_request` chokepoint,
        BEFORE the request is sent: the connection fails and the worker
        never sees the request (a dropped SYN / refused connect);
      - ``net_partition`` — the same chokepoint, AFTER the worker has
        processed the request: the response is discarded and the caller
        sees a connection error — the request *happened* but nobody
        knows (the partition that punishes non-idempotent retries);
      - ``worker_kill``   — the router-side chaos kill: the target
        worker process is SIGKILL'd (no drain, no snapshot) and the
        in-flight request fails — the crash the durability plane's
        replicated journal must absorb (docs/fleet.md);
  * duration sites — ``value`` is a duration (``5s``, ``250ms``): the
    site sleeps that long every time it fires:
      - ``compile_slow``  — injected compile latency, the wedged-compile
        stand-in the KSS_COMPILE_DEADLINE_S watchdog trips on;
      - ``dispatch_hang`` — injected dispatch latency at the serving
        layer's device-dispatch point, the wedged-dispatch stand-in the
        KSS_DISPATCH_DEADLINE_S watchdog trips on;
      - ``net_delay``     — injected router→worker network latency at
        the `_request` chokepoint (the slow-network row of the fleet
        failure matrix; the per-request deadline budget trips on it).

Determinism: every probability site draws from its own
``random.Random(f"kss-fault:{seed}:{site}")`` stream (seed from
``KSS_FAULT_INJECT_SEED``, default 0) — no global RNG, no wall clock, so
a single-threaded call sequence draws identically across runs. Sites are
independent streams: adding one never reshuffles another. NOTE: draws
from concurrent threads (request thread vs speculation worker) interleave
nondeterministically — specs that need strict determinism use 0/1
probabilities, which are interleaving-proof.

The plane is process-global and read per fire point from the
environment, cached on the raw env string — tests flip it with
``monkeypatch.setenv`` and the next fire sees the new plane; `activate`
overrides the environment entirely (unit tests, embedded drivers).
"""

from __future__ import annotations

import os
import random
import threading
import time

from . import locking, telemetry

PROBABILITY_SITES = (
    "compile_fail", "device_error", "device_lost", "worker_crash",
    # the fleet network sites (docs/fleet.md): fired at the router's
    # `_request` chokepoint, never inside the engine
    "net_drop", "net_partition", "worker_kill",
)
DURATION_SITES = ("compile_slow", "dispatch_hang", "net_delay")

ENV_VAR = "KSS_FAULT_INJECT"
SEED_VAR = "KSS_FAULT_INJECT_SEED"


class InjectedFault(RuntimeError):
    """A fault raised by the fault plane, never by real engine state."""

    def __init__(self, site: str):
        super().__init__(f"injected fault: {site}")
        self.site = site


@locking.guard_inferred
class FaultPlane:
    """One parsed fault-injection spec: per-site rules + seeded streams."""

    def __init__(self, rules: "dict[str, float]", seed: int = 0):
        for site in rules:
            if site not in PROBABILITY_SITES + DURATION_SITES:
                raise ValueError(
                    f"unknown fault site {site!r} (one of "
                    f"{'/'.join(PROBABILITY_SITES + DURATION_SITES)})"
                )
        self.rules = dict(rules)
        self.seed = int(seed)
        self._lock = locking.make_lock("faultinject.plane")
        self._rng = {
            site: random.Random(f"kss-fault:{self.seed}:{site}")
            for site in PROBABILITY_SITES
        }
        # how many faults each site actually injected (raises + sleeps)
        self.injected: dict[str, int] = {site: 0 for site in self.rules}

    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlane":
        """Parse the ``site:value,site:value`` grammar. Strict, like
        ChaosSpec: a typo'd spec raises at parse time, not as a silently
        fault-free run."""
        rules: dict[str, float] = {}
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            site, sep, raw = part.partition(":")
            site = site.strip()
            raw = raw.strip()
            if not sep or not raw:
                raise ValueError(
                    f"fault-inject entry {part!r}: expected site:value"
                )
            if site in DURATION_SITES:
                rules[site] = _parse_duration_s(site, raw)
            elif site in PROBABILITY_SITES:
                try:
                    p = float(raw)
                except ValueError:
                    raise ValueError(
                        f"fault site {site}: probability {raw!r} is not a number"
                    ) from None
                if not 0.0 <= p <= 1.0:
                    raise ValueError(
                        f"fault site {site}: probability {p} outside [0, 1]"
                    )
                rules[site] = p
            else:
                raise ValueError(
                    f"unknown fault site {site!r} (one of "
                    f"{'/'.join(PROBABILITY_SITES + DURATION_SITES)})"
                )
        return cls(rules, seed=seed)

    # -- fire points --------------------------------------------------------

    def maybe_raise(self, site: str) -> None:
        """Raise `InjectedFault` when the site's seeded draw says so."""
        p = self.rules.get(site, 0.0)
        if p <= 0.0:
            return
        with self._lock:
            hit = p >= 1.0 or self._rng[site].random() < p
            if hit:
                self.injected[site] = self.injected.get(site, 0) + 1
        if hit:
            # the injected fault lands on the flight recorder's timeline
            # (carrying the current pass id) so a chaos run's trace shows
            # WHERE each fault bit, not just that it did
            telemetry.instant("fault.injected", site=site)
            raise InjectedFault(site)

    def delay(self, site: str) -> float:
        """Sleep the site's configured duration; returns seconds slept."""
        d = self.rules.get(site, 0.0)
        if d <= 0.0:
            return 0.0
        with self._lock:
            self.injected[site] = self.injected.get(site, 0) + 1
        telemetry.instant("fault.delay", site=site, seconds=d)
        time.sleep(d)
        return d

    def counts(self) -> dict:
        with self._lock:
            return {k: v for k, v in self.injected.items() if v}


def _parse_duration_s(site: str, raw: str) -> float:
    for suffix, scale in (("ms", 1e-3), ("s", 1.0)):
        if raw.endswith(suffix):
            body = raw[: -len(suffix)]
            try:
                d = float(body)
            except ValueError:
                break
            if d < 0:
                break
            return d * scale
    raise ValueError(
        f"fault site {site}: duration {raw!r} must be like '5s' or '250ms'"
    )


# -- the process-global active plane ----------------------------------------

# Thread-local scoped plane (the multi-tenant bulkhead, docs/sessions.md):
# a session created with its own fault spec enters `scoped(plane)` for the
# duration of each of its passes, so its storm fires on ITS request thread
# only — neighbors (and the env-configured plane) are untouched. The
# broker's speculative worker re-enters the arming thread's scope so a
# session's background builds draw from the same plane.
_tls = threading.local()


class scoped:
    """Make `plane` the active plane on THIS thread for the block,
    shadowing the env-configured (or `activate`d) process plane. Nests;
    restores the previous scope on exit."""

    __slots__ = ("_plane", "_prev")

    def __init__(self, plane: "FaultPlane | None"):
        self._plane = plane
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "scope", None)
        _tls.scope = (self._plane,)
        return self

    def __exit__(self, *exc):
        _tls.scope = self._prev
        return False


def scoped_active() -> "FaultPlane | None":
    """The thread-locally scoped plane, or None when this thread is not
    inside `scoped` (callers that capture a scope to re-enter on a
    worker thread — CompileBroker.speculate)."""
    sc = getattr(_tls, "scope", None)
    return sc[0] if sc is not None else None


_lock = locking.make_lock("faultinject.registry")
# (raw env string, seed string) -> plane parsed from them; an explicit
# `activate` overrides the environment until `deactivate`
_cached: "tuple[tuple[str, str], FaultPlane | None] | None" = None
_override: "FaultPlane | None" = None
_overridden = False


def active() -> "FaultPlane | None":
    """The currently active plane, or None (the default: no injection).

    Reads KSS_FAULT_INJECT / KSS_FAULT_INJECT_SEED each call but reparses
    only when they change, so fire points are cheap enough for compile
    and dispatch paths. A malformed env spec raises here — at the first
    fire point — rather than being silently ignored: a fault-injection
    run that injects nothing is the worst failure mode this module has.

    A thread-local `scoped` plane (the session bulkhead) shadows both
    the override and the environment on its thread.
    """
    global _cached
    sc = getattr(_tls, "scope", None)
    if sc is not None:
        return sc[0]
    with _lock:
        if _overridden:
            return _override
        raw = os.environ.get(ENV_VAR, "")
        seed_raw = os.environ.get(SEED_VAR, "0")
        key = (raw, seed_raw)
        if _cached is not None and _cached[0] == key:
            return _cached[1]
        if not raw.strip():
            plane = None
        else:
            try:
                seed = int(seed_raw)
            except ValueError:
                seed = 0
            plane = FaultPlane.parse(raw, seed=seed)
        _cached = (key, plane)
        return plane


def activate(plane: "FaultPlane | None") -> None:
    """Install `plane` as the active plane regardless of the environment
    (None = injection explicitly off). Until `deactivate`, the env vars
    are not consulted."""
    global _override, _overridden
    with _lock:
        _override = plane
        _overridden = True


def deactivate() -> None:
    """Drop any `activate` override; the environment rules again."""
    global _override, _overridden
    with _lock:
        _override = None
        _overridden = False
