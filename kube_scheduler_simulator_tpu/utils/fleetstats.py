"""The fleet & memory observatory: per-device HBM accounting + per-pass
cluster-quality time-series (docs/observability.md).

PR 5 gave the serving stack spans (*where a millisecond went*) and PR 10
per-program cost (*what a compile/dispatch costs*); this module supplies
the third leg every training/inference stack has — live **resource**
telemetry: device memory and fleet-quality gauges sampled once per
scheduling pass, cheap enough to leave on, bounded by construction.

Two sample halves:

  * **device memory** — ``device.memory_stats()`` HBM bytes-in-use /
    peak / limit per local device (None on backends without an
    allocator report, e.g. CPU), plus a **live-buffer census** that
    attributes retained device arrays to their owners: the
    delta-encoder's retained encoding (`engine/delta.py` keeps the last
    `EncodedCluster` on device), the broker's warm-engine executables
    (estimated from the PR 10 ledger's ``memory_analysis`` bytes), the
    process-wide ``jax.live_arrays()`` total, and the session count —
    the answer to "who is holding the HBM" that ROADMAP #3's
    multi-chip sharding decisions are otherwise blind to.

  * **cluster quality** — jitted masked reductions over the pass's
    already-encoded cluster tensors (`_quality`, routed through
    ``broker.jit`` with a KSS7xx audit label so the program is
    contract-checked like every other engine program): a per-node
    utilization histogram, a **fragmentation index** per resource
    (``1 - largest-free-block / total-free`` — 0 when one node could
    absorb the fleet's whole slack, →1 as free capacity shatters into
    unusably small shards), the pending-queue depth from the encoded
    assignment, and host-side pending-age percentiles (first-seen
    tracking per (session, pod)).

Samples land in a bounded ring (`FleetRecorder` — the `SpanRecorder`
pattern: short lock hold, subscribers notified outside the lock) and
surface four ways: ``GET /api/v1/timeseries`` (windowed, per-session
nested routes), the ``kss_device_hbm_*`` / ``kss_fleet_*`` Prometheus
gauges, Perfetto counter tracks (``fleet.*`` / ``hbm.bytesInUse``), and
the dashboard's Observability tab sparklines fed by the ``fleet`` SSE
event (server/webui.py).

One robustness consumer closes the loop: the broker's speculative
compile worker calls `speculation_memory_ok()` before arming a
background build — with ``KSS_SPEC_MEM_HEADROOM_BYTES`` set, a device
whose free HBM is below the floor skips speculation instead of letting
a background XLA allocation OOM a serving process.

Off by default (``KSS_FLEET_STATS``), like every observer in this tree;
when armed, a pass pays one warm jitted reduction + one small host
fetch every ``KSS_FLEET_SAMPLE``-th pass, and placements are pinned
byte-identical to a stats-off run (the ``KSS_PROGRAM_TIMING_SAMPLE``
sampling-invariance precedent, tests/test_fleetstats.py).
"""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp

from . import locking, telemetry
from .envcheck import TRUTHY as _TRUE

ENV_VAR = "KSS_FLEET_STATS"
CAP_VAR = "KSS_FLEET_RING_CAP"
SAMPLE_VAR = "KSS_FLEET_SAMPLE"
HEADROOM_VAR = "KSS_SPEC_MEM_HEADROOM_BYTES"

DEFAULT_RING_CAP = 1024

# per-node utilization histogram bins: [0, 0.1) ... [0.9, 1.0]
UTIL_BINS = 10


def _lenient_int(raw: str, default: int, minimum: int) -> int:
    """The shared lenient-knob parse: a typo must never disable the
    observatory or blow a bound (the telemetry ring-cap contract)."""
    try:
        v = int(raw) if raw else default
    except ValueError:
        return default
    return v if v >= minimum else default


def ring_capacity_from_env() -> int:
    return _lenient_int(os.environ.get(CAP_VAR, ""), DEFAULT_RING_CAP, 1)


def sample_every_from_env() -> int:
    """Sample cadence from KSS_FLEET_SAMPLE: record every Nth pass
    (default 1 — every pass; the quality reduction is one warm program
    plus a small host fetch)."""
    return _lenient_int(os.environ.get(SAMPLE_VAR, ""), 1, 1)


def spec_mem_headroom_bytes() -> int:
    """The speculation HBM floor from KSS_SPEC_MEM_HEADROOM_BYTES:
    0 (the default) disables the gate — speculation arms regardless of
    memory pressure, the historical behavior."""
    return _lenient_int(os.environ.get(HEADROOM_VAR, ""), 0, 0)


# -- device memory -------------------------------------------------------------


def device_memory(devices=None) -> "list[dict]":
    """Per-device allocator stats: ``{"id", "platform", "bytesInUse",
    "peakBytesInUse", "bytesLimit"}`` — byte fields present only when
    the backend reports them (`device.memory_stats()` answers None on
    CPU). Never raises: a dead backend yields an empty list, not a
    failed sample."""
    try:
        if devices is None:
            devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — a dead backend still has a sample
        return []
    out: list[dict] = []
    for d in devices:
        entry: dict = {
            "id": int(getattr(d, "id", len(out))),
            "platform": str(getattr(d, "platform", "")),
        }
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — optional per backend
            stats = None
        if stats:
            if stats.get("bytes_in_use") is not None:
                entry["bytesInUse"] = int(stats["bytes_in_use"])
            if stats.get("peak_bytes_in_use") is not None:
                entry["peakBytesInUse"] = int(stats["peak_bytes_in_use"])
            limit = stats.get("bytes_limit")
            if limit is None:
                limit = stats.get("bytes_reservable_limit")
            if limit is not None:
                entry["bytesLimit"] = int(limit)
        out.append(entry)
    return out


def hbm_headroom_bytes() -> "int | None":
    """The tightest device's free HBM — min over devices of
    (bytesLimit - bytesInUse) — or None when no device reports both
    (CPU backends): the speculation gate cannot block what it cannot
    measure."""
    head: "list[int]" = []
    for d in device_memory():
        if "bytesLimit" in d and "bytesInUse" in d:
            head.append(d["bytesLimit"] - d["bytesInUse"])
    return min(head) if head else None


def speculation_memory_ok() -> bool:
    """The broker's pre-arm check (utils/broker.py): False when
    KSS_SPEC_MEM_HEADROOM_BYTES is set and some device's free HBM is
    below it — a background compile's workspace must never be the
    allocation that OOMs a serving process. Unmeasurable headroom
    (no allocator stats) passes: the gate is a guard, not a jailer."""
    need = spec_mem_headroom_bytes()
    if need <= 0:
        return True
    head = hbm_headroom_bytes()
    return head is None or head >= need


# -- the live-buffer census ----------------------------------------------------

# the session plane registers its id lister here (server/sessions.py)
# so the census can report "how many tenants share this memory" and the
# Prometheus exposition can drop deleted tenants' series, without the
# utils layer importing the server; None until a SessionManager exists
_session_ids_fn = None


def set_session_provider(fn) -> None:
    """Register the known-session-id lister (the SessionManager's; the
    most recent manager wins — one serving process owns one plane).
    `fn()` answers an iterable of session ids, or None when the plane
    is gone (the manager registers a weakref-backed closure so a
    shut-down server never stays reachable through this hook)."""
    global _session_ids_fn
    _session_ids_fn = fn


def known_sessions() -> "set[str] | None":
    """The session plane's known ids, or None when no plane is
    registered (standalone services, tests) — callers treat None as
    "no filter", never as an empty plane."""
    fn = _session_ids_fn
    if fn is None:
        return None
    try:
        ids = fn()
    except Exception:  # noqa: BLE001 — census is best-effort
        return None
    return None if ids is None else {str(s) for s in ids}


def _tree_bytes(obj) -> int:
    """Total device bytes of a pytree's array leaves (the retained
    encoding census)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(obj):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


def buffer_census(service=None) -> dict:
    """Attribute retained device memory to its owners: the process-wide
    ``jax.live_arrays()`` total, the delta-encoder's retained encoding
    (when `service` is given), the broker's warm-engine count plus the
    ledger's memory-analysis byte estimate of their executables, and
    the session count. Every field is best-effort — the census
    describes memory, it must never hold any of it hostage."""
    out: dict = {}
    try:
        live = jax.live_arrays()
        out["liveArrays"] = len(live)
        out["liveBytes"] = sum(
            int(getattr(a, "nbytes", 0) or 0) for a in live
        )
    except Exception:  # noqa: BLE001 — census is optional per backend
        pass
    if service is not None:
        try:
            st = service._delta._st
            if st is not None:
                out["deltaRetainedBytes"] = _tree_bytes(
                    (st.enc.arrays, st.enc.state0)
                )
        except Exception:  # noqa: BLE001
            pass
        try:
            out["warmEngines"] = service.broker.health()["warmEngines"]
        except Exception:  # noqa: BLE001
            pass
    try:
        from . import ledger as ledger_mod

        mem = ledger_mod.LEDGER.memory_bytes_total()
        if mem is not None:
            out["ledgerMemoryBytes"] = mem
    except Exception:  # noqa: BLE001
        pass
    known = known_sessions()
    if known is not None:
        out["sessions"] = len(known)
    return out


# -- the cluster-quality program -----------------------------------------------


def _quality(node_alloc, node_mask, requested, assignment, pod_mask):
    """Masked reductions over one pass's encoded cluster tensors —
    pure array code (KSS3xx), traced once per shape bucket:

      * per-node utilization = max over resources of requested/alloc
        (the dominant-resource view), histogrammed into UTIL_BINS;
      * fragmentation index per resource: 1 - largest-free-block /
        total-free — how shattered the fleet's slack is;
      * pending depth: real pods with no assignment.
    """
    f = jnp.float32
    alloc = jnp.asarray(node_alloc, f)
    used = jnp.asarray(requested, f)
    nmask = jnp.asarray(node_mask, bool)
    has = (alloc > 0) & nmask[:, None]
    ratio = jnp.where(has, used / jnp.maximum(alloc, 1.0), 0.0)
    util = jnp.clip(jnp.max(ratio, axis=1), 0.0, 1.0)  # [N]
    n_real = jnp.maximum(jnp.sum(nmask), 1).astype(f)
    util_mean = jnp.sum(jnp.where(nmask, util, 0.0)) / n_real
    util_max = jnp.max(jnp.where(nmask, util, 0.0))
    bins = jnp.clip(
        (util * UTIL_BINS).astype(jnp.int32), 0, UTIL_BINS - 1
    )
    onehot = (bins[:, None] == jnp.arange(UTIL_BINS)[None, :]) & nmask[:, None]
    hist = jnp.sum(onehot, axis=0).astype(jnp.int32)
    free = jnp.where(has, jnp.maximum(alloc - used, 0.0), 0.0)
    largest = jnp.max(free, axis=0)  # [R]
    total = jnp.sum(free, axis=0)
    frag = jnp.where(
        total > 0, 1.0 - largest / jnp.where(total > 0, total, 1.0), 0.0
    )
    pending = jnp.sum(
        jnp.asarray(pod_mask, bool) & (assignment < 0)
    ).astype(jnp.int32)
    return hist, util_mean, util_max, frag, pending


_quality_jit = None
_quality_lock = locking.make_lock("fleet.jitwrap")


def _quality_program():
    """The jitted quality program, built once through `broker.jit` (the
    KSS7xx audit + ledger hook; the jit's internal signature cache
    handles shape-bucket reuse). Inside an eager-fallback pass the raw
    function is returned WITHOUT caching — an eager build must never
    poison the jitted slot (the delta-scatter precedent)."""
    from . import broker as broker_mod

    if broker_mod.eager_active():
        return _quality
    global _quality_jit
    if _quality_jit is None:
        with _quality_lock:
            if _quality_jit is None:
                _quality_jit = broker_mod.jit(
                    _quality,
                    audit={
                        "label": "fleet.quality",
                        # the histogram-bin axis is a static constant,
                        # not a capacity bucket; N/P/R ride the normal
                        # bucket check
                        "exempt": lambda args, kwargs: (UTIL_BINS,),
                        # inputs inherit the pass's dtype policy — under
                        # EXACT they are legitimately 64-bit (the
                        # reductions themselves compute in f32)
                        "allow_f64": True,
                    },
                )
    return _quality_jit


def _percentile(sorted_vals: "list[float]", q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = int(round(q * (len(sorted_vals) - 1)))
    return sorted_vals[idx]


# -- the sample ring -----------------------------------------------------------


@locking.guard_inferred
class FleetRecorder:
    """A bounded ring of fleet samples + live subscribers — the
    `SpanRecorder` shape: `push` holds the lock only to place the
    sample and advance the sequence; subscriber callbacks (the SSE
    route's `fleet` event feed) run OUTSIDE the lock."""

    def __init__(self, capacity: "int | None" = None):
        cap = ring_capacity_from_env() if capacity is None else int(capacity)
        if cap < 1:
            raise ValueError(f"ring capacity must be >= 1, got {cap}")
        self.capacity = cap
        self._lock = locking.make_lock("fleet.ring")
        self._ring: "list[dict | None]" = [None] * cap
        self._seq = 0
        self._subs: list = []
        # per-recorder sampling cadence state (KSS_FLEET_SAMPLE)
        self._pass_count = 0
        # (session, ns, name) -> monotonic first-seen-pending stamp:
        # the pending-age percentile source
        self._pending_seen: "dict[tuple, float]" = {}

    # -- writing ------------------------------------------------------------

    def push(self, sample: dict) -> None:
        with self._lock:
            sample = dict(sample)
            sample["seq"] = self._seq
            self._ring[self._seq % self.capacity] = sample
            self._seq += 1
            subs = tuple(self._subs) if self._subs else ()
        for fn in subs:
            try:
                fn(sample)
            except Exception:  # noqa: BLE001 — a dead subscriber never breaks a pass
                pass

    # -- reading ------------------------------------------------------------

    @property
    def emitted(self) -> int:
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self._seq - self.capacity)

    def __len__(self) -> int:
        with self._lock:
            return min(self._seq, self.capacity)

    def snapshot(self) -> "list[dict]":
        with self._lock:
            n = self._seq
            if n <= self.capacity:
                return list(self._ring[:n])
            i = n % self.capacity
            return self._ring[i:] + self._ring[:i]

    def drop_session(self, sid: str) -> None:
        """Purge a deleted session's pending-age bookkeeping (the
        session-plane DELETE path) — a dead tenant's first-seen stamps
        must not accumulate forever under session churn. Its historical
        ring samples stay: the time-series records what happened; the
        Prometheus exposition separately drops dead tenants via
        `known_sessions`."""
        with self._lock:
            for key in [k for k in self._pending_seen if k[0] == sid]:
                del self._pending_seen[key]

    def subscribe(self, fn) -> None:
        with self._lock:
            self._subs.append(fn)

    def unsubscribe(self, fn) -> None:
        with self._lock:
            try:
                self._subs.remove(fn)
            except ValueError:
                pass

    # -- the per-pass sampler -----------------------------------------------

    def sample_pass(self, service, enc, state, mode: str) -> "dict | None":
        """One per-pass sample over the pass's encoded tensors + final
        state (server/service.py calls this from the pass finish paths,
        inside the never-raise `_fleet_sample` guard). Honors the
        KSS_FLEET_SAMPLE cadence; returns the sample, or None when this
        pass was skipped. Read-only over the pass's arrays — placements
        are sampling-invariant by construction (test-pinned)."""
        with self._lock:
            self._pass_count += 1
            if (self._pass_count - 1) % sample_every_from_env():
                return None
        outs = _quality_program()(
            enc.arrays.node_alloc,
            enc.arrays.node_mask,
            state.requested,
            state.assignment,
            enc.arrays.pod_mask,
        )
        hist, util_mean, util_max, frag, pending, assignment = jax.device_get(
            (*outs, state.assignment)
        )
        session = service.session_id or "default"
        ages = self._pending_ages(session, enc, assignment)
        # the SLO plane's pendingAge observation point (utils/slo.py):
        # queue age is measured exactly once — here — and the plane
        # judges the p90 against its threshold (no second measurement
        # path). No-op with the plane off.
        service.metrics.record_pending_age(
            ages["p90Seconds"], ages["maxSeconds"]
        )
        frag_by_res = {
            name: round(float(frag[i]), 6)
            for i, name in enumerate(enc.resource_names)
            if i < len(frag)
        }
        frag_index = round(max(frag_by_res.values(), default=0.0), 6)
        devices = device_memory()
        hbm: dict = {}
        for key in ("bytesInUse", "peakBytesInUse", "bytesLimit"):
            vals = [d[key] for d in devices if key in d]
            if vals:
                hbm[key] = sum(vals)
        sample = {
            "wallTime": round(time.time(), 3),
            "passId": telemetry.current_pass_id(),
            "session": session,
            "mode": mode,
            "devices": devices,
            "hbm": hbm,
            "buffers": buffer_census(service),
            "fleet": {
                "nodes": enc.n_nodes,
                "pendingPods": int(pending),
                "utilization": {
                    "mean": round(float(util_mean), 6),
                    "max": round(float(util_max), 6),
                    "histogram": [int(x) for x in hist],
                },
                "fragmentation": frag_by_res,
                "fragmentationIndex": frag_index,
                "pendingAges": ages,
            },
        }
        self.push(sample)
        # Perfetto counter tracks (no-op when tracing is off): the
        # fleet gauges next to the pass spans that moved them
        telemetry.counter("fleet.pendingPods", int(pending))
        telemetry.counter("fleet.utilizationMax", float(util_max))
        telemetry.counter("fleet.fragmentationIndex", frag_index)
        mem = hbm.get("bytesInUse", sample["buffers"].get("liveBytes"))
        if mem is not None:
            telemetry.counter("hbm.bytesInUse", float(mem))
        return sample

    def _pending_ages(self, session: str, enc, assignment) -> dict:
        """Pending-age percentiles from first-seen tracking: a pod
        enters the map the first sample it appears pending (keyed by
        session so tenants never alias) and leaves when it binds or
        vanishes."""
        now = time.monotonic()
        pending_keys = {
            (session, *enc.pod_keys[p])
            for p in range(enc.n_pods)
            if int(assignment[p]) < 0
        }
        with self._lock:
            for key in [
                k
                for k in self._pending_seen
                if k[0] == session and k not in pending_keys
            ]:
                del self._pending_seen[key]
            ages = sorted(
                now - self._pending_seen.setdefault(key, now)
                for key in pending_keys
            )
        return {
            "count": len(ages),
            "p50Seconds": round(_percentile(ages, 0.5), 6),
            "p90Seconds": round(_percentile(ages, 0.9), 6),
            "maxSeconds": round(ages[-1], 6) if ages else 0.0,
        }


# -- the process-global active recorder ---------------------------------------

_lock = locking.make_lock("fleet.config")
# (KSS_FLEET_STATS, KSS_FLEET_RING_CAP) raw strings -> recorder; the
# same lock-free fast path as telemetry.active(): both globals hold one
# immutable tuple swapped whole under the GIL
_cached: "tuple[tuple[str, str], FleetRecorder | None] | None" = None
_override_state: "tuple[bool, FleetRecorder | None]" = (False, None)


def active() -> "FleetRecorder | None":
    """The active fleet recorder, or None (the default: stats off).
    Re-reads KSS_FLEET_STATS / KSS_FLEET_RING_CAP per call but rebuilds
    only when the raw strings change — the disabled path is two dict
    probes and a tuple compare."""
    global _cached
    overridden, override = _override_state
    if overridden:
        return override
    key = (os.environ.get(ENV_VAR, ""), os.environ.get(CAP_VAR, ""))
    cached = _cached
    if cached is not None and cached[0] == key:
        return cached[1]
    with _lock:
        overridden, override = _override_state
        if overridden:
            return override
        cached = _cached
        if cached is not None and cached[0] == key:
            return cached[1]
        rec = (
            FleetRecorder(ring_capacity_from_env())
            if key[0].strip().lower() in _TRUE
            else None
        )
        _cached = (key, rec)
        return rec


def enabled() -> bool:
    return active() is not None


def activate(recorder: "FleetRecorder | None") -> None:
    """Install `recorder` regardless of the environment (None = stats
    explicitly off) until `deactivate` — tests and the smoke tooling."""
    global _override_state
    with _lock:
        _override_state = (True, recorder)


def deactivate() -> None:
    global _override_state
    with _lock:
        _override_state = (False, None)


def drop_session(sid: str) -> None:
    """Forward a session deletion to the active recorder's bookkeeping
    (the session plane's DELETE path, next to the ledger's
    `drop_session`); no-op with stats off."""
    rec = active()
    if rec is not None:
        rec.drop_session(sid)


# -- Prometheus exposition -----------------------------------------------------


def render_prometheus(recorder: "FleetRecorder | None" = None) -> str:
    """The ``kss_device_hbm_*`` / ``kss_fleet_*`` gauge families from
    the recorder's freshest samples — device families from the latest
    sample overall, fleet families one series per session (each
    session's latest sample). Appended to the metrics exposition by the
    serving layer (server/httpserver.py); empty string when stats are
    off or nothing has been sampled yet."""
    rec = active() if recorder is None else recorder
    if rec is None:
        return ""
    samples = rec.snapshot()
    if not samples:
        return ""
    from .metrics import _fmt_value

    latest = samples[-1]
    by_session: "dict[str, dict]" = {}
    for s in samples:
        by_session[s.get("session") or "default"] = s
    lines: list[str] = []

    def device_family(name: str, help_text: str, key: str) -> None:
        rows = [
            (d["id"], d[key]) for d in latest.get("devices", ()) if key in d
        ]
        if not rows:
            return
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} gauge")
        for dev_id, v in rows:
            lines.append(f'{name}{{device="{dev_id}"}} {_fmt_value(v)}')

    device_family(
        "kss_device_hbm_bytes_in_use",
        "Device memory in use (device.memory_stats bytes_in_use).",
        "bytesInUse",
    )
    device_family(
        "kss_device_hbm_peak_bytes",
        "Peak device memory in use since process start.",
        "peakBytesInUse",
    )
    device_family(
        "kss_device_hbm_bytes_limit",
        "Device memory limit reported by the allocator.",
        "bytesLimit",
    )

    # dead tenants' series must not outlive them in the exposition: a
    # deleted session's last sample lingers in the ring (history), but
    # its frozen gauges would mislead alerting — filter to the session
    # plane's known ids (no plane registered = no filter)
    known = known_sessions()

    def fleet_family(name: str, help_text: str, value_of) -> None:
        rows = []
        for sid in sorted(by_session):
            if known is not None and sid not in known:
                continue
            v = value_of(by_session[sid])
            if v is not None:
                rows.append((sid, v))
        if not rows:
            return
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} gauge")
        for sid, v in rows:
            lines.append(f'{name}{{session="{sid}"}} {_fmt_value(v)}')

    fleet_family(
        "kss_fleet_pending_pods",
        "Pending-queue depth at the session's last sampled pass.",
        lambda s: s["fleet"]["pendingPods"],
    )
    fleet_family(
        "kss_fleet_utilization_mean",
        "Mean per-node dominant-resource utilization (last sample).",
        lambda s: s["fleet"]["utilization"]["mean"],
    )
    fleet_family(
        "kss_fleet_utilization_max",
        "Max per-node dominant-resource utilization (last sample).",
        lambda s: s["fleet"]["utilization"]["max"],
    )
    fleet_family(
        "kss_fleet_fragmentation_index",
        "1 - largest-free-block / total-free, worst resource "
        "(last sample).",
        lambda s: s["fleet"]["fragmentationIndex"],
    )
    def global_sample(name: str, mtype: str, help_text: str, value) -> None:
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        lines.append(f"{name} {_fmt_value(value)}")

    live = latest.get("buffers", {}).get("liveBytes")
    if live is not None:
        global_sample(
            "kss_fleet_live_buffer_bytes",
            "gauge",
            "Total bytes of live jax arrays (the buffer census).",
            live,
        )
    global_sample(
        "kss_fleet_samples_total",
        "counter",
        "Fleet samples recorded since the recorder was born.",
        rec.emitted,
    )
    return "\n".join(lines) + "\n"
