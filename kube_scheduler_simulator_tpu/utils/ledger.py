"""The program performance observatory: per-program cost ledger +
cold-start phase accounting (docs/observability.md).

ROADMAP #1 names the frontier — compiles cost 80–162 s per engine shape,
MFU sits at ~1e-6, and the wished-for headline is *time-to-first-
scheduled-pod from cold* — but none of that was observable on the
serving path: XLA cost/memory analysis only ran inside bench.py, compile
walls aggregated into one ``stallSeconds`` counter, and nothing diffed
across runs. Two instruments fix that:

**Per-program ledger** (``KSS_PROGRAM_LEDGER=1``, hooked into
``utils/broker.jit`` next to the KSS7xx auditor): every broker-jitted
program records, keyed ``(site label, compile fingerprint)``:

  * compile wall with the **lowering vs backend-compile split** — the
    first call of each argument signature goes through the AOT path
    (``trace().lower()`` timed, then ``.compile()`` timed) and later
    calls dispatch through the compiled executable, so the split costs
    no second compile;
  * ``compiled.cost_analysis()`` FLOPs/bytes and ``memory_analysis()``
    temp/argument/output bytes — XLA's own cost model of the program;
  * call count and cumulative dispatch seconds (async-dispatch wall —
    the host-side cost of driving the program);
  * a **sampled warm device wall**: ``KSS_PROGRAM_TIMING_SAMPLE=N``
    blocks on the result every Nth call (first/compile call excluded)
    — off by default so the async hot path never synchronizes;
  * derived per-program **MFU** (``utils/metrics.PEAK_FLOPS_PER_S``)
    on known accelerators, from the cost-model FLOPs over the sampled
    warm wall;
  * per-session call attribution via the telemetry session labels.

The ledger persists as ``kss-program-ledger.json`` (format
``kss-program-ledger/v1``), a sibling of the KSS715 fingerprint
baseline, and ``diff_ledger`` flags compile-seconds drift (KSS731),
FLOPs drift (KSS732), and vanished/new programs (KSS733/KSS734) across
runs — the ``analysis ledger-diff`` CLI subcommand turns that into a
perf-regression gate (tools/perf_smoke.py runs it).

**Cold-start phase accounting** (`COLD_START`): process-global
first-occurrence marks — boot probe → first encode → first compile →
first pass — each emitted as a ``coldstart.*`` telemetry instant and
summarized as ``timeToFirstPassSeconds`` in the ``coldStart`` block of
``GET /api/v1/metrics`` (schema v3) and the ``bench.py --cold-start``
headline. The origin is this module's import (the first package import
of the process), so the numbers answer "how long from process start
until the first pod was scheduled" — the gate ROADMAP #1's AOT-bundle
work will be measured against.

Everything here is **off the hot path by default**: the ledger arms per
jit-wrap via the env switch (like ``KSS_JAXPR_AUDIT``), cold-start
marks are one dict probe under a leaf lock per site, and warm-timing
samples never happen unless ``KSS_PROGRAM_TIMING_SAMPLE`` asks.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from . import locking, telemetry
from .envcheck import env_truthy
from ..analysis.core import Finding

LEDGER_FORMAT = "kss-program-ledger/v1"
LEDGER_BASENAME = "kss-program-ledger.json"

ENV_VAR = "KSS_PROGRAM_LEDGER"
SAMPLE_VAR = "KSS_PROGRAM_TIMING_SAMPLE"

# the session key unattributed calls land under (sessionless services,
# bench, the lifecycle CLI) — matches the serving plane's implicit
# default session id (server/sessions.py)
DEFAULT_SESSION_KEY = "default"

# diff_ledger defaults: a compile-seconds regression must exceed BOTH
# the ratio and the absolute floor before it flags — compile walls are
# noisy run to run, and a 0.2 s jitter on a 0.3 s CPU compile is not
# the 80 s chip regression this gate exists to catch
DRIFT_RATIO = 1.5
DRIFT_FLOOR_S = 1.0


def ledger_enabled() -> bool:
    """The ledger switch (``KSS_PROGRAM_LEDGER``), read at jit-wrap
    time by ``utils/broker.jit`` — engine construction — exactly like
    the KSS7xx audit switch."""
    return env_truthy(os.environ.get(ENV_VAR))


def timing_sample_every() -> int:
    """Warm-timing sample cadence from ``KSS_PROGRAM_TIMING_SAMPLE``:
    0 (the default) never blocks — the async hot path stays async;
    N > 0 blocks on the result every Nth call of each program (the
    first, compile-bearing call is never sampled). Lenient parse: a
    malformed value must not start synchronizing the serving path."""
    raw = os.environ.get(SAMPLE_VAR, "")
    try:
        n = int(raw) if raw else 0
    except ValueError:
        return 0
    return n if n >= 0 else 0


@dataclass
class ProgramRecord:
    """One (site label, compile fingerprint) program in the ledger."""

    label: str
    fingerprint: str
    in_avals: "tuple[Any, ...]" = ()
    out_avals: "tuple[Any, ...]" = ()
    builds: int = 0  # how many times this program's compile was paid
    lowering_s: float = 0.0  # cumulative trace+lower wall
    backend_s: float = 0.0  # cumulative XLA backend-compile wall
    # AOT-bundle accounting (utils/bundles.py): executables served by
    # deserializing an on-disk bundle instead of compiling — the
    # deserialization wall is recorded DISTINCTLY from the compile wall
    # so a bundled boot's ledger shows zero compile seconds, not a
    # mislabeled fast compile
    bundle_loads: int = 0
    deserialize_s: float = 0.0  # cumulative bundle-deserialize wall
    flops: "float | None" = None  # cost_analysis of ONE execution
    bytes: "float | None" = None
    memory: "dict | None" = None  # memory_analysis byte breakdown
    calls: int = 0
    dispatch_s: float = 0.0  # cumulative async-dispatch wall
    warm_samples: int = 0  # sampled block_until_ready executions
    warm_s: float = 0.0  # cumulative sampled warm device wall
    sessions: "dict[str, int]" = field(default_factory=dict)
    degraded: bool = False  # AOT dispatch fell back to plain jit
    # monotonic stamp of this record's most recent call — how a batched
    # dispatch's session fan-out (attribute_sessions) finds the record
    # that actually dispatched when several fingerprints share a label
    last_call_seq: int = 0


@locking.guard_inferred
class ProgramLedger:
    """The process-global per-program cost ledger (module docstring).

    Writers are the broker's `AuditedJit` wrappers (one `open_program`
    per new (site, signature), one `record_call` per dispatch) and the
    bench probes (`observe` — the shared AOT cost path). Readers are
    ``GET /api/v1/debug/programs``, the Prometheus exposition, and
    `persist`/`diff_ledger`."""

    def __init__(self) -> None:
        self._lock = locking.make_lock("ledger.records")
        self._records: "dict[tuple[str, str], ProgramRecord]" = {}
        self._dispatch_total = 0.0
        self._call_seq = 0

    # -- writing -------------------------------------------------------------

    def open_program(
        self,
        label: str,
        fingerprint: str,
        *,
        in_avals: tuple = (),
        out_avals: tuple = (),
        lowering_s: float = 0.0,
        backend_s: float = 0.0,
        deserialize_s: float = 0.0,
        loaded: bool = False,
        cost: "dict | None" = None,
        memory: "dict | None" = None,
    ) -> ProgramRecord:
        """Record one compile of ``(label, fingerprint)``; a re-build of
        a known program (broker eviction, device-epoch bump) accumulates
        its compile wall instead of opening a duplicate row — recompile
        cost is exactly what the ledger must not hide. With
        ``loaded=True`` the program came from an AOT bundle
        (utils/bundles.py): the deserialize wall accumulates instead of
        a build — the two costs must never conflate."""
        key = (label, fingerprint)
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                rec = self._records[key] = ProgramRecord(
                    label, fingerprint, in_avals, out_avals
                )
            if loaded:
                rec.bundle_loads += 1
                rec.deserialize_s += float(deserialize_s)
            else:
                rec.builds += 1
            rec.lowering_s += float(lowering_s)
            rec.backend_s += float(backend_s)
            if cost:
                rec.flops = float(cost.get("flops", 0.0))
                rec.bytes = float(cost.get("bytes", 0.0))
            if memory:
                rec.memory = dict(memory)
            return rec

    def record_call(
        self,
        rec: ProgramRecord,
        dispatch_s: float,
        session: "str | None" = None,
        warm_s: "float | None" = None,
        degraded: bool = False,
    ) -> None:
        sid = session if session is not None else DEFAULT_SESSION_KEY
        with self._lock:
            self._call_seq += 1
            rec.last_call_seq = self._call_seq
            rec.calls += 1
            rec.dispatch_s += float(dispatch_s)
            rec.sessions[sid] = rec.sessions.get(sid, 0) + 1
            if warm_s is not None:
                rec.warm_samples += 1
                rec.warm_s += float(warm_s)
            if degraded:
                rec.degraded = True
            self._dispatch_total += float(dispatch_s)
            total = self._dispatch_total
        # the Perfetto counter track rides the flight recorder (no-op
        # when tracing is off); emitted OUTSIDE the ledger lock
        telemetry.counter("ledger.dispatchSeconds", total)

    def observe(self, label: str, jitted: Any, args: tuple) -> "dict | None":
        """The shared AOT cost probe (bench's ``cost_fields`` routes
        here, so bench and the serving ledger are ONE accounting): time
        ``trace().lower()`` and ``.compile()``, read the compiled cost
        and memory models, record the program under `label`, and return
        ``{"flops", "bytes", "lowering_s", "backend_s"}`` — or None
        when the backend exposes no cost model. Never raises: cost
        telemetry must not break a measurement run."""
        probe = aot_probe(jitted, args)
        if probe is None:
            return None
        _compiled, info, _traced = probe
        if info.get("flops") is None:
            return None
        fingerprint = _observe_fingerprint(label, args)
        self.open_program(
            label,
            fingerprint,
            lowering_s=info["lowering_s"],
            backend_s=info["backend_s"],
            cost={"flops": info["flops"], "bytes": info["bytes"]},
            memory=info.get("memory"),
        )
        return info

    # -- bookkeeping ---------------------------------------------------------

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._dispatch_total = 0.0

    def attribute_sessions(self, label: str, sids: "list[str | None]") -> None:
        """Fan one batched dispatch's attribution out to every enrolled
        tenant (server/batchplane.py): the window's single device
        dispatch was recorded under the LEADER's session context; the
        other enrolled sessions' passes were served by the same call.
        For ``batch.*`` programs the per-session counts are therefore
        PASSES SERVED and may exceed `calls` (device dispatches) — the
        gap IS the batching win, and `make batch-smoke` pins it.

        Several fingerprints can share a label (one per batch bucket /
        cluster shape): the fan-out lands on the record that MOST
        RECENTLY dispatched — the caller attributes immediately after
        its own call, so the freshest stamp is that dispatch (a
        concurrent other-key window can at worst swap two same-label
        attributions, never invent one)."""
        with self._lock:
            matching = [
                rec for rec in self._records.values() if rec.label == label
            ]
            if not matching:
                return
            rec = max(matching, key=lambda r: r.last_call_seq)
            for sid in sids:
                key = sid if sid is not None else DEFAULT_SESSION_KEY
                rec.sessions[key] = rec.sessions.get(key, 0) + 1

    def drop_session(self, sid: str) -> None:
        """Purge a deleted session's call attribution (the session-plane
        DELETE path, server/sessions.py) — a dead tenant's label must
        not linger in every later scrape. Programs themselves stay: the
        compiled executable (and its cost) outlives any one tenant."""
        with self._lock:
            for rec in self._records.values():
                rec.sessions.pop(sid, None)

    # -- reading -------------------------------------------------------------

    def memory_bytes_total(self) -> "int | None":
        """Summed `memory_analysis` bytes across every recorded program
        — the fleet observatory's estimate of what the broker's warm
        executables hold (utils/fleetstats.py buffer census). None when
        no program carries a memory model (the backend may expose
        none); never zero-as-unknown."""
        with self._lock:
            vals = [
                sum(rec.memory.values())
                for rec in self._records.values()
                if rec.memory
            ]
        return sum(vals) if vals else None

    def totals(self) -> dict:
        """The small summary block ``GET /api/v1/metrics`` embeds."""
        with self._lock:
            return {
                "enabled": ledger_enabled(),
                "count": len(self._records),
                "compileSeconds": round(
                    sum(
                        r.lowering_s + r.backend_s
                        for r in self._records.values()
                    ),
                    6,
                ),
                "dispatchSeconds": round(self._dispatch_total, 6),
                "deserializeSeconds": round(
                    sum(r.deserialize_s for r in self._records.values()), 6
                ),
                "bundleLoads": sum(
                    r.bundle_loads for r in self._records.values()
                ),
                "calls": sum(r.calls for r in self._records.values()),
            }

    def snapshot(self, session: "str | None" = None) -> dict:
        """The full ledger document (``GET /api/v1/debug/programs`` and
        the persisted file). `session` filters to programs that session's
        passes actually dispatched (the nested per-session route)."""
        platform = _platform()
        from . import metrics as metrics_mod

        programs: list[dict] = []
        with self._lock:
            records = [
                rec
                for rec in self._records.values()
                if session is None or session in rec.sessions
            ]
            for rec in sorted(records, key=lambda r: (r.label, r.fingerprint)):
                warm_mean = (
                    rec.warm_s / rec.warm_samples if rec.warm_samples else None
                )
                entry = {
                    "label": rec.label,
                    "fingerprint": rec.fingerprint,
                    "builds": rec.builds,
                    "bundleLoads": rec.bundle_loads,
                    "deserializeSeconds": round(rec.deserialize_s, 6),
                    "compileSeconds": {
                        "lowering": round(rec.lowering_s, 6),
                        "backend": round(rec.backend_s, 6),
                        "total": round(rec.lowering_s + rec.backend_s, 6),
                    },
                    "flops": rec.flops,
                    "bytes": rec.bytes,
                    "memory": rec.memory,
                    "calls": rec.calls,
                    "dispatchSeconds": round(rec.dispatch_s, 6),
                    "warm": {
                        "samples": rec.warm_samples,
                        "seconds": round(rec.warm_s, 6),
                        "meanSeconds": round(warm_mean, 9)
                        if warm_mean is not None
                        else None,
                    },
                    "mfu": metrics_mod.mfu(rec.flops, warm_mean, platform)
                    if warm_mean
                    else None,
                    "sessions": dict(rec.sessions),
                    "degraded": rec.degraded,
                }
                programs.append(entry)
        return {
            "format": LEDGER_FORMAT,
            "platform": platform,
            "programs": programs,
        }

    def render_prometheus(self) -> str:
        """The ``kss_program_*`` exposition families, one sample per
        (program, fingerprint) series — appended to the session-labeled
        document by the metrics route. Empty string when the ledger has
        recorded nothing (an empty family block is just noise)."""
        doc = self.snapshot()
        if not doc["programs"]:
            return ""
        from .metrics import _fmt_value

        lines: list[str] = []

        def family(name: str, mtype: str, help_text: str, value_of) -> None:
            samples = [
                (p, value_of(p)) for p in doc["programs"]
            ]
            samples = [(p, v) for p, v in samples if v is not None]
            if not samples:
                return
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {mtype}")
            for p, v in samples:
                lines.append(
                    f'{name}{{program="{p["label"]}",'
                    f'fingerprint="{p["fingerprint"]}"}} {_fmt_value(v)}'
                )

        family(
            "kss_program_compile_seconds",
            "gauge",
            "Compile wall (lowering + backend) paid for this program.",
            lambda p: p["compileSeconds"]["total"],
        )
        family(
            "kss_program_flops",
            "gauge",
            "XLA cost-model FLOPs of one execution of this program.",
            lambda p: p["flops"],
        )
        family(
            "kss_program_calls_total",
            "counter",
            "Executions dispatched through this program.",
            lambda p: p["calls"],
        )
        family(
            "kss_program_warm_seconds",
            "gauge",
            "Mean sampled warm device wall of this program "
            "(KSS_PROGRAM_TIMING_SAMPLE).",
            lambda p: p["warm"]["meanSeconds"],
        )
        return "\n".join(lines) + "\n" if lines else ""

    # -- persistence ---------------------------------------------------------

    def persist(self, path: "str | None" = None) -> "list[Finding]":
        """Write the current ledger as the new baseline at `path`
        (default: next to the persistent compile cache), returning the
        drift findings against what was there (`diff_ledger`). Unlike
        the fingerprint baseline this OVERWRITES rather than merges:
        stale compile walls from dead programs would poison every later
        diff."""
        path = ledger_path() if path is None else path
        current = self.snapshot()
        previous = load_ledger(path)
        drift = (
            diff_ledger(previous, current) if previous is not None else []
        )
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(current, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return drift


def _platform() -> str:
    try:
        import jax

        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001 — a dead backend still has a ledger
        return ""


def _observe_fingerprint(label: str, args: tuple) -> str:
    """A bench-probe fingerprint from the argument avals alone (the
    full jaxpr fingerprint needs the jit kwargs the probe doesn't
    carry; aval identity is what the probe's compile is keyed by)."""
    sig = []
    for a in args:
        shape = tuple(int(d) for d in getattr(a, "shape", ()))
        sig.append((shape, str(getattr(a, "dtype", type(a).__name__))))
    doc = json.dumps({"label": label, "avals": sig}, sort_keys=True)
    return hashlib.sha256(doc.encode()).hexdigest()[:16]


def aot_probe(jitted: Any, args: tuple, kwargs: "dict | None" = None):
    """Time the AOT path of one program: returns ``(compiled, info,
    traced)`` with ``info = {"lowering_s", "backend_s", "flops",
    "bytes", "memory"}`` (flops/bytes None when the backend exposes no
    cost model), or None when lowering/compiling itself failed. The one
    compile-splitting primitive the ledger wrapper and the bench cost
    path share; `traced` is handed back so the wrapper's fingerprint
    never pays a second trace."""
    try:
        t0 = time.perf_counter()
        traced = jitted.trace(*args, **(kwargs or {}))
        lowered = traced.lower()
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
    except Exception:  # noqa: BLE001 — observability must not fail the program
        return None
    info: dict = {
        "lowering_s": t1 - t0,
        "backend_s": t2 - t1,
        "flops": None,
        "bytes": None,
        "memory": None,
    }
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            info["flops"] = float(ca.get("flops", 0.0) or 0.0)
            info["bytes"] = float(ca.get("bytes accessed", 0.0) or 0.0)
    except Exception:  # noqa: BLE001 — cost model is optional per backend
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            mem = {
                key: int(getattr(ma, attr))
                for key, attr in (
                    ("tempBytes", "temp_size_in_bytes"),
                    ("argumentBytes", "argument_size_in_bytes"),
                    ("outputBytes", "output_size_in_bytes"),
                    ("aliasBytes", "alias_size_in_bytes"),
                    ("generatedCodeBytes", "generated_code_size_in_bytes"),
                )
                if getattr(ma, attr, None) is not None
            }
            if mem:
                info["memory"] = mem
    except Exception:  # noqa: BLE001 — memory model is optional per backend
        pass
    return compiled, info, traced


# -- persistence / diff --------------------------------------------------------


def ledger_path(cache_dir: "str | None" = None) -> str:
    """The baseline file, next to the persistent compile cache and the
    KSS715 fingerprint baseline (same KSS_JAX_CACHE_DIR override)."""
    from .compilecache import default_cache_dir

    if cache_dir is None:
        cache_dir = os.environ.get("KSS_JAX_CACHE_DIR") or default_cache_dir()
    return os.path.join(cache_dir, LEDGER_BASENAME)


def load_ledger(path: "str | None" = None) -> "dict | None":
    """A persisted ledger document, or None when absent/foreign/corrupt
    (callers distinguish "no baseline yet" from "unreadable baseline"
    only by existence — both mean: nothing to diff against)."""
    path = ledger_path() if path is None else path
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("format") != LEDGER_FORMAT:
        return None
    if not isinstance(doc.get("programs"), list):
        return None
    return doc


def _by_key(doc: dict) -> "dict[tuple[str, str], dict]":
    out: dict[tuple[str, str], dict] = {}
    for p in doc.get("programs", []):
        if isinstance(p, dict) and "label" in p and "fingerprint" in p:
            out[(str(p["label"]), str(p["fingerprint"]))] = p
    return out


def diff_ledger(
    previous: dict,
    current: dict,
    *,
    ratio: float = DRIFT_RATIO,
    floor_s: float = DRIFT_FLOOR_S,
) -> "list[Finding]":
    """Perf-regression diff of two ledger documents:

      KSS731  compile-seconds regression — a label's TOTAL compile wall
              (summed over its fingerprints, so a changed fingerprint
              cannot hide the cost under a 'different' key) grew past
              BOTH ``ratio`` × the baseline and the absolute ``floor_s``
              (compile walls jitter; only a real regression clears both
              bars — improvements never flag);
      KSS732  FLOPs drift — the cost model of an identically-
              fingerprinted program changed (the program is not the
              program the baseline measured);
      KSS733  a baseline program label the current run no longer
              builds (vanished work — or a silently renamed site);
      KSS734  a program label the baseline never saw (new compile
              cost the baseline didn't budget);
      KSS735  fingerprint churn under a surviving label — the site
              compiles DIFFERENT programs than the baseline (an
              avals/static-arg drift: exactly the recompile class the
              gate exists to catch, and invisible to per-fingerprint
              comparison alone).

    Two identically-seeded runs diff clean; the tier-1 gate pins it."""
    findings: list[Finding] = []
    prev, cur = _by_key(previous), _by_key(current)
    prev_labels = {label for label, _ in prev}
    cur_labels = {label for label, _ in cur}

    def label_compile_s(doc_keys: dict, label: str) -> float:
        return sum(
            float((p.get("compileSeconds") or {}).get("total", 0.0))
            for (lb, _fp), p in doc_keys.items()
            if lb == label
        )

    for label in sorted(prev_labels & cur_labels):
        site = f"<program:{label}>"
        p_fps = {fp for lb, fp in prev if lb == label}
        c_fps = {fp for lb, fp in cur if lb == label}
        if p_fps != c_fps:
            gained = sorted(c_fps - p_fps)
            lost = sorted(p_fps - c_fps)
            parts = []
            if gained:
                parts.append(f"gained {gained}")
            if lost:
                parts.append(f"lost {lost}")
            findings.append(
                Finding(
                    "KSS735",
                    site,
                    0,
                    f"compile-fingerprint churn at {label!r}: "
                    + "; ".join(parts)
                    + " — the site compiles different programs than "
                    "the baseline",
                    hint="an avals/static-arg change reached this site "
                    "(compare with the KSS715 fingerprint baseline); "
                    "re-baseline by persisting if intended",
                )
            )
        # compile regression at LABEL granularity: summed over
        # fingerprints, so a changed fingerprint cannot park the new
        # cost under a key the per-key comparison never visits
        p_compile = label_compile_s(prev, label)
        c_compile = label_compile_s(cur, label)
        if c_compile > p_compile * ratio and c_compile - p_compile > floor_s:
            findings.append(
                Finding(
                    "KSS731",
                    site,
                    0,
                    f"compile wall regressed {p_compile:.3f}s -> "
                    f"{c_compile:.3f}s (> {ratio}x and > +{floor_s}s)",
                    hint="a program this site compiles got expensive — "
                    "bisect the lowering change, or re-baseline by "
                    "persisting if intended",
                )
            )
    for key in sorted(set(prev) & set(cur)):
        label, fp = key
        site = f"<program:{label}@{fp}>"
        p, c = prev[key], cur[key]
        p_flops, c_flops = p.get("flops"), c.get("flops")
        if (
            p_flops is not None
            and c_flops is not None
            and float(p_flops) != float(c_flops)
        ):
            findings.append(
                Finding(
                    "KSS732",
                    site,
                    0,
                    f"cost-model FLOPs drifted {p_flops} -> {c_flops} "
                    f"for an identically-fingerprinted program",
                    hint="the compiled program changed under a stable "
                    "fingerprint — compare the two runs' jaxprs",
                )
            )
    for label in sorted(prev_labels - cur_labels):
        findings.append(
            Finding(
                "KSS733",
                f"<program:{label}>",
                0,
                f"baseline program {label!r} vanished from the current "
                f"run",
                hint="the site no longer compiles (dead code, a rename, "
                "or lost coverage) — re-baseline if intended",
            )
        )
    for label in sorted(cur_labels - prev_labels):
        findings.append(
            Finding(
                "KSS734",
                f"<program:{label}>",
                0,
                f"program {label!r} is new against the baseline",
                hint="new compile cost the baseline didn't budget — "
                "re-baseline by persisting if intended",
            )
        )
    return findings


# -- cold-start phase accounting ----------------------------------------------

# the canonical phase order (docs/performance.md): marks may land in
# any order at runtime (a lifecycle CLI has no boot probe), but the
# snapshot renders them in this sequence
COLD_START_PHASES = ("bootProbe", "firstEncode", "firstCompile", "firstPass")


@locking.guard_inferred
class ColdStartTracker:
    """Process-global first-occurrence marks from process start (this
    module's import) to the first scheduled pass. Each `mark` is
    latched — only the FIRST occurrence of a phase records — and emits
    a ``coldstart.<phase>`` telemetry instant so the Perfetto timeline
    shows where the cold start went."""

    def __init__(self) -> None:
        self._lock = locking.make_lock("ledger.coldstart")
        self._origin = time.perf_counter()
        self._marks: "dict[str, float]" = {}

    def mark(self, phase: str) -> None:
        now = time.perf_counter()
        with self._lock:
            if phase in self._marks:
                return
            self._marks[phase] = now - self._origin
            offset = self._marks[phase]
        telemetry.instant(
            f"coldstart.{phase}", secondsSinceStart=round(offset, 6)
        )

    def snapshot(self) -> dict:
        """The ``coldStart`` block of ``GET /api/v1/metrics``: seconds
        from process start per phase, the headline
        ``timeToFirstPassSeconds``, and whether the cold start is over
        (`complete`: the first pass happened)."""
        with self._lock:
            marks = dict(self._marks)
        phases = {
            phase: round(marks[phase], 6)
            for phase in COLD_START_PHASES
            if phase in marks
        }
        ttfp = marks.get("firstPass")
        return {
            "phases": phases,
            "timeToFirstPassSeconds": round(ttfp, 6)
            if ttfp is not None
            else None,
            "complete": ttfp is not None,
        }

    def reset(self) -> None:
        """Restart the clock (tests; a forked bench probe)."""
        with self._lock:
            self._origin = time.perf_counter()
            self._marks.clear()


LEDGER = ProgramLedger()
COLD_START = ColdStartTracker()
