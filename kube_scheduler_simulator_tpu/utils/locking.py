"""Runtime lock-order witness: ``KSS_LOCK_CHECK=1`` (docs/static-analysis.md).

The static lock-order analyzer (analysis/lock_order.py) sees only the
acquisitions it can resolve lexically; locks reached through
cross-module calls — the schedule lock over the broker lock over the
store locks — are invisible to it. This module is the dynamic half: a
lightweight deadlock/race detector in the happens-before style (cf.
Go's lock-order assertions and pthread's PTHREAD_MUTEX_ERRORCHECK
lineage), cheap enough to run under the whole test suite.

Every lock the serving stack creates goes through `make_lock` /
`make_rlock` with a stable ROLE name ("broker.lock",
"sessions.manager", ...). With ``KSS_LOCK_CHECK`` unset (the default)
these return plain `threading.Lock`/`RLock` objects — zero overhead,
byte-identical behavior. With ``KSS_LOCK_CHECK=1`` they return witness
wrappers that:

  * track the set of roles each thread currently holds;
  * on every acquisition record the edge ``held role -> acquired
    role`` into a process-global order graph, stamped with the first
    observing call site;
  * RAISE `LockOrderInversion` the moment an acquisition would close a
    cycle in that graph — two call paths have been SEEN acquiring the
    same roles in opposite orders, which is a deadlock waiting for the
    right interleaving.

Same-role edges are skipped: roles name lock *classes* (every
`SpanRecorder` ring shares "telemetry.ring"), and two instances of one
role cannot be ordered by name. Re-entrant re-acquisition of an RLock
records nothing (depth bookkeeping only).

`tests/test_lock_witness.py` drives a concurrent session-plane stress
under the witness and pins zero inversions; the witness itself is
negative-tested by forcing an AB/BA pair.

GUARDED-STATE witness — ``KSS_RACE_CHECK=1`` (the runtime half of the
KSS6xx analyzer, analysis/guarded_state.py): classes decorated with
`guard_inferred` get their lock-claimed attributes wrapped in checking
descriptors when the knob is set at construction time. The claims come
from the SAME static inference the analyzer runs (an attribute written
under ``with self._lock`` in one method is protected by that lock
everywhere), so the two halves cannot drift. Each descriptor access
verifies some claiming lock is currently held — by ANY thread: the
dispatch→resolve pass-handle shape legally accesses state on a thread
other than the acquirer — and raises `UnguardedAccess` otherwise.
``KSS_RACE_CHECK_SAMPLE=N`` checks every Nth access (default 1: all)
to bound the overhead on hot paths. Arming KSS_RACE_CHECK also arms
the witness lock wrappers (held-state tracking needs them), so the
lock-order inversion check rides along.
"""

from __future__ import annotations

import functools
import os
import threading
import traceback
from typing import Any, Callable, Mapping

from . import envcheck

ENV_VAR = "KSS_LOCK_CHECK"
RACE_ENV_VAR = "KSS_RACE_CHECK"
RACE_SAMPLE_ENV_VAR = "KSS_RACE_CHECK_SAMPLE"


def lock_check_enabled(env: "Mapping[str, str] | None" = None) -> bool:
    """The witness switch, read at LOCK CREATION time (wrapping is a
    construction-time decision; flipping the env mid-process affects
    only locks created afterwards)."""
    env = os.environ if env is None else env
    return envcheck.env_truthy(env.get(ENV_VAR))


def race_check_enabled(env: "Mapping[str, str] | None" = None) -> bool:
    """The guarded-state witness switch (``KSS_RACE_CHECK``), read at
    OBJECT CONSTRUCTION time — instances built while it is unset are
    never checked, exactly like the lock witness's creation-time
    contract."""
    env = os.environ if env is None else env
    return envcheck.env_truthy(env.get(RACE_ENV_VAR))


def race_sample_rate(env: "Mapping[str, str] | None" = None) -> int:
    """Check every Nth guarded access (``KSS_RACE_CHECK_SAMPLE``,
    default 1 = every access). Lenient parse: a malformed value must
    not take a witnessed run down."""
    env = os.environ if env is None else env
    raw = env.get(RACE_SAMPLE_ENV_VAR, "")
    try:
        n = int(raw) if raw else 1
    except ValueError:
        return 1
    return n if n >= 1 else 1


class LockOrderInversion(RuntimeError):
    """Two lock roles have been acquired in both orders — a deadlock
    exists for some interleaving. Carries both sites."""


class LockWitness:
    """The process-global order graph + per-thread held sets."""

    def __init__(self) -> None:
        self._graph_lock = threading.Lock()
        # (held role, acquired role) -> first observing site (str)
        self.edges: "dict[tuple[str, str], str]" = {}
        self.inversions: "list[str]" = []
        self.acquisitions = 0
        self._held = threading.local()

    # -- bookkeeping ---------------------------------------------------------

    def _held_list(self) -> "list[str]":
        held = getattr(self._held, "roles", None)
        if held is None:
            held = self._held.roles = []
        return held

    @staticmethod
    def _site() -> str:
        for frame in reversed(traceback.extract_stack(limit=16)):
            if "utils/locking" not in frame.filename.replace(os.sep, "/"):
                return f"{frame.filename}:{frame.lineno}"
        return "<unknown>"

    def _would_cycle(self, a: str, b: str) -> "list[str] | None":
        """Path b ~> a in the edge graph (so adding a -> b closes a
        cycle); returns the role path or None. Graph is tiny (one node
        per lock role), so a DFS per new edge is fine."""
        stack = [(b, [b])]
        seen = {b}
        while stack:
            node, path = stack.pop()
            if node == a:
                return path
            for (x, y) in self.edges:
                if x == node and y not in seen:
                    seen.add(y)
                    stack.append((y, path + [y]))
        return None

    def on_acquired(self, role: str) -> "list[str]":
        """Called by a wrapper AFTER it acquired its underlying lock:
        record edges from every held role, raising on an inversion.
        Returns the acquiring thread's held list so a plain-Lock wrapper
        can hand it to `on_released_list` even when the release happens
        on ANOTHER thread (the pass-handle dispatch→resolve shape)."""
        held = self._held_list()
        # the call site is only needed when a NEW edge lands (or an
        # inversion fires) — extracting the stack on every steady-state
        # acquisition would dominate a witnessed run's cost
        site: "str | None" = None
        error: "str | None" = None
        with self._graph_lock:
            self.acquisitions += 1
            for h in held:
                if h == role or (h, role) in self.edges:
                    continue
                if site is None:
                    site = self._site()
                cycle = self._would_cycle(h, role)
                if cycle is not None:
                    first = self.edges.get(
                        (cycle[0], cycle[1]), "<site unknown>"
                    ) if len(cycle) > 1 else "<site unknown>"
                    error = (
                        f"lock-order inversion: acquiring {role!r} while "
                        f"holding {h!r} at {site}, but the opposite order "
                        f"{' -> '.join(cycle)} was seen at {first}"
                    )
                    self.inversions.append(error)
                    break
                self.edges[(h, role)] = site
            if error is None:
                # appended under the graph lock: a cross-thread release
                # (on_released_list) may mutate this list concurrently
                held.append(role)
        if error is not None:
            # the caller releases the underlying lock on this raise, so
            # the role must NOT enter the held list
            raise LockOrderInversion(error)
        return held

    def on_released_list(self, held: "list[str]", role: str) -> None:
        """Drop `role` from a specific thread's held list (the one
        `on_acquired` returned) — correct even when a plain Lock is
        released by a thread other than its acquirer."""
        with self._graph_lock:
            # locks need not release LIFO: drop the most recent matching
            for i in range(len(held) - 1, -1, -1):
                if held[i] == role:
                    del held[i]
                    break

    def on_released(self, role: str) -> None:
        """Drop `role` from the CALLING thread's held list (the RLock
        path: RLocks are owner-released by contract)."""
        self.on_released_list(self._held_list(), role)

    def snapshot(self) -> "dict[str, Any]":
        with self._graph_lock:
            return {
                "edges": {
                    f"{a} -> {b}": site
                    for (a, b), site in sorted(self.edges.items())
                },
                "inversions": list(self.inversions),
                "acquisitions": self.acquisitions,
            }

    def reset(self) -> None:
        with self._graph_lock:
            self.edges.clear()
            self.inversions.clear()
            self.acquisitions = 0


WITNESS = LockWitness()


class _WitnessBase:
    """Shared context-manager plumbing for the witness wrappers. Both
    play the Condition(lock) role (threading.Condition only needs
    acquire/release; its `_is_owned` fallback probes with a
    non-blocking acquire, which flows through here like any other
    acquisition)."""

    def __init__(self, role: str, witness: "LockWitness | None" = None):
        self.role = role
        self.witness = witness if witness is not None else WITNESS

    def __enter__(self) -> "_WitnessBase":
        self.acquire()
        return self

    def __exit__(self, *exc: object) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<{type(self).__name__} role={self.role!r}>"


class WitnessLock(_WitnessBase):
    """Plain-Lock wrapper. A `threading.Lock` may legally be released
    by a thread other than its acquirer (the `SchedulingPassHandle`
    dispatch→resolve shape), so the acquirer's held list travels on the
    INSTANCE — release removes the role from the list `on_acquired`
    returned, whichever thread calls it."""

    def __init__(self, role: str, witness: "LockWitness | None" = None):
        super().__init__(role, witness)
        self._inner = threading.Lock()
        self._holder_held: "list[str] | None" = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            try:
                self._holder_held = self.witness.on_acquired(self.role)
            except BaseException:
                self._inner.release()
                raise
        return ok

    def release(self) -> None:
        held, self._holder_held = self._holder_held, None
        if held is not None:
            self.witness.on_released_list(held, self.role)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def held_anywhere(self) -> bool:
        """Is the lock currently held by ANY thread — the guarded-state
        witness's probe (a plain Lock may be held on one thread and
        released on another, so owner identity is not part of the
        contract)."""
        return self._inner.locked()


class WitnessRLock(_WitnessBase):
    """RLock wrapper: re-entrant re-acquisition records nothing (depth
    bookkeeping only). RLocks are owner-released by contract — the
    inner RLock raises on a foreign release — so per-thread depth is
    sound. No `locked()`: threading.RLock exposes none on this Python,
    and the wrapper keeps the underlying type's surface."""

    def __init__(self, role: str, witness: "LockWitness | None" = None):
        super().__init__(role, witness)
        self._inner = threading.RLock()
        self._depth = threading.local()
        # True while any thread's outer acquisition is live — a plain
        # boolean store/load (atomic under the GIL; only the owning
        # thread flips it, RLocks being owner-released by contract)
        self._held_flag = False

    def _depth_add(self, delta: int) -> int:
        n = getattr(self._depth, "n", 0) + delta
        self._depth.n = n
        return n

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if self._depth_add(+1) == 1:
                try:
                    self.witness.on_acquired(self.role)
                except BaseException:
                    self._depth_add(-1)
                    self._inner.release()
                    raise
                self._held_flag = True
        return ok

    def release(self) -> None:
        if getattr(self._depth, "n", 0) <= 0:
            # foreign/over-release: let the inner RLock raise its own
            # RuntimeError without corrupting the witness
            self._inner.release()
            return
        if self._depth_add(-1) == 0:
            self.witness.on_released(self.role)
            self._held_flag = False
        self._inner.release()

    def held_anywhere(self) -> bool:
        """Is some thread inside an outer acquire of this RLock — the
        guarded-state witness's probe."""
        return self._held_flag


def make_lock(role: str) -> "threading.Lock | WitnessLock":
    """A `threading.Lock` — witness-wrapped when KSS_LOCK_CHECK (or
    KSS_RACE_CHECK, whose held-state probes need the wrapper) is set at
    creation time. `role` is the stable order-graph node name."""
    if lock_check_enabled() or race_check_enabled():
        return WitnessLock(role)
    return threading.Lock()


def make_rlock(role: str) -> "threading.RLock | WitnessRLock":
    """A `threading.RLock` — witness-wrapped when KSS_LOCK_CHECK or
    KSS_RACE_CHECK is set at creation time (re-entrant re-acquisition
    records nothing)."""
    if lock_check_enabled() or race_check_enabled():
        return WitnessRLock(role)
    return threading.RLock()


# -- guarded-state witness (KSS_RACE_CHECK=1; analysis/guarded_state.py) -----


class UnguardedAccess(RuntimeError):
    """A lock-claimed attribute was touched while NO claiming lock was
    held — the race the KSS6xx static pass flags lexically, caught at
    runtime on the paths the static view cannot follow."""


class GuardedAttr:
    """Data descriptor standing in for one claimed instance attribute.

    The real value lives in the instance ``__dict__`` under the same
    name (``vars(obj)`` and state-dump code keep working); every load
    and store first verifies that at least one of the claiming lock
    attributes is currently held — by any thread (see
    `WitnessLock.held_anywhere`). Instances are only checked once
    construction finished (`guard_inferred` arms the instance after
    ``__init__`` returns) and only when they were built in an armed
    process; a claiming lock that is NOT a witness wrapper (created
    while the knob was off) fails open. A shadowed plain class-level
    value (the dataclass simple-default shape) is preserved as the
    read fallback, so the witness observes without ever changing what
    an attribute read returns."""

    __slots__ = (
        "name", "owner_name", "lock_attrs", "default", "_tick", "_sample",
    )

    #: sentinel: no class-level default was shadowed
    MISSING: Any = object()

    def __init__(
        self,
        name: str,
        owner_name: str,
        lock_attrs: "tuple[str, ...]",
        default: Any = MISSING,
    ):
        self.name = name
        self.owner_name = owner_name
        self.lock_attrs = lock_attrs
        self.default = default
        self._tick = 0
        self._sample = race_sample_rate()

    def _check(self, obj: Any, what: str) -> None:
        d = obj.__dict__
        if not d.get("_kss_guard_armed"):
            return
        # sampling: benign data race on the tick — it only shifts WHICH
        # accesses get checked, never whether violations are possible
        self._tick += 1
        if self._tick % self._sample:
            return
        witnessed = False
        for lname in self.lock_attrs:
            lk = d.get(lname)
            if lk is None:
                lk = getattr(type(obj), lname, None)
            held = getattr(lk, "held_anywhere", None)
            if held is None:
                # not a witness wrapper — a Condition alias (its
                # acquisitions flow through the wrapped lock, which IS
                # checked) or a lock created while disarmed. Skip it;
                # fail open only when NO claimer is witnessable.
                continue
            witnessed = True
            if held():
                return
        if witnessed:
            raise UnguardedAccess(
                f"unguarded {what} of {self.owner_name}.{self.name}: "
                f"claimed by lock attr(s) {', '.join(self.lock_attrs)} "
                f"but none is held (KSS_RACE_CHECK; see "
                f"docs/static-analysis.md KSS6xx)"
            )

    def __get__(self, obj: Any, objtype: "type | None" = None) -> Any:
        if obj is None:
            return self
        self._check(obj, "read")
        try:
            return obj.__dict__[self.name]
        except KeyError:
            if self.default is not GuardedAttr.MISSING:
                return self.default  # the shadowed class-level default
            raise AttributeError(self.name) from None

    def __set__(self, obj: Any, value: Any) -> None:
        self._check(obj, "write")
        obj.__dict__[self.name] = value

    def __delete__(self, obj: Any) -> None:
        self._check(obj, "delete")
        try:
            del obj.__dict__[self.name]
        except KeyError:
            raise AttributeError(self.name) from None


def install_guards(cls: type, claims: "dict[str, tuple[str, ...]]") -> None:
    """Install `GuardedAttr` descriptors on `cls` for each ``attr ->
    (claiming lock attrs)`` entry. Idempotent per attribute. The direct
    entry point for tests and for classes whose map is hand-declared;
    `guard_inferred` derives `claims` from the static analyzer."""
    for attr, lock_attrs in sorted(claims.items()):
        missing = object()
        existing = cls.__dict__.get(attr, missing)
        if isinstance(existing, GuardedAttr):
            continue
        if existing is not missing and hasattr(existing, "__get__"):
            # the name is already a DESCRIPTOR at class level (a
            # property, a function, a custom descriptor): shadowing it
            # would change behavior, and the witness must only observe
            # — skip, unwitnessed but faithful
            continue
        default = GuardedAttr.MISSING if existing is missing else existing
        setattr(
            cls,
            attr,
            GuardedAttr(attr, cls.__name__, tuple(lock_attrs), default),
        )


def _rel_of_module(module: str) -> "str | None":
    """'kube_scheduler_simulator_tpu.utils.broker' -> 'utils/broker.py'
    (None for classes outside the package — nothing to infer from)."""
    parts = module.split(".")
    if len(parts) < 2:
        return None
    return "/".join(parts[1:]) + ".py"


@functools.lru_cache(maxsize=1)
def _inferred_maps() -> "dict[tuple[str, str], Any]":
    """The static analyzer's protection map over the LIVE package —
    parsed once per process, only ever on an armed construction path."""
    from ..analysis import guarded_state
    from ..analysis.core import SourceTree

    return guarded_state.protection_map(SourceTree.load())


def _instrument_from_inference(cls: type) -> None:
    rel = _rel_of_module(cls.__module__)
    if rel is None:
        return
    cmap = _inferred_maps().get((rel, cls.__name__))
    if cmap is None:
        return
    claims = {
        attr: tuple(
            sorted(
                a
                for a, role in cmap.lock_attrs.items()
                if role in roles
            )
        )
        for attr, roles in cmap.claims.items()
    }
    install_guards(cls, {a: la for a, la in claims.items() if la})


def guard_inferred(cls: type) -> type:
    """Class decorator: under ``KSS_RACE_CHECK=1`` (checked at each
    construction), wrap the class's statically-inferred lock-claimed
    attributes in `GuardedAttr` witnesses and arm the new instance once
    its ``__init__`` has returned (construction writes are exempt, like
    the static pass's ``__init__`` exemption). A no-op wrapper when the
    knob is off — one env probe per construction."""
    orig_init: "Callable[..., None]" = cls.__init__

    @functools.wraps(orig_init)
    def __init__(self: Any, *args: Any, **kwargs: Any) -> None:
        orig_init(self, *args, **kwargs)
        if race_check_enabled():
            _instrument_from_inference(cls)
            self.__dict__["_kss_guard_armed"] = True

    cls.__init__ = __init__  # type: ignore[method-assign]
    cls._kss_guarded_class = True  # type: ignore[attr-defined]
    return cls
