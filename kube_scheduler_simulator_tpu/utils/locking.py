"""Runtime lock-order witness: ``KSS_LOCK_CHECK=1`` (docs/static-analysis.md).

The static lock-order analyzer (analysis/lock_order.py) sees only the
acquisitions it can resolve lexically; locks reached through
cross-module calls — the schedule lock over the broker lock over the
store locks — are invisible to it. This module is the dynamic half: a
lightweight deadlock/race detector in the happens-before style (cf.
Go's lock-order assertions and pthread's PTHREAD_MUTEX_ERRORCHECK
lineage), cheap enough to run under the whole test suite.

Every lock the serving stack creates goes through `make_lock` /
`make_rlock` with a stable ROLE name ("broker.lock",
"sessions.manager", ...). With ``KSS_LOCK_CHECK`` unset (the default)
these return plain `threading.Lock`/`RLock` objects — zero overhead,
byte-identical behavior. With ``KSS_LOCK_CHECK=1`` they return witness
wrappers that:

  * track the set of roles each thread currently holds;
  * on every acquisition record the edge ``held role -> acquired
    role`` into a process-global order graph, stamped with the first
    observing call site;
  * RAISE `LockOrderInversion` the moment an acquisition would close a
    cycle in that graph — two call paths have been SEEN acquiring the
    same roles in opposite orders, which is a deadlock waiting for the
    right interleaving.

Same-role edges are skipped: roles name lock *classes* (every
`SpanRecorder` ring shares "telemetry.ring"), and two instances of one
role cannot be ordered by name. Re-entrant re-acquisition of an RLock
records nothing (depth bookkeeping only).

`tests/test_lock_witness.py` drives a concurrent session-plane stress
under the witness and pins zero inversions; the witness itself is
negative-tested by forcing an AB/BA pair.
"""

from __future__ import annotations

import os
import threading
import traceback

from . import envcheck

ENV_VAR = "KSS_LOCK_CHECK"


def lock_check_enabled(env: "dict | None" = None) -> bool:
    """The witness switch, read at LOCK CREATION time (wrapping is a
    construction-time decision; flipping the env mid-process affects
    only locks created afterwards)."""
    env = os.environ if env is None else env
    return envcheck.env_truthy(env.get(ENV_VAR))


class LockOrderInversion(RuntimeError):
    """Two lock roles have been acquired in both orders — a deadlock
    exists for some interleaving. Carries both sites."""


class LockWitness:
    """The process-global order graph + per-thread held sets."""

    def __init__(self) -> None:
        self._graph_lock = threading.Lock()
        # (held role, acquired role) -> first observing site (str)
        self.edges: "dict[tuple[str, str], str]" = {}
        self.inversions: "list[str]" = []
        self.acquisitions = 0
        self._held = threading.local()

    # -- bookkeeping ---------------------------------------------------------

    def _held_list(self) -> "list[str]":
        held = getattr(self._held, "roles", None)
        if held is None:
            held = self._held.roles = []
        return held

    @staticmethod
    def _site() -> str:
        for frame in reversed(traceback.extract_stack(limit=16)):
            if "utils/locking" not in frame.filename.replace(os.sep, "/"):
                return f"{frame.filename}:{frame.lineno}"
        return "<unknown>"

    def _would_cycle(self, a: str, b: str) -> "list[str] | None":
        """Path b ~> a in the edge graph (so adding a -> b closes a
        cycle); returns the role path or None. Graph is tiny (one node
        per lock role), so a DFS per new edge is fine."""
        stack = [(b, [b])]
        seen = {b}
        while stack:
            node, path = stack.pop()
            if node == a:
                return path
            for (x, y) in self.edges:
                if x == node and y not in seen:
                    seen.add(y)
                    stack.append((y, path + [y]))
        return None

    def on_acquired(self, role: str) -> "list[str]":
        """Called by a wrapper AFTER it acquired its underlying lock:
        record edges from every held role, raising on an inversion.
        Returns the acquiring thread's held list so a plain-Lock wrapper
        can hand it to `on_released_list` even when the release happens
        on ANOTHER thread (the pass-handle dispatch→resolve shape)."""
        held = self._held_list()
        # the call site is only needed when a NEW edge lands (or an
        # inversion fires) — extracting the stack on every steady-state
        # acquisition would dominate a witnessed run's cost
        site: "str | None" = None
        error: "str | None" = None
        with self._graph_lock:
            self.acquisitions += 1
            for h in held:
                if h == role or (h, role) in self.edges:
                    continue
                if site is None:
                    site = self._site()
                cycle = self._would_cycle(h, role)
                if cycle is not None:
                    first = self.edges.get(
                        (cycle[0], cycle[1]), "<site unknown>"
                    ) if len(cycle) > 1 else "<site unknown>"
                    error = (
                        f"lock-order inversion: acquiring {role!r} while "
                        f"holding {h!r} at {site}, but the opposite order "
                        f"{' -> '.join(cycle)} was seen at {first}"
                    )
                    self.inversions.append(error)
                    break
                self.edges[(h, role)] = site
            if error is None:
                # appended under the graph lock: a cross-thread release
                # (on_released_list) may mutate this list concurrently
                held.append(role)
        if error is not None:
            # the caller releases the underlying lock on this raise, so
            # the role must NOT enter the held list
            raise LockOrderInversion(error)
        return held

    def on_released_list(self, held: "list[str]", role: str) -> None:
        """Drop `role` from a specific thread's held list (the one
        `on_acquired` returned) — correct even when a plain Lock is
        released by a thread other than its acquirer."""
        with self._graph_lock:
            # locks need not release LIFO: drop the most recent matching
            for i in range(len(held) - 1, -1, -1):
                if held[i] == role:
                    del held[i]
                    break

    def on_released(self, role: str) -> None:
        """Drop `role` from the CALLING thread's held list (the RLock
        path: RLocks are owner-released by contract)."""
        self.on_released_list(self._held_list(), role)

    def snapshot(self) -> dict:
        with self._graph_lock:
            return {
                "edges": {
                    f"{a} -> {b}": site
                    for (a, b), site in sorted(self.edges.items())
                },
                "inversions": list(self.inversions),
                "acquisitions": self.acquisitions,
            }

    def reset(self) -> None:
        with self._graph_lock:
            self.edges.clear()
            self.inversions.clear()
            self.acquisitions = 0


WITNESS = LockWitness()


class _WitnessBase:
    """Shared context-manager plumbing for the witness wrappers. Both
    play the Condition(lock) role (threading.Condition only needs
    acquire/release; its `_is_owned` fallback probes with a
    non-blocking acquire, which flows through here like any other
    acquisition)."""

    def __init__(self, role: str, witness: "LockWitness | None" = None):
        self.role = role
        self.witness = witness if witness is not None else WITNESS

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<{type(self).__name__} role={self.role!r}>"


class WitnessLock(_WitnessBase):
    """Plain-Lock wrapper. A `threading.Lock` may legally be released
    by a thread other than its acquirer (the `SchedulingPassHandle`
    dispatch→resolve shape), so the acquirer's held list travels on the
    INSTANCE — release removes the role from the list `on_acquired`
    returned, whichever thread calls it."""

    def __init__(self, role: str, witness: "LockWitness | None" = None):
        super().__init__(role, witness)
        self._inner = threading.Lock()
        self._holder_held: "list[str] | None" = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            try:
                self._holder_held = self.witness.on_acquired(self.role)
            except BaseException:
                self._inner.release()
                raise
        return ok

    def release(self) -> None:
        held, self._holder_held = self._holder_held, None
        if held is not None:
            self.witness.on_released_list(held, self.role)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()


class WitnessRLock(_WitnessBase):
    """RLock wrapper: re-entrant re-acquisition records nothing (depth
    bookkeeping only). RLocks are owner-released by contract — the
    inner RLock raises on a foreign release — so per-thread depth is
    sound. No `locked()`: threading.RLock exposes none on this Python,
    and the wrapper keeps the underlying type's surface."""

    def __init__(self, role: str, witness: "LockWitness | None" = None):
        super().__init__(role, witness)
        self._inner = threading.RLock()
        self._depth = threading.local()

    def _depth_add(self, delta: int) -> int:
        n = getattr(self._depth, "n", 0) + delta
        self._depth.n = n
        return n

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if self._depth_add(+1) == 1:
                try:
                    self.witness.on_acquired(self.role)
                except BaseException:
                    self._depth_add(-1)
                    self._inner.release()
                    raise
        return ok

    def release(self) -> None:
        if getattr(self._depth, "n", 0) <= 0:
            # foreign/over-release: let the inner RLock raise its own
            # RuntimeError without corrupting the witness
            self._inner.release()
            return
        if self._depth_add(-1) == 0:
            self.witness.on_released(self.role)
        self._inner.release()


def make_lock(role: str):
    """A `threading.Lock` — witness-wrapped when KSS_LOCK_CHECK is set
    at creation time. `role` is the stable order-graph node name."""
    return WitnessLock(role) if lock_check_enabled() else threading.Lock()


def make_rlock(role: str):
    """A `threading.RLock` — witness-wrapped when KSS_LOCK_CHECK is set
    at creation time (re-entrant re-acquisition records nothing)."""
    return WitnessRLock(role) if lock_check_enabled() else threading.RLock()
