"""In-framework scheduling metrics + profiler wiring (SURVEY.md §5).

The reference has no metrics beyond echo request logging — its *product*
is the decision trace. Here the BASELINE metric (scheduling decisions per
second per chip) is a first-class counter: every scheduling pass reports
into a process-wide `SchedulingMetrics` registry that the serving layer
exposes (`GET /api/v1/metrics`, an extension route) and benchmarks read
directly.

`profile_trace` wraps `jax.profiler.trace` so a pass can be captured for
TensorBoard/XProf without the caller importing jax.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class PassRecord:
    """One scheduling pass (one engine execution over the queue)."""

    mode: str  # "sequential" | "gang" | "extender"
    pods: int  # queue length scheduled over
    scheduled: int  # pods that received a node
    wall_s: float
    rounds: int = 0  # gang mode only

    @property
    def decisions_per_s(self) -> float:
        return self.pods / self.wall_s if self.wall_s > 0 else 0.0


@dataclass
class SchedulingMetrics:
    """Thread-safe rolling pass statistics (the decisions/sec/chip
    counter from BASELINE.json, kept in-framework)."""

    keep: int = 256
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)
    _passes: list[PassRecord] = field(default_factory=list, repr=False)
    _pass_count: int = 0  # monotonic; _passes is a bounded window of it
    _total_pods: int = 0
    _total_scheduled: int = 0
    _total_wall_s: float = 0.0

    def record(self, rec: PassRecord) -> None:
        with self._lock:
            self._passes.append(rec)
            if len(self._passes) > self.keep:
                self._passes = self._passes[-self.keep :]
            self._pass_count += 1
            self._total_pods += rec.pods
            self._total_scheduled += rec.scheduled
            self._total_wall_s += rec.wall_s

    @contextmanager
    def time_pass(self, mode: str):
        """Context manager: `ctx.done(pods, scheduled, rounds=...)` inside
        the block stamps the pass; wall time is measured around it."""
        holder = {}

        class _Ctx:
            @staticmethod
            def done(pods: int, scheduled: int, rounds: int = 0):
                holder["args"] = (pods, scheduled, rounds)

        t0 = time.perf_counter()
        yield _Ctx
        wall = time.perf_counter() - t0
        pods, scheduled, rounds = holder.get("args", (0, 0, 0))
        self.record(PassRecord(mode, pods, scheduled, wall, rounds))

    def snapshot(self) -> dict:
        with self._lock:
            recent = self._passes[-16:]
            return {
                "passes": self._pass_count,
                "totalPods": self._total_pods,
                "totalScheduled": self._total_scheduled,
                "totalWallSeconds": round(self._total_wall_s, 6),
                "decisionsPerSecond": round(
                    self._total_pods / self._total_wall_s, 2
                )
                if self._total_wall_s > 0
                else 0.0,
                "recent": [
                    {
                        "mode": r.mode,
                        "pods": r.pods,
                        "scheduled": r.scheduled,
                        "wallSeconds": round(r.wall_s, 6),
                        "decisionsPerSecond": round(r.decisions_per_s, 2),
                        "rounds": r.rounds,
                    }
                    for r in recent
                ],
            }

    def reset(self) -> None:
        with self._lock:
            self._passes.clear()
            self._pass_count = 0
            self._total_pods = 0
            self._total_scheduled = 0
            self._total_wall_s = 0.0


# process-wide shared registry for ad-hoc callers (benchmarks, scripts).
# Serving-layer services each own a SchedulingMetrics instance instead
# (server/service.py) so per-server numbers stay attributable when
# several services share a process.
GLOBAL = SchedulingMetrics()


@contextmanager
def profile_trace(log_dir: str):
    """Capture a JAX profiler trace (TensorBoard/XProf format) around the
    block — per-phase device timing for any pass run inside."""
    import jax

    with jax.profiler.trace(log_dir):
        yield
