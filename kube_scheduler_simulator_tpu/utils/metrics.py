"""In-framework scheduling metrics + profiler wiring (SURVEY.md §5).

The reference has no metrics beyond echo request logging — its *product*
is the decision trace. Here the BASELINE metric (scheduling decisions per
second per chip) is a first-class counter: every scheduling pass reports
into a process-wide `SchedulingMetrics` registry that the serving layer
exposes (`GET /api/v1/metrics`, an extension route) and benchmarks read
directly.

`profile_trace` wraps `jax.profiler.trace` so a pass can be captured for
TensorBoard/XProf without the caller importing jax.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class PassRecord:
    """One scheduling pass (one engine execution over the queue)."""

    mode: str  # "sequential" | "gang" | "extender"
    pods: int  # queue length scheduled over
    scheduled: int  # pods that received a node
    wall_s: float
    rounds: int = 0  # gang mode only

    @property
    def decisions_per_s(self) -> float:
        return self.pods / self.wall_s if self.wall_s > 0 else 0.0


@dataclass
class SchedulingMetrics:
    """Thread-safe rolling pass statistics (the decisions/sec/chip
    counter from BASELINE.json, kept in-framework)."""

    keep: int = 256
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)
    _passes: list[PassRecord] = field(default_factory=list, repr=False)
    _pass_count: int = 0  # monotonic; _passes is a bounded window of it
    _total_pods: int = 0
    _total_scheduled: int = 0
    _total_wall_s: float = 0.0
    # disruption counters (lifecycle/ chaos runs): evictions caused by
    # injected faults, how many of those pods found a node again, and
    # the simulated time each spent pending before its re-bind
    _evicted: int = 0
    _rescheduled: int = 0
    _tts_sum_s: float = 0.0  # sum of time-to-reschedule, sim seconds
    _tts_max_s: float = 0.0
    _tts_count: int = 0
    # phase-timing breakdown (perf_opt PR: where a pass's wall-clock
    # goes — encode vs compile vs execute vs decode) plus the encode-path
    # counters that prove the incremental encoder is carrying churn
    # (docs/performance.md). encodeSeconds includes cache probes;
    # compileSeconds is engine-build time (jit compile included).
    _phase_s: dict = field(
        default_factory=lambda: {
            "encode": 0.0, "compile": 0.0, "execute": 0.0, "decode": 0.0
        },
        repr=False,
    )
    _encode_counts: dict = field(
        default_factory=lambda: {"delta": 0, "full": 0, "cached": 0, "empty": 0},
        repr=False,
    )
    _engine_builds: int = 0
    # compile-broker counters (utils/broker.py): warm-engine hits vs
    # request-thread synchronous compiles, background speculative builds,
    # and request-thread seconds blocked on ANY compile — the stall the
    # predictive warm-up service exists to eliminate
    _compile_hits: int = 0
    _compile_misses: int = 0
    _speculative_compiles: int = 0
    _stall_s: float = 0.0
    # run-supervision / degradation-ladder counters (docs/resilience.md):
    # compile retries after a failed/timed-out build, passes served by
    # the un-jitted eager fallback, passes that ran degraded at all, and
    # speculative-worker crashes contained by the hardened broker loop
    _compile_retries: int = 0
    _eager_fallbacks: int = 0
    _degraded_passes: int = 0
    _worker_crashes: int = 0

    def record(self, rec: PassRecord) -> None:
        with self._lock:
            self._passes.append(rec)
            if len(self._passes) > self.keep:
                self._passes = self._passes[-self.keep :]
            self._pass_count += 1
            self._total_pods += rec.pods
            self._total_scheduled += rec.scheduled
            self._total_wall_s += rec.wall_s

    def record_disruption(
        self,
        evicted: int = 0,
        rescheduled: int = 0,
        times_to_reschedule_s: "list[float] | None" = None,
    ) -> None:
        """One fault-injection event's disruption tally: pods evicted by
        the fault, pods re-bound afterwards, and per-pod simulated
        time-to-reschedule for the re-binds that happened this event."""
        with self._lock:
            self._evicted += int(evicted)
            self._rescheduled += int(rescheduled)
            for t in times_to_reschedule_s or ():
                self._tts_sum_s += float(t)
                self._tts_max_s = max(self._tts_max_s, float(t))
                self._tts_count += 1

    def record_encode(self, mode: str, seconds: float = 0.0) -> None:
        """One encode attempt: `mode` is the path that served it
        (``delta`` / ``full`` / ``cached`` / ``empty``); `seconds` is the
        host time it took (including event replay / cache probes)."""
        with self._lock:
            if mode not in self._encode_counts:
                self._encode_counts[mode] = 0
            self._encode_counts[mode] += 1
            self._phase_s["encode"] += float(seconds)

    def record_engine_build(self, seconds: float = 0.0) -> None:
        """One compiled-engine construction (the recompile proxy: a
        warm churn pass retargets instead and never lands here)."""
        with self._lock:
            self._engine_builds += 1
            self._phase_s["compile"] += float(seconds)

    def record_compile(
        self,
        *,
        hits: int = 0,
        misses: int = 0,
        speculative: int = 0,
        stall_s: float = 0.0,
    ) -> None:
        """Compile-broker accounting: `hits` served warm (including waits
        on an in-flight build), `misses` compiled synchronously on the
        request thread, `speculative` background builds completed,
        `stall_s` request-thread seconds blocked on compilation."""
        with self._lock:
            self._compile_hits += int(hits)
            self._compile_misses += int(misses)
            self._speculative_compiles += int(speculative)
            self._stall_s += float(stall_s)

    def record_resilience(
        self,
        *,
        retries: int = 0,
        eager_fallbacks: int = 0,
        degraded_passes: int = 0,
        worker_crashes: int = 0,
    ) -> None:
        """Degradation-ladder accounting (docs/resilience.md): `retries`
        compile attempts re-run after a failure or deadline, `degraded_passes`
        passes that could not be served by a compiled engine,
        `eager_fallbacks` of those that the un-jitted eager rung served,
        `worker_crashes` speculative-worker crashes the broker contained."""
        with self._lock:
            self._compile_retries += int(retries)
            self._eager_fallbacks += int(eager_fallbacks)
            self._degraded_passes += int(degraded_passes)
            self._worker_crashes += int(worker_crashes)

    def record_phase_seconds(
        self, execute: float = 0.0, decode: float = 0.0
    ) -> None:
        """Per-pass execute (compiled program) / decode (results +
        write-backs) wall seconds."""
        with self._lock:
            self._phase_s["execute"] += float(execute)
            self._phase_s["decode"] += float(decode)

    @contextmanager
    def time_pass(self, mode: str):
        """Context manager: `ctx.done(pods, scheduled, rounds=...)` inside
        the block stamps the pass; wall time is measured around it."""
        holder = {}

        class _Ctx:
            @staticmethod
            def done(pods: int, scheduled: int, rounds: int = 0):
                holder["args"] = (pods, scheduled, rounds)

        t0 = time.perf_counter()
        yield _Ctx
        wall = time.perf_counter() - t0
        pods, scheduled, rounds = holder.get("args", (0, 0, 0))
        self.record(PassRecord(mode, pods, scheduled, wall, rounds))

    def snapshot(self) -> dict:
        with self._lock:
            recent = self._passes[-16:]
            return {
                "passes": self._pass_count,
                "totalPods": self._total_pods,
                "totalScheduled": self._total_scheduled,
                "totalWallSeconds": round(self._total_wall_s, 6),
                "decisionsPerSecond": round(
                    self._total_pods / self._total_wall_s, 2
                )
                if self._total_wall_s > 0
                else 0.0,
                "recent": [
                    {
                        "mode": r.mode,
                        "pods": r.pods,
                        "scheduled": r.scheduled,
                        "wallSeconds": round(r.wall_s, 6),
                        "decisionsPerSecond": round(r.decisions_per_s, 2),
                        "rounds": r.rounds,
                    }
                    for r in recent
                ],
                "disruption": {
                    "evicted": self._evicted,
                    "rescheduled": self._rescheduled,
                    "meanTimeToRescheduleS": round(
                        self._tts_sum_s / self._tts_count, 6
                    )
                    if self._tts_count
                    else 0.0,
                    "maxTimeToRescheduleS": round(self._tts_max_s, 6),
                },
                "phases": {
                    "encodeSeconds": round(self._phase_s["encode"], 6),
                    "compileSeconds": round(self._phase_s["compile"], 6),
                    "executeSeconds": round(self._phase_s["execute"], 6),
                    "decodeSeconds": round(self._phase_s["decode"], 6),
                    "deltaEncodes": self._encode_counts.get("delta", 0),
                    "fullEncodes": self._encode_counts.get("full", 0),
                    "cachedEncodes": self._encode_counts.get("cached", 0),
                    "emptyEncodes": self._encode_counts.get("empty", 0),
                    "engineBuilds": self._engine_builds,
                    "compileHits": self._compile_hits,
                    "compileMisses": self._compile_misses,
                    "speculativeCompiles": self._speculative_compiles,
                    "stallSeconds": round(self._stall_s, 6),
                    "compileRetries": self._compile_retries,
                    "eagerFallbacks": self._eager_fallbacks,
                    "degradedPasses": self._degraded_passes,
                    "brokerWorkerCrashes": self._worker_crashes,
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._passes.clear()
            self._pass_count = 0
            self._total_pods = 0
            self._total_scheduled = 0
            self._total_wall_s = 0.0
            self._evicted = 0
            self._rescheduled = 0
            self._tts_sum_s = 0.0
            self._tts_max_s = 0.0
            self._tts_count = 0
            self._phase_s = {
                "encode": 0.0, "compile": 0.0, "execute": 0.0, "decode": 0.0
            }
            self._encode_counts = {
                "delta": 0, "full": 0, "cached": 0, "empty": 0
            }
            self._engine_builds = 0
            self._compile_hits = 0
            self._compile_misses = 0
            self._speculative_compiles = 0
            self._stall_s = 0.0
            self._compile_retries = 0
            self._eager_fallbacks = 0
            self._degraded_passes = 0
            self._worker_crashes = 0

    # -- checkpointing (lifecycle/checkpoint.py) -----------------------------

    # counter fields a lifecycle checkpoint carries: everything cumulative
    # (the bounded `recent` pass window is cosmetic and stays out)
    _STATE_FIELDS = (
        "_pass_count", "_total_pods", "_total_scheduled", "_total_wall_s",
        "_evicted", "_rescheduled", "_tts_sum_s", "_tts_max_s", "_tts_count",
        "_engine_builds", "_compile_hits", "_compile_misses",
        "_speculative_compiles", "_stall_s", "_compile_retries",
        "_eager_fallbacks", "_degraded_passes", "_worker_crashes",
    )

    def state_dict(self) -> dict:
        """The cumulative counters as one JSON-able dict — what a
        lifecycle checkpoint persists so a resumed run's final metrics
        report the WHOLE run, not just the post-resume suffix."""
        with self._lock:
            out = {f: getattr(self, f) for f in self._STATE_FIELDS}
            out["_phase_s"] = dict(self._phase_s)
            out["_encode_counts"] = dict(self._encode_counts)
            return out

    def load_state(self, state: dict) -> None:
        """Restore counters written by `state_dict` (unknown keys are
        ignored so old checkpoints stay loadable across counter growth)."""
        with self._lock:
            for f in self._STATE_FIELDS:
                if f in state:
                    setattr(self, f, state[f])
            for key in ("_phase_s", "_encode_counts"):
                if isinstance(state.get(key), dict):
                    getattr(self, key).update(state[key])


# process-wide shared registry for ad-hoc callers (benchmarks, scripts).
# Serving-layer services each own a SchedulingMetrics instance instead
# (server/service.py) so per-server numbers stay attributable when
# several services share a process.
GLOBAL = SchedulingMetrics()


@contextmanager
def profile_trace(log_dir: str):
    """Capture a JAX profiler trace (TensorBoard/XProf format) around the
    block — per-phase device timing for any pass run inside."""
    import jax

    with jax.profiler.trace(log_dir):
        yield


# MFU denominator for the one real accelerator class in this image: a
# TPU v5e (v5 lite) chip — 197 TFLOP/s bf16 peak (394 TOPS int8). The
# scheduling kernels are f32/int32 elementwise+reduce, so measured MFU
# is expected to be ~0: the point of reporting it is to make
# "latency-bound, negligible MFU" a measured number rather than prose
# (VERDICT r4 missing #2), and to give the optimization loop a
# denominator that doesn't move between rounds. "axon" is the
# experimental PJRT plugin fronting that same v5e chip in this image —
# whatever name the backend reports, the silicon (and peak) is the v5e.
PEAK_FLOPS_PER_S = {"tpu": 197.0e12, "v5e": 197.0e12, "axon": 197.0e12}


def cost_analysis(jitted, *args) -> "dict | None":
    """FLOPs + bytes of one execution of `jitted(*args)` from XLA's own
    compiled-program cost model.

    Uses the AOT path (`.lower(*args).compile().cost_analysis()`) which
    shares the jit compilation cache, so calling this after the program
    already ran is cheap. Returns {"flops": float, "bytes": float} or
    None when the backend doesn't expose a cost model (the experimental
    axon backend may not) — callers must treat None as "unavailable",
    never as zero work."""
    try:
        compiled = jitted.lower(*args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            return None
        return {
            "flops": float(ca.get("flops", 0.0) or 0.0),
            "bytes": float(ca.get("bytes accessed", 0.0) or 0.0),
        }
    except Exception:  # noqa: BLE001 — cost telemetry must never break a run
        return None


def mfu(flops: "float | None", seconds: float, platform: str) -> "float | None":
    """Model-FLOPs-utilization of `flops` of useful work in `seconds`
    against the platform's peak; None off-accelerator or without a
    cost-model number."""
    if not flops or seconds <= 0:
        return None
    for key, peak in PEAK_FLOPS_PER_S.items():
        if platform.startswith(key):
            return flops / seconds / peak
    return None


def cost_fields(
    jitted, args: tuple, seconds: "float | None" = None,
    platform: str = "", per: str = "",
) -> dict:
    """The shared cost-telemetry block of every bench program: run
    `cost_analysis`, and when it answers emit `flops`/`bytes` (suffixed
    `_per_<per>` when given) plus — with a measured wall `seconds` —
    `flops_per_s` and, on a known accelerator, `mfu`. Empty dict when
    the backend exposes no cost model (callers merge it and move on)."""
    cost = cost_analysis(jitted, *args)
    if not cost:
        return {}
    sfx = f"_per_{per}" if per else ""
    out = {f"flops{sfx}": cost["flops"], f"bytes{sfx}": cost["bytes"]}
    if seconds is not None and seconds > 0:
        out["flops_per_s"] = round(cost["flops"] / seconds, 1)
        m = mfu(cost["flops"], seconds, platform)
        if m is not None:
            out["mfu"] = m
    return out
