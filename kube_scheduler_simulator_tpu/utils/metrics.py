"""In-framework scheduling metrics + profiler wiring (SURVEY.md §5).

The reference has no metrics beyond echo request logging — its *product*
is the decision trace. Here the BASELINE metric (scheduling decisions per
second per chip) is a first-class counter: every scheduling pass reports
into a process-wide `SchedulingMetrics` registry that the serving layer
exposes (`GET /api/v1/metrics`, an extension route) and benchmarks read
directly.

`profile_trace` wraps `jax.profiler.trace` so a pass can be captured for
TensorBoard/XProf without the caller importing jax.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from . import locking, telemetry

# The /api/v1/metrics JSON document's schema version: bumped whenever a
# field changes meaning or disappears (additions don't bump it). v2
# introduced the version stamp itself, uptimeSeconds, and the
# histograms block; v3 marks the observatory document shape — the
# `coldStart` (phase accounting, timeToFirstPassSeconds) and `programs`
# (per-program ledger summary) blocks the serving layer attaches; v4
# marks the SLO plane shape (docs/observability.md): the `slo` block
# (per-objective compliance + alert states, utils/slo.py) and the
# histogram `exemplars` entries the OpenMetrics exposition attaches to
# buckets. The `batching` block (continuous batching, PR 14) is a pure
# ADDITION — per this contract it does not bump the version.
METRICS_SCHEMA_VERSION = 4

# Exemplar capture (docs/observability.md): histogram observations
# remember the causal pass id of a recent observation per bucket, so
# `?format=openmetrics` can link a latency bucket straight to its
# Perfetto span. On by default (one dict write per observation); any
# FALSY spelling of KSS_EXEMPLARS disables capture entirely.
_EXEMPLARS_VAR = "KSS_EXEMPLARS"


def exemplars_enabled() -> bool:
    from .envcheck import FALSY

    raw = os.environ.get(_EXEMPLARS_VAR)
    if not raw:
        return True  # unset/empty = the default: capture on
    return raw.strip().lower() not in FALSY


class Histogram:
    """A fixed-bucket histogram in the Prometheus style: per-bucket
    observation counts over strictly increasing upper bounds plus an
    implicit +Inf overflow, a running sum, a total count, and the most
    recent EXEMPLAR per bucket (the observation's causal pass id — the
    OpenMetrics hook that links a bucket to its Perfetto span,
    docs/observability.md). NOT itself thread-safe —
    `SchedulingMetrics` guards every observation and read with its own
    lock."""

    __slots__ = ("bounds", "counts", "sum", "count", "exemplars")

    def __init__(self, bounds: "tuple[float, ...]"):
        bounds = tuple(float(b) for b in bounds)
        if not bounds or any(
            b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])
        ):
            raise ValueError(
                f"histogram bounds must be strictly increasing: {bounds}"
            )
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # [-1] is the +Inf bucket
        self.sum = 0.0
        self.count = 0
        # parallel to counts: the latest exemplar landing in each
        # bucket — {"labels": {...}, "value": v, "timestamp": wall}
        self.exemplars: "list[dict | None]" = [None] * (len(bounds) + 1)

    def observe(self, value: float, exemplar: "dict | None" = None) -> None:
        v = float(value)
        idx = bisect.bisect_left(self.bounds, v)
        self.counts[idx] += 1
        self.sum += v
        self.count += 1
        if exemplar is not None:
            self.exemplars[idx] = {
                "labels": dict(exemplar),
                "value": v,
                "timestamp": round(time.time(), 3),
            }

    def _bucket_keys(self) -> "list[str]":
        return [repr(b) for b in self.bounds] + ["+Inf"]

    def snapshot(self) -> dict:
        """JSON shape (the /api/v1/metrics histograms block): CUMULATIVE
        bucket counts keyed by upper bound, Prometheus-style, plus the
        per-bucket exemplars (NON-cumulative: an exemplar belongs to
        the bucket its observation landed in)."""
        cum = 0
        buckets = {}
        for bound, n in zip(self.bounds, self.counts):
            cum += n
            buckets[repr(bound)] = cum
        buckets["+Inf"] = self.count
        out = {
            "buckets": buckets,
            "sum": round(self.sum, 9),
            "count": self.count,
        }
        exemplars = {
            key: dict(ex)
            for key, ex in zip(self._bucket_keys(), self.exemplars)
            if ex is not None
        }
        if exemplars:
            out["exemplars"] = exemplars
        return out

    def state_dict(self) -> dict:
        return {
            "bounds": list(self.bounds),
            "counts": list(self.counts),
            "sum": self.sum,
            "count": self.count,
            "exemplars": [
                dict(ex) if ex is not None else None for ex in self.exemplars
            ],
        }

    def load_state(self, state: dict) -> None:
        """Restore `state_dict` output. A checkpoint written with
        different bucket bounds cannot be re-bucketed exactly — it is
        ignored (fresh histogram) rather than loaded wrong. Exemplar
        state written before the SLO PR is simply absent and those
        slots restart empty."""
        if tuple(float(b) for b in state.get("bounds", ())) != self.bounds:
            return
        counts = state.get("counts")
        if not isinstance(counts, list) or len(counts) != len(self.counts):
            return
        self.counts = [int(c) for c in counts]
        self.sum = float(state.get("sum", 0.0))
        self.count = int(state.get("count", 0))
        exemplars = state.get("exemplars")
        if isinstance(exemplars, list) and len(exemplars) == len(self.exemplars):
            self.exemplars = [
                dict(ex) if isinstance(ex, dict) else None for ex in exemplars
            ]


# Default bucket bounds. Pass latency and compile stalls are wall-clock
# host seconds (sub-ms warm passes up to ~minute-scale cold compiles);
# time-to-reschedule is SIMULATED seconds (lifecycle disruption scale).
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
TTS_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 600.0)

# (JSON key in the histograms block, Prometheus metric name, bounds,
# help text) — the ONE place the histogram families are defined, so the
# JSON snapshot, the exposition text, and the checkpoint state can't
# drift apart.
HISTOGRAM_FAMILIES = (
    (
        "passLatencySeconds",
        "kss_pass_latency_seconds",
        LATENCY_BUCKETS,
        "Wall-clock latency of one scheduling pass.",
    ),
    (
        "compileStallSeconds",
        "kss_compile_stall_seconds",
        LATENCY_BUCKETS,
        "Request-thread seconds blocked on one compile (miss builds and "
        "in-flight waits).",
    ),
    (
        "timeToRescheduleSeconds",
        "kss_time_to_reschedule_seconds",
        TTS_BUCKETS,
        "Simulated seconds an evicted pod spent pending before its "
        "re-bind.",
    ),
)


def _new_histograms() -> dict:
    return {
        key: Histogram(bounds) for key, _, bounds, _ in HISTOGRAM_FAMILIES
    }


@dataclass
class PassRecord:
    """One scheduling pass (one engine execution over the queue)."""

    mode: str  # "sequential" | "gang" | "extender"
    pods: int  # queue length scheduled over
    scheduled: int  # pods that received a node
    wall_s: float
    rounds: int = 0  # gang mode only

    @property
    def decisions_per_s(self) -> float:
        return self.pods / self.wall_s if self.wall_s > 0 else 0.0


@dataclass
class SchedulingMetrics:
    """Thread-safe rolling pass statistics (the decisions/sec/chip
    counter from BASELINE.json, kept in-framework)."""

    keep: int = 256
    _lock: threading.Lock = field(
        default_factory=lambda: locking.make_lock("metrics.registry"),
        repr=False,
    )
    _passes: list[PassRecord] = field(default_factory=list, repr=False)
    _pass_count: int = 0  # monotonic; _passes is a bounded window of it
    _total_pods: int = 0
    _total_scheduled: int = 0
    _total_wall_s: float = 0.0
    # disruption counters (lifecycle/ chaos runs): evictions caused by
    # injected faults, how many of those pods found a node again, and
    # the simulated time each spent pending before its re-bind
    _evicted: int = 0
    _rescheduled: int = 0
    _tts_sum_s: float = 0.0  # sum of time-to-reschedule, sim seconds
    _tts_max_s: float = 0.0
    _tts_count: int = 0
    # phase-timing breakdown (perf_opt PR: where a pass's wall-clock
    # goes — encode vs compile vs execute vs decode) plus the encode-path
    # counters that prove the incremental encoder is carrying churn
    # (docs/performance.md). encodeSeconds includes cache probes;
    # compileSeconds is engine-build time (jit compile included).
    _phase_s: dict = field(
        default_factory=lambda: {
            "encode": 0.0, "compile": 0.0, "execute": 0.0, "decode": 0.0
        },
        repr=False,
    )
    _encode_counts: dict = field(
        default_factory=lambda: {"delta": 0, "full": 0, "cached": 0, "empty": 0},
        repr=False,
    )
    # full re-encodes forced by a KSS_DTYPE_POLICY flip landing on a
    # delta encoder retaining the other policy's widths
    _encode_policy_misses: int = 0
    _engine_builds: int = 0
    # compile-broker counters (utils/broker.py): warm-engine hits vs
    # request-thread synchronous compiles, background speculative builds,
    # and request-thread seconds blocked on ANY compile — the stall the
    # predictive warm-up service exists to eliminate
    _compile_hits: int = 0
    _compile_misses: int = 0
    _speculative_compiles: int = 0
    _stall_s: float = 0.0
    # run-supervision / degradation-ladder counters (docs/resilience.md):
    # compile retries after a failed/timed-out build, passes served by
    # the un-jitted eager fallback, passes that ran degraded at all, and
    # speculative-worker crashes contained by the hardened broker loop
    _compile_retries: int = 0
    _eager_fallbacks: int = 0
    _degraded_passes: int = 0
    _worker_crashes: int = 0
    # execution-ladder counters (the runtime device-fault ladder,
    # docs/resilience.md): dispatch attempts re-run after a device
    # fault, passes that escalated to the mid-process CPU failover, and
    # mesh rebuilds over a shrunken surviving-device set
    _dispatch_retries: int = 0
    _device_failovers: int = 0
    _mesh_shrinks: int = 0
    # AOT-bundle counters (utils/bundles.py, KSS_AOT_BUNDLES=1):
    # executables deserialized from / serialized to the on-disk bundle
    # store, bundles present but rejected (version/fingerprint/checksum
    # mismatch — the silent fallback), and the cumulative deserialize
    # wall, kept DISTINCT from stallSeconds' compile wall
    _bundle_loads: int = 0
    _bundle_saves: int = 0
    _bundle_bypasses: int = 0
    _aot_deserialize_s: float = 0.0
    # cross-tenant continuous-batching counters (server/batchplane.py,
    # KSS_BATCH=1): passes served by a batched device dispatch and
    # passes that fell back to solo dispatch land on each SESSION's own
    # registry; windows executed and the cumulative window fill (the
    # occupancy numerator — mean fill = batchOccupancySum/batchWindows,
    # derived in the snapshot's `batching` block) land on the plane's
    # default registry
    _batched_passes: int = 0
    _batch_windows: int = 0
    _batch_occupancy_sum: int = 0
    _solo_fallbacks: int = 0
    # gang-engine counters (engine/gang.py, server/batchplane.py):
    # cumulative commit rounds the device-resident fixpoint used
    # (rounds/pass = gangFixpointRounds / gang passes) and gang passes
    # served by a batched dispatch (batch.gang.run) — both per-session
    _gang_fixpoint_rounds: int = 0
    _batched_gang_passes: int = 0
    # latency-distribution state (the observability PR): Prometheus-style
    # histograms behind the same lock as the counters, rendered into the
    # JSON snapshot's `histograms` block and the exposition text
    _hist: dict = field(default_factory=_new_histograms, repr=False)
    # uptime epoch of this registry (monotonic; NOT checkpointed — a
    # resumed run's uptime is the new process's)
    _born_monotonic: float = field(default_factory=time.monotonic, repr=False)
    # the SLO plane (utils/slo.py): the session id labeling this
    # registry's alerts (set once by the owning SchedulerService), the
    # plane itself (env-derived and cached on the raw KSS_SLO_* strings,
    # or an explicit PUT/test override), and the cache key
    session_id: "str | None" = None
    _slo_plane: "object | None" = field(default=None, repr=False)
    _slo_override: bool = field(default=False, repr=False)
    _slo_env_key: "tuple | None" = field(default=None, repr=False)
    # ratio-objective bookkeeping: a degraded/eager pass's BAD event is
    # emitted by record_resilience (mid-pass), and these counters make
    # the pass's own record() skip the matching GOOD event — one event
    # per pass, so an all-degraded run reads compliance 0.0, not 0.5
    _slo_skip_eager: int = field(default=0, repr=False)
    _slo_skip_degraded: int = field(default=0, repr=False)

    # -- the SLO plane (utils/slo.py) ----------------------------------------

    def slo_plane(self):
        """The registry's SLO plane, or None (the default: plane off).
        An explicit override (`set_slo_plane` — the per-session PUT
        route, checkpoint restore, tests) wins; otherwise the plane is
        built from the KSS_SLO_* environment and rebuilt only when
        those raw strings change (the telemetry `active()` pattern)."""
        from . import slo as slo_mod

        with self._lock:
            if self._slo_override:
                return self._slo_plane
            key = slo_mod.env_key()
            if self._slo_env_key == key:
                return self._slo_plane
            plane = (
                slo_mod.SloPlane(session_id=self.session_id)
                if slo_mod.enabled()
                else None
            )
            self._slo_env_key = key
            self._slo_plane = plane
            return plane

    def set_slo_plane(self, plane) -> None:
        """Install `plane` regardless of the environment (None = plane
        explicitly off) — the per-session PUT /slo override."""
        with self._lock:
            self._slo_plane = plane
            self._slo_override = True

    def clear_slo_override(self) -> None:
        """Drop any explicit plane; the environment rules again."""
        with self._lock:
            self._slo_plane = None
            self._slo_override = False
            self._slo_env_key = None

    def slo_tick(self, sim_t: float) -> None:
        """Advance the plane's clock to simulated time `sim_t` (the
        lifecycle engine's per-batch call): windows slide and alerts
        evaluate on the run's own timeline. No-op with the plane off."""
        plane = self.slo_plane()
        if plane is not None:
            plane.tick_sim(sim_t)

    def record_pending_age(
        self, p90_s: float, max_s: "float | None" = None
    ) -> None:
        """The pending-age observation point (fed by the fleet
        observatory's per-pass age percentiles, utils/fleetstats.py —
        the one place queue age is already measured): one SLO event
        per sampled pass, judged against the pendingAge threshold."""
        plane = self.slo_plane()
        if plane is not None:
            plane.observe("pendingAge", value=float(p90_s))

    def _hist_exemplar(self, pass_id: "int | None" = None) -> "dict | None":
        """The exemplar attached to a histogram observation: the causal
        pass id (`span_id` — the id every one of the pass's Perfetto
        spans carries as args.pass) plus the session label. None when
        capture is disabled (KSS_EXEMPLARS) or no pass is in context."""
        if not exemplars_enabled():
            return None
        pid = pass_id if pass_id is not None else telemetry.current_pass_id()
        if pid is None:
            return None
        ex = {"span_id": str(pid)}
        sid = telemetry.current_session_id() or self.session_id
        if sid is not None:
            ex["session"] = sid
        return ex

    def record(self, rec: PassRecord, pass_id: "int | None" = None) -> None:
        exemplar = self._hist_exemplar(pass_id)
        with self._lock:
            self._passes.append(rec)
            if len(self._passes) > self.keep:
                self._passes = self._passes[-self.keep :]
            self._pass_count += 1
            self._total_pods += rec.pods
            self._total_scheduled += rec.scheduled
            self._total_wall_s += rec.wall_s
            self._hist["passLatencySeconds"].observe(
                rec.wall_s, exemplar=exemplar
            )
            # this pass's ratio events: a degraded/eager pass already
            # emitted its BAD event from record_resilience — consume
            # the skip so the pass contributes exactly one event
            eager_ok = self._slo_skip_eager <= 0
            if not eager_ok:
                self._slo_skip_eager -= 1
            degraded_ok = self._slo_skip_degraded <= 0
            if not degraded_ok:
                self._slo_skip_degraded -= 1
        # cold-start accounting (utils/ledger.py): every pass — any
        # registry, any driver — lands here, so the FIRST one that
        # actually placed a pod closes the process's
        # timeToFirstPassSeconds window (latched; one dict probe per
        # pass afterwards). Empty passes don't count: the headline is
        # time-to-first-SCHEDULED-pod, not time-to-first-no-op.
        if rec.scheduled > 0:
            from .ledger import COLD_START

            COLD_START.mark("firstPass")
        # SLO observation points (utils/slo.py), outside the lock: one
        # passLatency event per pass, plus the GOOD half of the
        # eager-fallback / degraded-pass ratio objectives — skipped for
        # a pass whose bad event record_resilience already emitted
        plane = self.slo_plane()
        if plane is not None:
            plane.observe("passLatency", value=rec.wall_s)
            if eager_ok:
                plane.observe("eagerFallback", good=True)
            if degraded_ok:
                plane.observe("degradedPass", good=True)

    def record_disruption(
        self,
        evicted: int = 0,
        rescheduled: int = 0,
        times_to_reschedule_s: "list[float] | None" = None,
    ) -> None:
        """One fault-injection event's disruption tally: pods evicted by
        the fault, pods re-bound afterwards, and per-pod simulated
        time-to-reschedule for the re-binds that happened this event."""
        times = [float(t) for t in times_to_reschedule_s or ()]
        exemplar = self._hist_exemplar() if times else None
        with self._lock:
            self._evicted += int(evicted)
            self._rescheduled += int(rescheduled)
            for t in times:
                self._tts_sum_s += t
                self._tts_max_s = max(self._tts_max_s, t)
                self._tts_count += 1
                self._hist["timeToRescheduleSeconds"].observe(
                    t, exemplar=exemplar
                )
        if times:
            plane = self.slo_plane()
            if plane is not None:
                for t in times:
                    plane.observe("timeToReschedule", value=t)

    def record_encode(self, mode: str, seconds: float = 0.0) -> None:
        """One encode attempt: `mode` is the path that served it
        (``delta`` / ``full`` / ``cached`` / ``empty``); `seconds` is the
        host time it took (including event replay / cache probes)."""
        with self._lock:
            if mode not in self._encode_counts:
                self._encode_counts[mode] = 0
            self._encode_counts[mode] += 1
            self._phase_s["encode"] += float(seconds)

    def record_encode_policy_miss(self) -> None:
        """One full re-encode whose only trigger was a dtype-policy flip
        (the fallback ladder protecting the width contract)."""
        with self._lock:
            self._encode_policy_misses += 1

    def record_engine_build(self, seconds: float = 0.0) -> None:
        """One compiled-engine construction (the recompile proxy: a
        warm churn pass retargets instead and never lands here)."""
        with self._lock:
            self._engine_builds += 1
            self._phase_s["compile"] += float(seconds)

    def record_compile(
        self,
        *,
        hits: int = 0,
        misses: int = 0,
        speculative: int = 0,
        stall_s: float = 0.0,
    ) -> None:
        """Compile-broker accounting: `hits` served warm (including waits
        on an in-flight build), `misses` compiled synchronously on the
        request thread, `speculative` background builds completed,
        `stall_s` request-thread seconds blocked on compilation."""
        exemplar = self._hist_exemplar() if stall_s > 0 else None
        with self._lock:
            self._compile_hits += int(hits)
            self._compile_misses += int(misses)
            self._speculative_compiles += int(speculative)
            self._stall_s += float(stall_s)
            if stall_s > 0:
                self._hist["compileStallSeconds"].observe(
                    float(stall_s), exemplar=exemplar
                )

    def record_resilience(
        self,
        *,
        retries: int = 0,
        eager_fallbacks: int = 0,
        degraded_passes: int = 0,
        worker_crashes: int = 0,
        dispatch_retries: int = 0,
        device_failovers: int = 0,
        mesh_shrinks: int = 0,
    ) -> None:
        """Degradation-ladder accounting (docs/resilience.md): `retries`
        compile attempts re-run after a failure or deadline, `degraded_passes`
        passes that could not be served by a compiled engine,
        `eager_fallbacks` of those that the un-jitted eager rung served,
        `worker_crashes` speculative-worker crashes the broker contained.
        The execution ladder's rungs land here too: `dispatch_retries`
        device dispatches re-run after a device fault, `mesh_shrinks`
        engine rebuilds over a shrunken surviving-device mesh, and
        `device_failovers` passes that escalated to the mid-process CPU
        failover rung."""
        with self._lock:
            self._compile_retries += int(retries)
            self._eager_fallbacks += int(eager_fallbacks)
            self._degraded_passes += int(degraded_passes)
            self._worker_crashes += int(worker_crashes)
            self._dispatch_retries += int(dispatch_retries)
            self._device_failovers += int(device_failovers)
            self._mesh_shrinks += int(mesh_shrinks)
            # arm the ratio-objective skips: the enclosing pass's
            # record() must not also count a GOOD event for a pass
            # whose bad event lands right here
            self._slo_skip_eager += int(eager_fallbacks)
            self._slo_skip_degraded += int(degraded_passes)
        # the bad halves of the ratio objectives (utils/slo.py),
        # emitted immediately — a terminally-degraded pass that never
        # reaches record() still burns its budget
        if eager_fallbacks or degraded_passes:
            plane = self.slo_plane()
            if plane is not None:
                if eager_fallbacks:
                    plane.observe(
                        "eagerFallback", good=False, count=int(eager_fallbacks)
                    )
                if degraded_passes:
                    plane.observe(
                        "degradedPass", good=False, count=int(degraded_passes)
                    )

    def record_bundles(
        self,
        *,
        loads: int = 0,
        saves: int = 0,
        bypasses: int = 0,
        deserialize_s: float = 0.0,
    ) -> None:
        """AOT-bundle-store accounting (utils/bundles.py): `loads`
        executables deserialized from disk instead of compiled, `saves`
        bundles written, `bypasses` bundles present but rejected (the
        silent fall-back-to-compile path), `deserialize_s` wall seconds
        spent deserializing — never booked as compile stall."""
        with self._lock:
            self._bundle_loads += int(loads)
            self._bundle_saves += int(saves)
            self._bundle_bypasses += int(bypasses)
            self._aot_deserialize_s += float(deserialize_s)

    def record_batching(
        self,
        *,
        batched_passes: int = 0,
        windows: int = 0,
        occupancy: int = 0,
        solo_fallbacks: int = 0,
    ) -> None:
        """Continuous-batching accounting (server/batchplane.py):
        `batched_passes` passes this registry's session had served by a
        batched device dispatch, `solo_fallbacks` passes that fell back
        to solo dispatch (incompatible, lone window, fault-scoped, or a
        failed batched execution), `windows` batched windows executed
        and `occupancy` the window's fill — the latter two recorded on
        the plane's default registry."""
        with self._lock:
            self._batched_passes += int(batched_passes)
            self._batch_windows += int(windows)
            self._batch_occupancy_sum += int(occupancy)
            self._solo_fallbacks += int(solo_fallbacks)

    def record_gang(
        self, *, fixpoint_rounds: int = 0, batched_passes: int = 0
    ) -> None:
        """Gang-engine accounting: `fixpoint_rounds` commit rounds the
        pass's device-resident fixpoint used (engine/gang.py — booked at
        decode, where the rounds scalar is fetched with the assignment
        anyway), `batched_passes` gang passes this registry's session
        had served by a cross-tenant batched dispatch
        (server/batchplane.py ``batch.gang.run``)."""
        with self._lock:
            self._gang_fixpoint_rounds += int(fixpoint_rounds)
            self._batched_gang_passes += int(batched_passes)

    def record_phase_seconds(
        self, execute: float = 0.0, decode: float = 0.0
    ) -> None:
        """Per-pass execute (compiled program) / decode (results +
        write-backs) wall seconds."""
        with self._lock:
            self._phase_s["execute"] += float(execute)
            self._phase_s["decode"] += float(decode)

    @contextmanager
    def time_pass(self, mode: str):
        """Context manager: `ctx.done(pods, scheduled, rounds=...)` inside
        the block stamps the pass; wall time is measured around it."""
        holder = {}

        class _Ctx:
            @staticmethod
            def done(pods: int, scheduled: int, rounds: int = 0):
                holder["args"] = (pods, scheduled, rounds)

        t0 = time.perf_counter()
        yield _Ctx
        wall = time.perf_counter() - t0
        pods, scheduled, rounds = holder.get("args", (0, 0, 0))
        self.record(PassRecord(mode, pods, scheduled, wall, rounds))

    def snapshot(self) -> dict:
        with self._lock:
            recent = self._passes[-16:]
            doc = {
                "schemaVersion": METRICS_SCHEMA_VERSION,
                "uptimeSeconds": round(
                    time.monotonic() - self._born_monotonic, 3
                ),
                "passes": self._pass_count,
                "totalPods": self._total_pods,
                "totalScheduled": self._total_scheduled,
                "totalWallSeconds": round(self._total_wall_s, 6),
                "decisionsPerSecond": round(
                    self._total_pods / self._total_wall_s, 2
                )
                if self._total_wall_s > 0
                else 0.0,
                "recent": [
                    {
                        "mode": r.mode,
                        "pods": r.pods,
                        "scheduled": r.scheduled,
                        "wallSeconds": round(r.wall_s, 6),
                        "decisionsPerSecond": round(r.decisions_per_s, 2),
                        "rounds": r.rounds,
                    }
                    for r in recent
                ],
                "disruption": {
                    "evicted": self._evicted,
                    "rescheduled": self._rescheduled,
                    "meanTimeToRescheduleS": round(
                        self._tts_sum_s / self._tts_count, 6
                    )
                    if self._tts_count
                    else 0.0,
                    "maxTimeToRescheduleS": round(self._tts_max_s, 6),
                },
                "phases": {
                    "encodeSeconds": round(self._phase_s["encode"], 6),
                    "compileSeconds": round(self._phase_s["compile"], 6),
                    "executeSeconds": round(self._phase_s["execute"], 6),
                    "decodeSeconds": round(self._phase_s["decode"], 6),
                    "deltaEncodes": self._encode_counts.get("delta", 0),
                    "fullEncodes": self._encode_counts.get("full", 0),
                    "cachedEncodes": self._encode_counts.get("cached", 0),
                    "emptyEncodes": self._encode_counts.get("empty", 0),
                    "encodePolicyMisses": self._encode_policy_misses,
                    "engineBuilds": self._engine_builds,
                    "compileHits": self._compile_hits,
                    "compileMisses": self._compile_misses,
                    "speculativeCompiles": self._speculative_compiles,
                    "stallSeconds": round(self._stall_s, 6),
                    "compileRetries": self._compile_retries,
                    "eagerFallbacks": self._eager_fallbacks,
                    "degradedPasses": self._degraded_passes,
                    "brokerWorkerCrashes": self._worker_crashes,
                    "dispatchRetries": self._dispatch_retries,
                    "deviceFailovers": self._device_failovers,
                    "meshShrinks": self._mesh_shrinks,
                    "bundleLoads": self._bundle_loads,
                    "bundleSaves": self._bundle_saves,
                    "bundleBypasses": self._bundle_bypasses,
                    "aotDeserializeSeconds": round(self._aot_deserialize_s, 6),
                    "batchedPasses": self._batched_passes,
                    "batchWindows": self._batch_windows,
                    "batchOccupancySum": self._batch_occupancy_sum,
                    "soloFallbacks": self._solo_fallbacks,
                    "gangFixpointRounds": self._gang_fixpoint_rounds,
                    "batchedGangPasses": self._batched_gang_passes,
                },
                # derived continuous-batching view (server/batchplane.py):
                # mean window fill — a ratio, so it lives outside the
                # cumulative `phases` counters the checkpoint carries
                "batching": {
                    "batchOccupancy": round(
                        self._batch_occupancy_sum / self._batch_windows, 3
                    )
                    if self._batch_windows
                    else 0.0,
                },
                "histograms": {
                    key: h.snapshot() for key, h in self._hist.items()
                },
            }
        # the SLO block (schema v4, utils/slo.py) attaches OUTSIDE the
        # registry lock — the plane has its own lock, and the two never
        # nest (lock-order discipline, docs/static-analysis.md)
        plane = self.slo_plane()
        doc["slo"] = plane.summary() if plane is not None else {"enabled": False}
        return doc

    def reset(self) -> None:
        with self._lock:
            self._passes.clear()
            self._pass_count = 0
            self._total_pods = 0
            self._total_scheduled = 0
            self._total_wall_s = 0.0
            self._evicted = 0
            self._rescheduled = 0
            self._tts_sum_s = 0.0
            self._tts_max_s = 0.0
            self._tts_count = 0
            self._phase_s = {
                "encode": 0.0, "compile": 0.0, "execute": 0.0, "decode": 0.0
            }
            self._encode_counts = {
                "delta": 0, "full": 0, "cached": 0, "empty": 0
            }
            self._encode_policy_misses = 0
            self._engine_builds = 0
            self._compile_hits = 0
            self._compile_misses = 0
            self._speculative_compiles = 0
            self._stall_s = 0.0
            self._compile_retries = 0
            self._eager_fallbacks = 0
            self._degraded_passes = 0
            self._worker_crashes = 0
            self._dispatch_retries = 0
            self._device_failovers = 0
            self._mesh_shrinks = 0
            self._bundle_loads = 0
            self._bundle_saves = 0
            self._bundle_bypasses = 0
            self._aot_deserialize_s = 0.0
            self._batched_passes = 0
            self._batch_windows = 0
            self._batch_occupancy_sum = 0
            self._solo_fallbacks = 0
            self._gang_fixpoint_rounds = 0
            self._batched_gang_passes = 0
            self._slo_skip_eager = 0
            self._slo_skip_degraded = 0
            self._hist = _new_histograms()
            self._born_monotonic = time.monotonic()

    # -- checkpointing (lifecycle/checkpoint.py) -----------------------------

    # counter fields a lifecycle checkpoint carries: everything cumulative
    # (the bounded `recent` pass window is cosmetic and stays out)
    _STATE_FIELDS = (
        "_pass_count", "_total_pods", "_total_scheduled", "_total_wall_s",
        "_evicted", "_rescheduled", "_tts_sum_s", "_tts_max_s", "_tts_count",
        "_encode_policy_misses",
        "_engine_builds", "_compile_hits", "_compile_misses",
        "_speculative_compiles", "_stall_s", "_compile_retries",
        "_eager_fallbacks", "_degraded_passes", "_worker_crashes",
        "_dispatch_retries", "_device_failovers", "_mesh_shrinks",
        "_bundle_loads", "_bundle_saves", "_bundle_bypasses",
        "_aot_deserialize_s",
        "_batched_passes", "_batch_windows", "_batch_occupancy_sum",
        "_solo_fallbacks", "_gang_fixpoint_rounds", "_batched_gang_passes",
    )

    def state_dict(self) -> dict:
        """The cumulative counters as one JSON-able dict — what a
        lifecycle checkpoint persists so a resumed run's final metrics
        report the WHOLE run, not just the post-resume suffix. With the
        SLO plane armed, its window + alert state rides along
        (`_slo`), so a drained/resumed process keeps burning the same
        error budget instead of starting a fresh one."""
        with self._lock:
            out = {f: getattr(self, f) for f in self._STATE_FIELDS}
            out["_phase_s"] = dict(self._phase_s)
            out["_encode_counts"] = dict(self._encode_counts)
            out["_histograms"] = {
                key: h.state_dict() for key, h in self._hist.items()
            }
        plane = self.slo_plane()
        if plane is not None:
            out["_slo"] = plane.state_dict()
        return out

    def load_state(self, state: dict) -> None:
        """Restore counters written by `state_dict` (unknown keys are
        ignored so old checkpoints stay loadable across counter growth;
        histogram state written before the telemetry PR is simply
        absent and those distributions restart empty). A checkpointed
        SLO plane is restored when it was an explicit override OR the
        environment still arms the plane — an operator who turned
        KSS_SLO off must not have a checkpoint re-arm it."""
        slo_state = state.get("_slo")
        with self._lock:
            for f in self._STATE_FIELDS:
                if f in state:
                    setattr(self, f, state[f])
            for key in ("_phase_s", "_encode_counts"):
                if isinstance(state.get(key), dict):
                    getattr(self, key).update(state[key])
            hists = state.get("_histograms")
            if isinstance(hists, dict):
                for key, h in self._hist.items():
                    if isinstance(hists.get(key), dict):
                        h.load_state(hists[key])
        if isinstance(slo_state, dict):
            from . import slo as slo_mod

            explicit = bool(
                (slo_state.get("config") or {}).get("explicit")
            )
            if explicit or slo_mod.enabled():
                plane = slo_mod.SloPlane.from_state(slo_state)
                if plane.session_id is None:
                    plane.session_id = self.session_id
                # an explicit (PUT-override) plane restores as an
                # override; an env-derived one restores into the env
                # cache slot instead — a later KSS_SLO_* change must
                # still rebuild/disarm it, exactly as before the resume
                with self._lock:
                    self._slo_plane = plane
                    self._slo_override = explicit
                    self._slo_env_key = None if explicit else slo_mod.env_key()


# process-wide shared registry for ad-hoc callers (benchmarks, scripts).
# Serving-layer services each own a SchedulingMetrics instance instead
# (server/service.py) so per-server numbers stay attributable when
# several services share a process.
GLOBAL = SchedulingMetrics()


# -- Prometheus exposition ----------------------------------------------------

# (metric name, help, snapshot path) — counters straight off the JSON
# snapshot. Metric names are STABLE (docs/observability.md's table): a
# rename is a breaking change for every scrape config pointed here.
_PROM_COUNTERS = (
    ("kss_passes_total", "Scheduling passes executed.", ("passes",)),
    ("kss_pods_total", "Pods evaluated across all passes.", ("totalPods",)),
    ("kss_scheduled_total", "Pods that received a node.", ("totalScheduled",)),
    (
        "kss_pass_wall_seconds_total",
        "Wall-clock seconds spent inside scheduling passes.",
        ("totalWallSeconds",),
    ),
    (
        "kss_evicted_total",
        "Pods evicted by injected lifecycle faults.",
        ("disruption", "evicted"),
    ),
    (
        "kss_rescheduled_total",
        "Evicted pods that found a node again.",
        ("disruption", "rescheduled"),
    ),
    (
        "kss_encode_policy_misses_total",
        "Full re-encodes forced by a dtype-policy flip.",
        ("phases", "encodePolicyMisses"),
    ),
    (
        "kss_engine_builds_total",
        "Compiled-engine constructions (the recompile proxy).",
        ("phases", "engineBuilds"),
    ),
    (
        "kss_compile_hits_total",
        "Engine requests served warm by the CompileBroker.",
        ("phases", "compileHits"),
    ),
    (
        "kss_compile_misses_total",
        "Engine requests compiled synchronously on the request thread.",
        ("phases", "compileMisses"),
    ),
    (
        "kss_speculative_compiles_total",
        "Background speculative compiles completed.",
        ("phases", "speculativeCompiles"),
    ),
    (
        "kss_compile_retries_total",
        "Compile attempts re-run after a failure or deadline.",
        ("phases", "compileRetries"),
    ),
    (
        "kss_eager_fallbacks_total",
        "Passes served by the un-jitted eager rung.",
        ("phases", "eagerFallbacks"),
    ),
    (
        "kss_degraded_passes_total",
        "Passes that could not be served by a compiled engine.",
        ("phases", "degradedPasses"),
    ),
    (
        "kss_broker_worker_crashes_total",
        "Speculative-worker crashes contained by the broker.",
        ("phases", "brokerWorkerCrashes"),
    ),
    (
        "kss_stall_seconds_total",
        "Request-thread seconds blocked on any compile.",
        ("phases", "stallSeconds"),
    ),
    (
        "kss_dispatch_retries_total",
        "Device dispatches re-run after a device fault.",
        ("phases", "dispatchRetries"),
    ),
    (
        "kss_device_failovers_total",
        "Passes that escalated to the mid-process CPU failover rung.",
        ("phases", "deviceFailovers"),
    ),
    (
        "kss_mesh_shrinks_total",
        "Engine rebuilds over a shrunken surviving-device mesh.",
        ("phases", "meshShrinks"),
    ),
    (
        "kss_bundle_loads_total",
        "Engine executables deserialized from the AOT bundle store.",
        ("phases", "bundleLoads"),
    ),
    (
        "kss_bundle_saves_total",
        "Engine executables serialized into the AOT bundle store.",
        ("phases", "bundleSaves"),
    ),
    (
        "kss_bundle_bypasses_total",
        "Bundles present but rejected (fell back to a fresh compile).",
        ("phases", "bundleBypasses"),
    ),
    (
        "kss_aot_deserialize_seconds_total",
        "Wall seconds spent deserializing AOT bundles (not compile stall).",
        ("phases", "aotDeserializeSeconds"),
    ),
    (
        "kss_batched_passes_total",
        "Passes served by a cross-tenant batched device dispatch.",
        ("phases", "batchedPasses"),
    ),
    (
        "kss_batch_windows_total",
        "Batched collection windows executed as one device dispatch.",
        ("phases", "batchWindows"),
    ),
    (
        "kss_batch_occupancy_total",
        "Cumulative batched-window fill (mean occupancy numerator).",
        ("phases", "batchOccupancySum"),
    ),
    (
        "kss_solo_fallbacks_total",
        "Passes that fell back from the batch plane to solo dispatch.",
        ("phases", "soloFallbacks"),
    ),
    (
        "kss_gang_fixpoint_rounds_total",
        "Commit rounds used by device-resident gang fixpoint passes.",
        ("phases", "gangFixpointRounds"),
    ),
    (
        "kss_batched_gang_passes_total",
        "Gang passes served by a cross-tenant batched dispatch.",
        ("phases", "batchedGangPasses"),
    ),
)


def _fmt_value(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(
    snapshot: dict,
    extra_gauges: "dict | None" = None,
    openmetrics: bool = False,
) -> str:
    """Render a `SchedulingMetrics.snapshot()` document in the
    Prometheus text exposition format (version 0.0.4): counters,
    gauges, and the histogram families, with stable metric names.
    `extra_gauges` maps metric name -> (help, value) for serving-stack
    extras (the encoding-cache capacity). `openmetrics` attaches the
    snapshot's histogram exemplars to bucket samples
    (``# {span_id="…"} value ts`` — the OpenMetrics exemplar syntax);
    the serving route appends the terminating ``# EOF`` itself, after
    the observatory families."""
    return _render_prometheus(
        [({}, snapshot, extra_gauges)], openmetrics=openmetrics
    )


def render_prometheus_sessions(
    entries: "list[tuple[dict, dict, dict | None]]",
    global_counters: "dict | None" = None,
    global_gauges: "dict | None" = None,
    openmetrics: bool = False,
) -> str:
    """Multi-tenant exposition (docs/sessions.md): one document, each
    family declared ONCE, every sample labeled per entry. `entries` is
    ``[(labels, snapshot, extra_gauges), ...]`` — the session plane
    passes ``{"session": id}`` labels so one scrape covers every tenant.
    `global_counters`/`global_gauges` map name -> (help, value) for
    server-wide unlabeled extras (the SSE drop counter, session counts)."""
    return _render_prometheus(
        entries,
        global_counters=global_counters,
        global_gauges=global_gauges,
        openmetrics=openmetrics,
    )


_WORKER_ID_VAR = "KSS_WORKER_ID"

# lazily-compiled sample-line splitter for `label_exposition` (re stays
# off the import path, like _PROM_SAMPLE_RE below)
_LABEL_INJECT_RE = None


def worker_id(env: "dict | None" = None) -> "str | None":
    """The process's fleet worker identity (``KSS_WORKER_ID``), or None
    outside a fleet. The router launches each worker with a distinct id
    so every exposition self-labels (docs/fleet.md); the value must be
    Prometheus-label-safe (envcheck validates the charset at boot)."""
    env = os.environ if env is None else env
    wid = (env.get(_WORKER_ID_VAR) or "").strip()
    return wid or None


def label_exposition(text: str, labels: "dict[str, str]") -> str:
    """Inject `labels` into EVERY sample line of a text exposition
    (0.0.4 or OpenMetrics) — the fleet's `worker` label, applied after
    the whole document (sessions + ledger + observatory + SLO families)
    is assembled, so no renderer needs to thread the label through.
    Comment lines (`# HELP`/`# TYPE`/`# EOF`) and OpenMetrics exemplar
    suffixes (everything after the sample's value separator) pass
    through untouched."""
    if not labels or not text:
        return text
    global _LABEL_INJECT_RE
    if _LABEL_INJECT_RE is None:
        import re

        _LABEL_INJECT_RE = re.compile(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?( .*)$"
        )
    extra = ",".join(f'{k}="{v}"' for k, v in labels.items())
    out: list[str] = []
    for line in text.split("\n"):
        if not line or line.startswith("#"):
            out.append(line)
            continue
        # split the metric name + optional {label body} off the front;
        # the rest of the line (value, timestamp, exemplar) is opaque
        m = _LABEL_INJECT_RE.match(line)
        if m is None:
            out.append(line)
            continue
        name, body, rest = m.group(1), m.group(2), m.group(3)
        inner = body[1:-1] if body else ""
        merged = f"{inner},{extra}" if inner else extra
        out.append(f"{name}{{{merged}}}{rest}")
    return "\n".join(out)


def _fmt_exemplar(ex: dict) -> str:
    """One OpenMetrics exemplar suffix: ``# {labels} value [timestamp]``
    appended to a histogram bucket sample line."""
    labels = ",".join(
        f'{k}="{v}"' for k, v in (ex.get("labels") or {}).items()
    )
    out = f" # {{{labels}}} {_fmt_value(ex.get('value', 0.0))}"
    ts = ex.get("timestamp")
    if ts is not None:
        out += f" {_fmt_value(ts)}"
    return out


def _label_body(labels: dict, extra: "tuple | None" = None) -> str:
    items = list(labels.items()) + list(extra or ())
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in items) + "}"


def _render_prometheus(
    entries,
    global_counters: "dict | None" = None,
    global_gauges: "dict | None" = None,
    openmetrics: bool = False,
) -> str:
    lines: list[str] = []

    def family(name: str, mtype: str, help_text: str) -> None:
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")

    def walk(snapshot: dict, path: tuple):
        v = snapshot
        for p in path:
            v = v.get(p, 0) if isinstance(v, dict) else 0
        return v if isinstance(v, (int, float)) else 0

    for name, help_text, path in _PROM_COUNTERS:
        family(name, "counter", help_text)
        for labels, snapshot, _extra in entries:
            lines.append(
                f"{name}{_label_body(labels)} "
                f"{_fmt_value(walk(snapshot, path))}"
            )

    family(
        "kss_encodes_total",
        "counter",
        "Cluster encodes by the path that served them.",
    )
    for labels, snapshot, _extra in entries:
        phases = snapshot.get("phases", {})
        for mode, key in (
            ("delta", "deltaEncodes"),
            ("full", "fullEncodes"),
            ("cached", "cachedEncodes"),
            ("empty", "emptyEncodes"),
        ):
            lines.append(
                f"kss_encodes_total{_label_body(labels, (('mode', mode),))} "
                f"{_fmt_value(phases.get(key, 0))}"
            )
    family(
        "kss_phase_seconds_total",
        "counter",
        "Pass wall-clock by phase (encode/compile/execute/decode).",
    )
    for labels, snapshot, _extra in entries:
        phases = snapshot.get("phases", {})
        for phase in ("encode", "compile", "execute", "decode"):
            lines.append(
                f"kss_phase_seconds_total"
                f"{_label_body(labels, (('phase', phase),))} "
                f"{_fmt_value(phases.get(phase + 'Seconds', 0.0))}"
            )

    family("kss_uptime_seconds", "gauge", "Seconds since this registry was born.")
    for labels, snapshot, _extra in entries:
        lines.append(
            f"kss_uptime_seconds{_label_body(labels)} "
            f"{_fmt_value(snapshot.get('uptimeSeconds', 0.0))}"
        )
    family(
        "kss_metrics_schema_version",
        "gauge",
        "Schema version of the /api/v1/metrics JSON document.",
    )
    for labels, snapshot, _extra in entries:
        lines.append(
            f"kss_metrics_schema_version{_label_body(labels)} "
            f"{_fmt_value(snapshot.get('schemaVersion', METRICS_SCHEMA_VERSION))}"
        )
    # per-entry extra gauges: each family declared once (help from the
    # first entry carrying it), then one labeled sample per entry
    extra_names: list[str] = []
    for _labels, _snapshot, extra in entries:
        for name in extra or ():
            if name not in extra_names:
                extra_names.append(name)
    for name in extra_names:
        help_text = next(
            extra[name][0]
            for _l, _s, extra in entries
            if extra and name in extra
        )
        family(name, "gauge", help_text)
        for labels, _snapshot, extra in entries:
            if extra and name in extra:
                lines.append(
                    f"{name}{_label_body(labels)} "
                    f"{_fmt_value(extra[name][1])}"
                )
    for name, (help_text, value) in (global_counters or {}).items():
        family(name, "counter", help_text)
        lines.append(f"{name} {_fmt_value(value)}")
    for name, (help_text, value) in (global_gauges or {}).items():
        family(name, "gauge", help_text)
        lines.append(f"{name} {_fmt_value(value)}")

    for key, name, _, help_text in HISTOGRAM_FAMILIES:
        carrying = [
            (labels, snapshot.get("histograms", {}).get(key))
            for labels, snapshot, _extra in entries
        ]
        carrying = [(labels, h) for labels, h in carrying if h]
        if not carrying:
            continue
        family(name, "histogram", help_text)
        for labels, h in carrying:
            exemplars = h.get("exemplars") or {}
            for le, cum in h["buckets"].items():
                line = (
                    f"{name}_bucket{_label_body(labels, (('le', le),))} "
                    f"{_fmt_value(cum)}"
                )
                if openmetrics and le in exemplars:
                    line += _fmt_exemplar(exemplars[le])
                lines.append(line)
            lines.append(f"{name}_sum{_label_body(labels)} {_fmt_value(h['sum'])}")
            lines.append(
                f"{name}_count{_label_body(labels)} {_fmt_value(h['count'])}"
            )
    return "\n".join(lines) + "\n"


def render_histogram(
    name: str,
    help_text: str,
    snapshot: dict,
    labels: "dict[str, str] | None" = None,
    openmetrics: bool = False,
) -> str:
    """One standalone histogram family in exposition text, from a
    `Histogram.snapshot()` document — the router's request-latency
    families live OUTSIDE any `SchedulingMetrics` registry, so they
    can't ride `_render_prometheus`'s HISTOGRAM_FAMILIES walk. Same
    line grammar: `_bucket{le=...}` (+ OpenMetrics exemplar suffix when
    asked), `_sum`, `_count`. Caller snapshots under its own lock."""
    labels = labels or {}
    lines = [
        f"# HELP {name} {help_text}",
        f"# TYPE {name} histogram",
    ]
    exemplars = snapshot.get("exemplars") or {}
    for le, cum in snapshot["buckets"].items():
        line = (
            f"{name}_bucket{_label_body(labels, (('le', le),))} "
            f"{_fmt_value(cum)}"
        )
        if openmetrics and le in exemplars:
            line += _fmt_exemplar(exemplars[le])
        lines.append(line)
    lines.append(f"{name}_sum{_label_body(labels)} {_fmt_value(snapshot['sum'])}")
    lines.append(
        f"{name}_count{_label_body(labels)} {_fmt_value(snapshot['count'])}"
    )
    return "\n".join(lines) + "\n"


_PROM_SAMPLE_RE = None  # compiled lazily (re import kept off the hot path)


def parse_prometheus_text(text: str) -> dict:
    """A real text-format (0.0.4) parse of an exposition document —
    what the observability smoke and the endpoint tests scrape through,
    so a malformed render can't pass as 'looks about right'. Returns
    ``{family: {"type", "help", "samples": [(name, labels, value)]}}``
    with labels as a dict. Raises ValueError on: unparseable lines,
    samples without a preceding TYPE, duplicate TYPE lines, histogram
    families with non-monotonic cumulative buckets, a missing/out-of-
    order +Inf bucket, or +Inf disagreeing with `_count`.

    OpenMetrics round-trip (the `?format=openmetrics` contract): a
    histogram bucket sample may carry an exemplar suffix
    (``# {labels} value [timestamp]``), collected into the family's
    ``"exemplars"`` list as ``(sample_name, labels, exemplar_labels,
    exemplar_value)``; a malformed exemplar, or one on a non-bucket
    sample, raises. A terminating ``# EOF`` line is accepted."""
    global _PROM_SAMPLE_RE
    import re

    if _PROM_SAMPLE_RE is None:
        _PROM_SAMPLE_RE = (
            re.compile(
                r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"  # metric name
                r"(?:\{(.*)\})?"  # optional label body
                r"\s+(-?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?|NaN|[+-]?Inf)"
                r"(?:\s+-?\d+)?$"  # optional timestamp
            ),
            re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"\s*(?:,|$)'),
        )
    sample_re, label_re = _PROM_SAMPLE_RE
    families: dict = {}

    def family_of(sample_name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            base = sample_name[: -len(suffix)] if sample_name.endswith(suffix) else None
            if base and families.get(base, {}).get("type") == "histogram":
                return base
        return sample_name

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line[len("# HELP ") :].split(" ", 1)
            families.setdefault(parts[0], {"type": None, "help": None, "samples": []})
            families[parts[0]]["help"] = parts[1] if len(parts) > 1 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE ") :].split()
            if len(parts) != 2 or parts[1] not in (
                "counter", "gauge", "histogram", "summary", "untyped",
            ):
                raise ValueError(f"line {lineno}: malformed TYPE line {line!r}")
            fam = families.setdefault(
                parts[0], {"type": None, "help": None, "samples": []}
            )
            if fam["type"] is not None:
                raise ValueError(f"line {lineno}: duplicate TYPE for {parts[0]}")
            fam["type"] = parts[1]
            continue
        if line.startswith("#"):
            continue  # comment (incl. the OpenMetrics "# EOF" terminator)
        # an OpenMetrics exemplar rides the sample line after " # " —
        # but '#' is legal inside quoted label values, so the split
        # point is the first " # " whose PREFIX is a complete sample (a
        # mid-label '#' leaves an unparseable prefix and is skipped).
        # Splits are tried BEFORE the whole-line match: the label
        # regex's greedy braces would otherwise swallow an exemplar's
        # label body into the sample's
        exemplar_part = None
        m = None
        pos = line.find(" # ")
        while pos != -1:
            cand = sample_re.match(line[:pos])
            if cand is not None:
                m = cand
                exemplar_part = line[pos + 3 :]
                break
            pos = line.find(" # ", pos + 1)
        if m is None:
            m = sample_re.match(line)
        if not m:
            raise ValueError(f"line {lineno}: unparseable sample {line!r}")
        name, label_body, raw_value = m.group(1), m.group(2), m.group(3)
        labels = {}
        if label_body:
            consumed = sum(
                len(lm.group(0)) for lm in label_re.finditer(label_body)
            )
            if consumed != len(label_body):
                raise ValueError(
                    f"line {lineno}: malformed label body {label_body!r}"
                )
            labels = {
                lm.group(1): lm.group(2) for lm in label_re.finditer(label_body)
            }
        value = float(raw_value.replace("Inf", "inf"))
        fam_name = family_of(name)
        fam = families.get(fam_name)
        if fam is None or fam["type"] is None:
            raise ValueError(
                f"line {lineno}: sample {name!r} has no preceding TYPE"
            )
        fam["samples"].append((name, labels, value))
        if exemplar_part is not None:
            if fam["type"] != "histogram" or not name.endswith("_bucket"):
                raise ValueError(
                    f"line {lineno}: exemplar on non-bucket sample {name!r}"
                )
            em = re.match(
                r"^\{(.*)\}\s+(-?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?)"
                r"(?:\s+(-?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?))?$",
                exemplar_part,
            )
            if not em:
                raise ValueError(
                    f"line {lineno}: malformed exemplar {exemplar_part!r}"
                )
            ex_body = em.group(1)
            consumed = sum(
                len(lm.group(0)) for lm in label_re.finditer(ex_body)
            )
            if ex_body and consumed != len(ex_body):
                raise ValueError(
                    f"line {lineno}: malformed exemplar labels {ex_body!r}"
                )
            ex_labels = {
                lm.group(1): lm.group(2) for lm in label_re.finditer(ex_body)
            }
            fam.setdefault("exemplars", []).append(
                (name, labels, ex_labels, float(em.group(2)))
            )

    # histogram semantics: cumulative monotone buckets, +Inf last and
    # equal to _count — validated PER LABEL SET (minus `le`), so a
    # multi-session exposition (one series per `session` label,
    # docs/sessions.md) checks each tenant's distribution independently
    for fam_name, fam in families.items():
        if fam["type"] != "histogram":
            continue
        groups: dict = {}

        def series_of(labels: dict) -> tuple:
            return tuple(
                sorted((k, v) for k, v in labels.items() if k != "le")
            )

        for name, labels, value in fam["samples"]:
            g = groups.setdefault(
                series_of(labels), {"buckets": [], "counts": []}
            )
            if name == fam_name + "_bucket":
                g["buckets"].append((labels.get("le"), value))
            elif name == fam_name + "_count":
                g["counts"].append(value)
        for g in groups.values():
            buckets, counts = g["buckets"], g["counts"]
            if not buckets or not counts:
                raise ValueError(
                    f"histogram {fam_name}: missing buckets or _count"
                )
            if buckets[-1][0] != "+Inf":
                raise ValueError(f"histogram {fam_name}: +Inf bucket not last")
            prev = -1.0
            for le, cum in buckets:
                if cum < prev:
                    raise ValueError(
                        f"histogram {fam_name}: non-monotonic bucket le={le}"
                    )
                prev = cum
            if buckets[-1][1] != counts[0]:
                raise ValueError(
                    f"histogram {fam_name}: +Inf bucket {buckets[-1][1]} != "
                    f"_count {counts[0]}"
                )
    return families


@contextmanager
def profile_trace(log_dir: str):
    """Capture a JAX profiler trace (TensorBoard/XProf format) around the
    block — per-phase device timing for any pass run inside."""
    import jax

    with jax.profiler.trace(log_dir):
        yield


# MFU denominator for the one real accelerator class in this image: a
# TPU v5e (v5 lite) chip — 197 TFLOP/s bf16 peak (394 TOPS int8). The
# scheduling kernels are f32/int32 elementwise+reduce, so measured MFU
# is expected to be ~0: the point of reporting it is to make
# "latency-bound, negligible MFU" a measured number rather than prose
# (VERDICT r4 missing #2), and to give the optimization loop a
# denominator that doesn't move between rounds. "axon" is the
# experimental PJRT plugin fronting that same v5e chip in this image —
# whatever name the backend reports, the silicon (and peak) is the v5e.
PEAK_FLOPS_PER_S = {"tpu": 197.0e12, "v5e": 197.0e12, "axon": 197.0e12}


def cost_analysis(jitted, *args) -> "dict | None":
    """FLOPs + bytes of one execution of `jitted(*args)` from XLA's own
    compiled-program cost model.

    Routed through the program ledger's shared AOT probe
    (`utils/ledger.aot_probe` — the same lower/compile/cost path the
    serving-side ledger wrapper times), which shares the jit
    compilation cache, so calling this after the program already ran is
    cheap. Returns {"flops": float, "bytes": float} or None when the
    backend doesn't expose a cost model (the experimental axon backend
    may not) — callers must treat None as "unavailable", never as zero
    work."""
    from .ledger import aot_probe

    probe = aot_probe(jitted, args)
    if probe is None:
        return None
    _compiled, info, _traced = probe
    if info["flops"] is None:
        return None
    return {"flops": info["flops"], "bytes": info["bytes"]}


def mfu(flops: "float | None", seconds: float, platform: str) -> "float | None":
    """Model-FLOPs-utilization of `flops` of useful work in `seconds`
    against the platform's peak; None off-accelerator or without a
    cost-model number."""
    if not flops or seconds <= 0:
        return None
    for key, peak in PEAK_FLOPS_PER_S.items():
        if platform.startswith(key):
            return flops / seconds / peak
    return None


def cost_fields(
    jitted, args: tuple, seconds: "float | None" = None,
    platform: str = "", per: str = "", label: "str | None" = None,
    variants: "int | None" = None,
) -> dict:
    """The shared cost-telemetry block of every bench program: run
    `cost_analysis`, and when it answers emit `flops`/`bytes` (suffixed
    `_per_<per>` when given) plus — with a measured wall `seconds` —
    `flops_per_s` and, on a known accelerator, `mfu`. Empty dict when
    the backend exposes no cost model (callers merge it and move on).

    `label` additionally records the probe into the process ledger
    (`utils/ledger.LEDGER`) so bench and the serving path share one
    accounting. `variants` marks a VMAPPED program: the emitted
    `flops` stays the whole-program cost-model number, and
    `flops_per_variant` spells out the per-variant share — the MFU
    denominator note every headline carries (docs/benchmarking.md:
    the cost model's vmapped totals have been observed inconsistent
    with variants x the single-variant program, BENCH_r05_chip)."""
    if label is not None:
        from .ledger import LEDGER

        info = LEDGER.observe(label, jitted, args)
        cost = (
            {"flops": info["flops"], "bytes": info["bytes"]} if info else None
        )
    else:
        cost = cost_analysis(jitted, *args)
    if not cost:
        return {}
    sfx = f"_per_{per}" if per else ""
    out = {f"flops{sfx}": cost["flops"], f"bytes{sfx}": cost["bytes"]}
    if variants and variants > 1:
        out["flops_per_variant"] = cost["flops"] / variants
        out["variants"] = variants
    if seconds is not None and seconds > 0:
        out["flops_per_s"] = round(cost["flops"] / seconds, 1)
        m = mfu(cost["flops"], seconds, platform)
        if m is not None:
            out["mfu"] = m
    return out
