"""Kubernetes `resource.Quantity` parsing.

Implements the quantity grammar used throughout the reference's manifests
(requests/limits/allocatable; e.g. "100m", "1.5Gi", "2e3"): a signed decimal
number with an optional binary-SI (Ki..Ei), decimal-SI (n..E) or
decimal-exponent (e/E) suffix. Values are held exactly as
`fractions.Fraction` and exposed as integer base units (ceil, the direction
kubernetes rounds when converting to a coarser scale) and milli-units.

This is a semantic re-implementation of the behavior relied on by the
reference simulator's resource handling (see SURVEY.md §2 #15); no kubernetes
code is copied.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from fractions import Fraction
from functools import lru_cache

_BINARY_SUFFIXES: "dict[str, int]" = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}

_DECIMAL_SUFFIXES: "dict[str, Fraction]" = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<digits>\d+(?:\.\d*)?|\.\d+)"
    r"(?:(?P<exp>[eE][+-]?\d+)|(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|[numkMGTPE]?))$"
)


@dataclass(frozen=True)
class Quantity:
    """An exact resource quantity."""

    value: Fraction
    original: str = field(compare=False)

    @property
    def units(self) -> int:
        """Integer base units, rounded up (kubernetes rounds up on scale loss)."""
        return math.ceil(self.value)

    @property
    def milli(self) -> int:
        """Integer milli-units, rounded up."""
        return math.ceil(self.value * 1000)

    def __int__(self) -> int:
        return self.units

    def __float__(self) -> float:
        return float(self.value)


def parse_quantity(s: "str | int | float") -> Quantity:
    """Parse a kubernetes quantity string (or bare number) exactly.

    String parses are memoized: manifests repeat a handful of distinct
    quantities ("100m", "128Mi", ...) tens of thousands of times in a
    large encode, and `Quantity` is a frozen dataclass over an immutable
    Fraction, so shared instances are safe. ~40% of the 10k-pod encode's
    host time was quantity parsing before the cache."""
    if isinstance(s, (int, float)):
        return Quantity(Fraction(s).limit_denominator(10**9), str(s))
    return _parse_quantity_str(s.strip())


@lru_cache(maxsize=4096)
def _parse_quantity_str(text: str) -> Quantity:
    m = _QUANTITY_RE.match(text)
    if m is None:
        raise ValueError(f"invalid quantity: {text!r}")
    digits = m.group("digits")
    value = Fraction(digits)
    if m.group("exp"):
        exp = int(m.group("exp")[1:])
        value *= Fraction(10) ** exp
    else:
        suffix = m.group("suffix") or ""
        if suffix in _BINARY_SUFFIXES:
            value *= _BINARY_SUFFIXES[suffix]
        else:
            value *= _DECIMAL_SUFFIXES[suffix]
    if m.group("sign") == "-":
        value = -value
    return Quantity(value, text)


def format_quantity(n: int) -> str:
    """Format an integer number of base units canonically (binary SI when even)."""
    if n == 0:
        return "0"
    for suffix, mult in reversed(list(_BINARY_SUFFIXES.items())):
        if n % mult == 0 and abs(n) >= mult:
            return f"{n // mult}{suffix}"
    for suffix in ("E", "P", "T", "G", "M", "k"):
        mult = int(_DECIMAL_SUFFIXES[suffix])
        if n % mult == 0 and abs(n) >= mult:
            return f"{n // mult}{suffix}"
    return str(n)
