"""The serving-grade SLO plane: per-tenant objectives, multi-window
burn-rate alerting, and the alert history ring (docs/observability.md).

The stack records every raw signal production needs — latency
histograms, per-tenant sessions, per-program cost, fleet time-series —
but nothing *judges* them. This module closes the operator loop with
the canonical SRE shape:

  * **Objectives** — a small declarative registry over signals the
    `SchedulingMetrics` observation points already record (no second
    measurement path): pass latency, time-to-reschedule, pending-queue
    age, and the eager-fallback / degraded-pass ratios. Each objective
    compiles into sliding good/bad event windows; defaults can be
    overridden by ``KSS_SLO_OBJECTIVES`` (a strict grammar validated at
    boot) or per session via ``PUT /api/v1/sessions/<id>/slo``.

  * **Burn-rate alerting** — the multi-window evaluation from the SRE
    workbook: an alert condition holds when the error-budget burn rate
    exceeds its threshold over BOTH a fast (~5m) and a slow (~1h)
    window, so a one-off blip (fast only) and a long-ago bad era (slow
    only) both stay quiet. Conditions walk a pending → firing →
    resolved state machine (``KSS_SLO_ALERT_FOR_S`` is the pending
    hold); every transition lands in a bounded process-wide
    `AlertLog` ring (the `SpanRecorder` pattern), is emitted as an
    ``alert.<state>`` telemetry instant, streamed as an SSE ``alert``
    event, and served by ``GET /api/v1/alerts``.

  * **Sim-time awareness** — the plane's clock is
    ``max(wall monotonic, last sim tick)``: the lifecycle engine ticks
    `SchedulingMetrics.slo_tick(sim_t)` as its timeline advances, so a
    chaos run that compresses an hour of simulated time into seconds
    of wall time still walks alerts through their full lifecycle —
    the injected-fault smoke gate (tools/observability_smoke.py)
    demonstrates pending → firing → resolved end-to-end this way.

Off by default (``KSS_SLO``), like every observer in this tree; armed,
an observation is one short lock hold per pass-level event, and
placements are byte-identical with the plane armed or off (the
sampling-invariance contract, pinned in tests/test_slo.py).
"""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass

from . import locking, telemetry
from .envcheck import env_truthy

ENV_VAR = "KSS_SLO"
OBJ_VAR = "KSS_SLO_OBJECTIVES"
FAST_VAR = "KSS_SLO_WINDOW_FAST_S"
SLOW_VAR = "KSS_SLO_WINDOW_SLOW_S"
BURN_FAST_VAR = "KSS_SLO_BURN_FAST"
BURN_SLOW_VAR = "KSS_SLO_BURN_SLOW"
FOR_VAR = "KSS_SLO_ALERT_FOR_S"
CAP_VAR = "KSS_SLO_ALERT_RING_CAP"

DEFAULT_WINDOW_FAST_S = 300.0
DEFAULT_WINDOW_SLOW_S = 3600.0
# the SRE-workbook page-tier pair: the slow window proves budget is
# really burning, the fast window proves it is STILL burning
DEFAULT_BURN_FAST = 14.4
DEFAULT_BURN_SLOW = 6.0
DEFAULT_ALERT_FOR_S = 60.0
DEFAULT_ALERT_RING_CAP = 256

# observation cadence guard: observe-triggered evaluations are
# rate-limited to one per plane-clock second (explicit evaluate() —
# route reads, sim ticks — always runs)
_EVAL_MIN_INTERVAL_S = 1.0


def _lenient_float(raw: str, default: float, minimum: float) -> float:
    """The shared lenient-knob parse (the telemetry ring-cap contract):
    a typo must never disable the plane or blow a bound — strict
    rejection happens at boot via envcheck."""
    try:
        v = float(raw) if raw else default
    except ValueError:
        return default
    return v if v >= minimum else default


def _lenient_int(raw: str, default: int, minimum: int) -> int:
    try:
        v = int(raw) if raw else default
    except ValueError:
        return default
    return v if v >= minimum else default


def enabled() -> bool:
    """True when KSS_SLO arms the plane process-wide (per-session PUT
    overrides work either way)."""
    return env_truthy(os.environ.get(ENV_VAR))


def window_fast_from_env() -> float:
    return _lenient_float(
        os.environ.get(FAST_VAR, ""), DEFAULT_WINDOW_FAST_S, 1.0
    )


def window_slow_from_env() -> float:
    return _lenient_float(
        os.environ.get(SLOW_VAR, ""), DEFAULT_WINDOW_SLOW_S, 1.0
    )


def burn_fast_from_env() -> float:
    return _lenient_float(os.environ.get(BURN_FAST_VAR, ""), DEFAULT_BURN_FAST, 0.0)


def burn_slow_from_env() -> float:
    return _lenient_float(os.environ.get(BURN_SLOW_VAR, ""), DEFAULT_BURN_SLOW, 0.0)


def alert_for_from_env() -> float:
    return _lenient_float(os.environ.get(FOR_VAR, ""), DEFAULT_ALERT_FOR_S, 0.0)


def alert_ring_cap_from_env() -> int:
    return _lenient_int(os.environ.get(CAP_VAR, ""), DEFAULT_ALERT_RING_CAP, 1)


def env_key() -> tuple:
    """The raw env strings the plane is built from — the metrics-side
    cache key (`SchedulingMetrics.slo_plane` rebuilds only when one of
    these changes, the telemetry/fleetstats `active()` pattern)."""
    return tuple(
        os.environ.get(var, "")
        for var in (
            ENV_VAR, OBJ_VAR, FAST_VAR, SLOW_VAR,
            BURN_FAST_VAR, BURN_SLOW_VAR, FOR_VAR,
        )
    )


# -- objectives ----------------------------------------------------------------


@dataclass(frozen=True)
class Objective:
    """One service-level objective over an already-recorded signal.

    `target` is the good-event fraction the SLO promises (error budget
    = 1 - target). `threshold` turns a valued signal (seconds) into a
    good/bad event: good iff value <= threshold; ratio signals
    (eager-fallback, degraded-pass) carry no threshold — their
    observation points declare good/bad directly."""

    name: str
    signal: str
    target: float
    threshold: "float | None" = None
    description: str = ""

    def judge(self, good: "bool | None", value: "float | None") -> bool:
        if self.threshold is not None and value is not None:
            return float(value) <= self.threshold
        return bool(good)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "signal": self.signal,
            "target": self.target,
            "threshold": self.threshold,
            "description": self.description,
        }


# signal name -> what a good event means (the observation points live
# in utils/metrics.py; pendingAge rides the fleet sampler's ages)
SIGNALS = {
    "passLatency": "wall-clock pass latency within threshold seconds",
    "timeToReschedule": "an evicted pod re-bound within threshold "
    "SIMULATED seconds",
    "pendingAge": "p90 pending-queue age within threshold seconds "
    "(needs KSS_FLEET_STATS sampling)",
    "eagerFallback": "a pass NOT served by the un-jitted eager rung",
    "degradedPass": "a pass served by a compiled engine (not degraded)",
}

_DEFAULTS = (
    Objective(
        "passLatency", "passLatency", 0.99, 1.0,
        "99% of scheduling passes complete within 1s",
    ),
    Objective(
        "timeToReschedule", "timeToReschedule", 0.95, 60.0,
        "95% of evicted pods re-bind within 60 simulated seconds",
    ),
    Objective(
        "pendingAge", "pendingAge", 0.90, 300.0,
        "90% of sampled passes keep p90 pending age under 300s",
    ),
    Objective(
        "eagerFallback", "eagerFallback", 0.99, None,
        "99% of passes are served jitted (not by the eager rung)",
    ),
    Objective(
        "degradedPass", "degradedPass", 0.99, None,
        "99% of passes are served by a compiled engine",
    ),
)


def default_objectives() -> "dict[str, Objective]":
    return {o.name: o for o in _DEFAULTS}


def parse_objectives(raw: str) -> "dict[str, Objective]":
    """The KSS_SLO_OBJECTIVES grammar, strictly parsed (the envcheck
    validator runs this, so a typo is a boot error, not a silently
    ignored override). Semicolon-separated entries over the default
    set:

        passLatency:target=0.999,threshold=0.5;pendingAge:off

    Each entry names a known signal and either disables it (``off``)
    or overrides ``target`` (a fraction in (0, 1)) and/or
    ``threshold`` (seconds, > 0)."""
    out = default_objectives()
    if not raw or not raw.strip():
        return out
    for entry in raw.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, params = entry.partition(":")
        name = name.strip()
        if name not in SIGNALS:
            raise ValueError(
                f"SLO objective {name!r}: unknown signal "
                f"(known: {', '.join(sorted(SIGNALS))})"
            )
        if not sep or not params.strip():
            raise ValueError(
                f"SLO objective {name!r}: expected "
                f"'{name}:off' or '{name}:target=...[,threshold=...]'"
            )
        if params.strip() == "off":
            out.pop(name, None)
            continue
        base = default_objectives()[name]
        target, threshold = base.target, base.threshold
        for kv in params.split(","):
            key, sep2, value = kv.partition("=")
            key = key.strip()
            if not sep2:
                raise ValueError(
                    f"SLO objective {name!r}: malformed parameter {kv!r} "
                    f"(expected key=value)"
                )
            try:
                v = float(value)
            except ValueError:
                raise ValueError(
                    f"SLO objective {name!r}: {key} {value!r} is not a "
                    f"number"
                ) from None
            if key == "target":
                if not 0.0 < v < 1.0:
                    raise ValueError(
                        f"SLO objective {name!r}: target {v} outside (0, 1)"
                    )
                target = v
            elif key == "threshold":
                if v <= 0:
                    raise ValueError(
                        f"SLO objective {name!r}: threshold {v} must be > 0"
                    )
                threshold = v
            else:
                raise ValueError(
                    f"SLO objective {name!r}: unknown parameter {key!r} "
                    f"(target, threshold)"
                )
        out[name] = Objective(
            name, base.signal, target, threshold, base.description
        )
    return out


def objectives_from_env() -> "dict[str, Objective]":
    """The effective objective set: defaults overridden by
    KSS_SLO_OBJECTIVES; a malformed value (already rejected at boot by
    envcheck) falls back to the defaults at this lenient runtime
    layer."""
    raw = os.environ.get(OBJ_VAR, "")
    try:
        return parse_objectives(raw)
    except ValueError:
        return default_objectives()


def objectives_from_spec(spec) -> "dict[str, Objective]":
    """Objectives from a PUT /slo JSON body: a list of
    ``{"signal", "target", "threshold"}`` mappings (or a
    name-keyed mapping of the same), layered over the defaults.
    Raises ValueError with a client-addressable message (400)."""
    out = default_objectives()
    if spec is None:
        return out
    if isinstance(spec, dict):
        spec = [
            {"signal": name, **(params or {})}
            for name, params in spec.items()
        ]
    if not isinstance(spec, list):
        raise ValueError("objectives must be a list or a mapping")
    for item in spec:
        if not isinstance(item, dict) or "signal" not in item:
            raise ValueError(
                f"objective {item!r} must be a mapping with a 'signal'"
            )
        name = str(item["signal"])
        if name not in SIGNALS:
            raise ValueError(
                f"unknown SLO signal {name!r} "
                f"(known: {', '.join(sorted(SIGNALS))})"
            )
        if item.get("enabled") is False or item.get("off"):
            out.pop(name, None)
            continue
        base = default_objectives()[name]
        target = float(item.get("target", base.target))
        if not 0.0 < target < 1.0:
            raise ValueError(f"objective {name!r}: target outside (0, 1)")
        threshold = item.get("threshold", base.threshold)
        if threshold is not None:
            threshold = float(threshold)
            if threshold <= 0:
                raise ValueError(f"objective {name!r}: threshold must be > 0")
        out[name] = Objective(
            name, base.signal, target, threshold, base.description
        )
    return out


def plane_from_put_spec(body, session_id: "str | None") -> "SloPlane | None":
    """The ONE parse of the PUT /slo body shape — shared by the HTTP
    route and session-create's ``"slo"`` key so the two surfaces can't
    drift: objectives layered over the defaults plus optional
    window/burn/hold overrides, built into an explicit plane. Returns
    None for ``{"enabled": false}`` (the caller disarms). Raises
    ValueError with a client-addressable message (400)."""
    if not isinstance(body, dict):
        raise ValueError("SLO spec must be a mapping")
    if body.get("enabled") is False:
        return None
    objectives = objectives_from_spec(body.get("objectives"))
    kwargs: dict = {}
    for key, name, minimum in (
        ("windowFastSeconds", "window_fast_s", 1.0),
        ("windowSlowSeconds", "window_slow_s", 1.0),
        ("burnFastThreshold", "burn_fast", 0.0),
        ("burnSlowThreshold", "burn_slow", 0.0),
        ("forSeconds", "for_s", 0.0),
    ):
        if key in body:
            try:
                v = float(body[key])
            except (TypeError, ValueError):
                raise ValueError(f"{key} must be a number") from None
            if v < minimum:
                raise ValueError(f"{key} must be >= {minimum}, got {v}")
            kwargs[name] = v
    return SloPlane(
        session_id=session_id, objectives=objectives, explicit=True, **kwargs
    )


# -- the alert history ring ----------------------------------------------------


@locking.guard_inferred
class AlertLog:
    """A bounded process-wide ring of alert transitions + live
    subscribers — the `SpanRecorder` shape: `emit` holds the lock only
    to place the event, stamp its sequence, and advance the cumulative
    counters; subscriber callbacks (the SSE route's ``alert`` feed) run
    OUTSIDE the lock. One ring serves every session's plane — each
    event carries its session id, exactly like spans."""

    def __init__(self, capacity: "int | None" = None):
        cap = alert_ring_cap_from_env() if capacity is None else int(capacity)
        if cap < 1:
            raise ValueError(f"ring capacity must be >= 1, got {cap}")
        self.capacity = cap
        self._lock = locking.make_lock("slo.alertlog")
        self._ring: "list[dict | None]" = [None] * cap
        self._seq = 0
        self._subs: list = []
        self._transitions = 0
        self._fired = 0

    def emit(self, ev: dict) -> None:
        with self._lock:
            ev = dict(ev)
            ev["seq"] = self._seq
            self._ring[self._seq % self.capacity] = ev
            self._seq += 1
            self._transitions += 1
            if ev.get("state") == "firing":
                self._fired += 1
            subs = tuple(self._subs) if self._subs else ()
        for fn in subs:
            try:
                fn(ev)
            except Exception:  # noqa: BLE001 — a dead subscriber never breaks a pass
                pass

    @property
    def emitted(self) -> int:
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self._seq - self.capacity)

    def __len__(self) -> int:
        with self._lock:
            return min(self._seq, self.capacity)

    def snapshot(self) -> "list[dict]":
        with self._lock:
            n = self._seq
            if n <= self.capacity:
                return list(self._ring[:n])
            i = n % self.capacity
            return self._ring[i:] + self._ring[:i]

    def counters(self) -> dict:
        with self._lock:
            return {"transitions": self._transitions, "fired": self._fired}

    def subscribe(self, fn) -> None:
        with self._lock:
            self._subs.append(fn)

    def unsubscribe(self, fn) -> None:
        with self._lock:
            try:
                self._subs.remove(fn)
            except ValueError:
                pass


_log_lock = locking.make_lock("slo.logconfig")
_log: "AlertLog | None" = None


def alert_log() -> AlertLog:
    """The process-wide alert history ring, built lazily (capacity from
    KSS_SLO_ALERT_RING_CAP at first use)."""
    global _log
    log = _log
    if log is not None:
        return log
    with _log_lock:
        if _log is None:
            _log = AlertLog(alert_ring_cap_from_env())
        return _log


def reset_alert_log(capacity: "int | None" = None) -> AlertLog:
    """Swap in a fresh ring (tests, the smoke tooling) and return it."""
    global _log
    with _log_lock:
        _log = AlertLog(capacity)
        return _log


# -- the per-tenant plane ------------------------------------------------------

_ALERT_STATE_VALUES = {"inactive": 0, "pending": 1, "firing": 2}


@locking.guard_inferred
class SloPlane:
    """One tenant's SLO state: objectives, sliding good/bad event
    windows, and the per-objective alert state machine. Owned by the
    session's `SchedulingMetrics` (the observation funnel forwards
    into `observe`); all mutable state lives under one short-hold
    lock, and transition side effects (ring emit, telemetry instants)
    run outside it."""

    def __init__(
        self,
        session_id: "str | None" = None,
        objectives: "dict[str, Objective] | None" = None,
        *,
        window_fast_s: "float | None" = None,
        window_slow_s: "float | None" = None,
        burn_fast: "float | None" = None,
        burn_slow: "float | None" = None,
        for_s: "float | None" = None,
        explicit: bool = False,
    ):
        self.session_id = session_id
        self.window_fast_s = float(
            window_fast_from_env() if window_fast_s is None else window_fast_s
        )
        self.window_slow_s = float(
            window_slow_from_env() if window_slow_s is None else window_slow_s
        )
        if self.window_slow_s < self.window_fast_s:
            self.window_slow_s = self.window_fast_s
        self.burn_fast = float(
            burn_fast_from_env() if burn_fast is None else burn_fast
        )
        self.burn_slow = float(
            burn_slow_from_env() if burn_slow is None else burn_slow
        )
        self.for_s = float(alert_for_from_env() if for_s is None else for_s)
        # a PUT-override plane: survives checkpoints as configuration,
        # not just window state (docs/observability.md)
        self.explicit = bool(explicit)
        self._bucket_s = max(1.0, self.window_fast_s / 30.0)
        self._lock = locking.make_lock("slo.plane")
        objs = (
            dict(objectives) if objectives is not None else objectives_from_env()
        )
        self._objectives: "dict[str, Objective]" = objs
        # per objective: deque of [bucket_start, good, bad], oldest first
        self._windows: "dict[str, deque]" = {n: deque() for n in objs}
        self._totals: "dict[str, list]" = {n: [0, 0] for n in objs}
        self._alerts: "dict[str, dict]" = {
            n: {"state": "inactive", "since": None, "firedAt": None}
            for n in objs
        }
        # sim-time clock: once ticked, now() = max(wall, base + sim_t)
        self._sim_base: "float | None" = None
        self._sim_now: "float | None" = None
        self._last_eval: "float | None" = None
        self._fired = 0

    # -- clock ---------------------------------------------------------------

    def _now_locked(self) -> float:
        t = time.monotonic()
        sim = self._sim_now
        return sim if sim is not None and sim > t else t

    def tick_sim(self, sim_t: float) -> None:
        """Advance the plane's clock to simulated time `sim_t` (the
        lifecycle engine's per-batch tick via
        `SchedulingMetrics.slo_tick`): windows slide and alerts
        resolve on the run's own timeline, so a compressed chaos run
        walks the full pending → firing → resolved lifecycle."""
        with self._lock:
            if self._sim_base is None:
                self._sim_base = time.monotonic()
            cand = self._sim_base + float(sim_t)
            if self._sim_now is None or cand > self._sim_now:
                self._sim_now = cand
        self.evaluate()

    # -- observation ---------------------------------------------------------

    def observe(
        self,
        signal: str,
        value: "float | None" = None,
        good: "bool | None" = None,
        count: int = 1,
    ) -> None:
        """One signal observation, fanned into every objective watching
        it. `value` signals judge against their threshold; ratio
        signals pass `good` directly. Forwarded by the
        `SchedulingMetrics` observation points — the ONE measurement
        path."""
        due = False
        with self._lock:
            hit = False
            for name, obj in self._objectives.items():
                if obj.signal != signal:
                    continue
                self._push_locked(name, obj.judge(good, value), count)
                hit = True
            if hit:
                now = self._now_locked()
                due = (
                    self._last_eval is None
                    or now - self._last_eval >= _EVAL_MIN_INTERVAL_S
                )
        if due:
            self.evaluate()

    def _push_locked(self, name: str, ok: bool, count: int) -> None:
        now = self._now_locked()
        b0 = now - (now % self._bucket_s)
        dq = self._windows[name]
        if dq and dq[-1][0] == b0:
            dq[-1][1 if ok else 2] += count
        else:
            dq.append([b0, count if ok else 0, 0 if ok else count])
        horizon = now - self.window_slow_s - self._bucket_s
        while dq and dq[0][0] < horizon:
            dq.popleft()
        self._totals[name][0 if ok else 1] += count

    def _window_counts_locked(
        self, name: str, now: float, window_s: float
    ) -> "tuple[int, int]":
        lo = now - window_s
        good = bad = 0
        for b0, g, b in self._windows[name]:
            if b0 + self._bucket_s > lo:
                good += g
                bad += b
        return good, bad

    # -- evaluation / the alert state machine --------------------------------

    def _burns_locked(self, name: str, obj: Objective, now: float):
        budget = max(1e-9, 1.0 - obj.target)
        fg, fb = self._window_counts_locked(name, now, self.window_fast_s)
        sg, sb = self._window_counts_locked(name, now, self.window_slow_s)
        bf = (fb / (fg + fb)) / budget if (fg + fb) else 0.0
        bs = (sb / (sg + sb)) / budget if (sg + sb) else 0.0
        return (fg, fb, bf), (sg, sb, bs)

    def evaluate(self) -> "list[dict]":
        """Walk every objective's burn rates and state machine; emit
        each transition to the alert ring + a telemetry instant
        (outside the lock). Called on sim ticks, route reads, the
        Prometheus render, and (rate-limited) observations."""
        transitions: list[dict] = []
        with self._lock:
            now = self._now_locked()
            self._last_eval = now
            session = self.session_id or "default"
            for name, obj in self._objectives.items():
                (fg, fb, bf), (sg, sb, bs) = self._burns_locked(name, obj, now)
                cond = (
                    fb > 0 and bf >= self.burn_fast and bs >= self.burn_slow
                )
                st = self._alerts[name]
                prev = st["state"]
                new = prev
                if cond:
                    if prev == "inactive":
                        new = "pending"
                        st.update(state="pending", since=now, firedAt=None)
                    elif (
                        prev == "pending" and now - st["since"] >= self.for_s
                    ):
                        new = "firing"
                        st.update(state="firing", firedAt=now)
                        self._fired += 1
                elif prev in ("pending", "firing"):
                    new = "inactive"
                    st.update(state="inactive", since=None, firedAt=None)
                if new == prev:
                    continue
                transitions.append(
                    {
                        "objective": name,
                        "signal": obj.signal,
                        "session": session,
                        # the wire states: inactive publishes as
                        # "resolved" — the lifecycle's terminal name
                        "state": "resolved" if new == "inactive" else new,
                        "previous": prev,
                        "fired": prev == "firing",
                        "wallTime": round(time.time(), 3),
                        "sloTime": round(now, 6),
                        "target": obj.target,
                        "threshold": obj.threshold,
                        "burnFast": round(bf, 4),
                        "burnSlow": round(bs, 4),
                        "windowFast": {"good": fg, "bad": fb},
                        "windowSlow": {"good": sg, "bad": sb},
                    }
                )
        log = alert_log()
        for ev in transitions:
            log.emit(ev)
            telemetry.instant(
                f"alert.{ev['state']}",
                objective=ev["objective"],
                session=ev["session"],
                burnFast=ev["burnFast"],
                burnSlow=ev["burnSlow"],
            )
        return transitions

    # -- reading -------------------------------------------------------------

    def status(self) -> dict:
        """The full per-objective document (GET /slo, GET /alerts):
        windows, burn rates, compliance, and alert states. Evaluate
        first for a current view."""
        self.evaluate()
        with self._lock:
            now = self._now_locked()
            objectives = {}
            for name, obj in self._objectives.items():
                (fg, fb, bf), (sg, sb, bs) = self._burns_locked(name, obj, now)
                st = self._alerts[name]
                objectives[name] = {
                    "signal": obj.signal,
                    "target": obj.target,
                    "threshold": obj.threshold,
                    "description": obj.description,
                    "windows": {
                        "fast": {
                            "seconds": self.window_fast_s,
                            "good": fg,
                            "bad": fb,
                            "burnRate": round(bf, 4),
                        },
                        "slow": {
                            "seconds": self.window_slow_s,
                            "good": sg,
                            "bad": sb,
                            "burnRate": round(bs, 4),
                        },
                    },
                    "compliance": round(sg / (sg + sb), 6) if (sg + sb) else 1.0,
                    "events": {
                        "good": self._totals[name][0],
                        "bad": self._totals[name][1],
                    },
                    "alert": {
                        "state": st["state"],
                        "sinceSeconds": round(now - st["since"], 3)
                        if st["since"] is not None
                        else None,
                    },
                }
            return {
                "enabled": True,
                "session": self.session_id or "default",
                "explicit": self.explicit,
                "windowFastSeconds": self.window_fast_s,
                "windowSlowSeconds": self.window_slow_s,
                "burnFastThreshold": self.burn_fast,
                "burnSlowThreshold": self.burn_slow,
                "forSeconds": self.for_s,
                "alertsFired": self._fired,
                "objectives": objectives,
            }

    def summary(self) -> dict:
        """The compact block the metrics snapshot embeds (schema v4):
        per-objective compliance + alert state, and the fired count."""
        with self._lock:
            now = self._now_locked()
            objectives = {}
            for name, obj in self._objectives.items():
                _fast, (sg, sb, bs) = self._burns_locked(name, obj, now)
                objectives[name] = {
                    "target": obj.target,
                    "compliance": round(sg / (sg + sb), 6) if (sg + sb) else 1.0,
                    "burnSlow": round(bs, 4),
                    "alertState": self._alerts[name]["state"],
                }
            return {
                "enabled": True,
                "alertsFired": self._fired,
                "objectives": objectives,
            }

    def headline(self) -> dict:
        """The bench --lifecycle-probe block: per-objective compliance
        + alerts fired (hoisted into the campaign headline as "slo")."""
        summary = self.summary()
        return {
            "objectives": {
                name: o["compliance"]
                for name, o in summary["objectives"].items()
            },
            "alertsFired": summary["alertsFired"],
            "firing": sorted(
                name
                for name, o in summary["objectives"].items()
                if o["alertState"] == "firing"
            ),
        }

    def active_alerts(self) -> "list[dict]":
        with self._lock:
            now = self._now_locked()
            session = self.session_id or "default"
            out = []
            for name, st in self._alerts.items():
                if st["state"] == "inactive":
                    continue
                out.append(
                    {
                        "objective": name,
                        "session": session,
                        "state": st["state"],
                        "sinceSeconds": round(now - st["since"], 3)
                        if st["since"] is not None
                        else None,
                    }
                )
            return out

    # -- checkpoint state (SchedulingMetrics.state_dict rides this) ----------

    def state_dict(self) -> dict:
        """Window + alert state as one JSON-able dict: bucket times and
        alert 'since' stamps serialize as AGES (seconds before now), so
        a resumed process reconstructs them against its own clock —
        checkpoint/drain/resume continuity (docs/resilience.md)."""
        with self._lock:
            now = self._now_locked()
            return {
                "config": {
                    "sessionId": self.session_id,
                    "explicit": self.explicit,
                    "windowFastSeconds": self.window_fast_s,
                    "windowSlowSeconds": self.window_slow_s,
                    "burnFastThreshold": self.burn_fast,
                    "burnSlowThreshold": self.burn_slow,
                    "forSeconds": self.for_s,
                    "objectives": [
                        o.to_dict() for o in self._objectives.values()
                    ],
                },
                "windows": {
                    name: [
                        [round(now - b0, 6), g, b] for b0, g, b in dq
                    ]
                    for name, dq in self._windows.items()
                },
                "totals": {n: list(v) for n, v in self._totals.items()},
                "alerts": {
                    name: {
                        "state": st["state"],
                        "sinceAge": round(now - st["since"], 6)
                        if st["since"] is not None
                        else None,
                    }
                    for name, st in self._alerts.items()
                },
                "fired": self._fired,
            }

    @classmethod
    def from_state(cls, state: dict) -> "SloPlane":
        cfg = state.get("config") or {}
        objectives = {}
        for od in cfg.get("objectives") or []:
            name = od.get("name") or od.get("signal")
            if name not in SIGNALS:
                continue
            objectives[name] = Objective(
                name,
                od.get("signal", name),
                float(od.get("target", 0.99)),
                od.get("threshold"),
                od.get("description", ""),
            )
        plane = cls(
            session_id=cfg.get("sessionId"),
            objectives=objectives or None,
            window_fast_s=cfg.get("windowFastSeconds"),
            window_slow_s=cfg.get("windowSlowSeconds"),
            burn_fast=cfg.get("burnFastThreshold"),
            burn_slow=cfg.get("burnSlowThreshold"),
            for_s=cfg.get("forSeconds"),
            explicit=bool(cfg.get("explicit")),
        )
        plane.load_state(state)
        return plane

    def load_state(self, state: dict) -> None:
        """Restore `state_dict` output into this plane (unknown
        objectives are ignored so old checkpoints stay loadable)."""
        with self._lock:
            now = self._now_locked()
            for name, rows in (state.get("windows") or {}).items():
                if name not in self._windows or not isinstance(rows, list):
                    continue
                dq = self._windows[name]
                dq.clear()
                for row in rows:
                    try:
                        age, g, b = row
                    except (TypeError, ValueError):
                        continue
                    b0 = now - float(age)
                    dq.append([b0 - (b0 % self._bucket_s), int(g), int(b)])
            for name, pair in (state.get("totals") or {}).items():
                if name in self._totals and isinstance(pair, list):
                    self._totals[name] = [int(pair[0]), int(pair[1])]
            for name, st in (state.get("alerts") or {}).items():
                if name not in self._alerts or not isinstance(st, dict):
                    continue
                alert_state = st.get("state", "inactive")
                if alert_state not in _ALERT_STATE_VALUES:
                    continue
                since = st.get("sinceAge")
                self._alerts[name] = {
                    "state": alert_state,
                    "since": now - float(since) if since is not None else None,
                    "firedAt": None,
                }
            self._fired = int(state.get("fired", 0))


# -- Prometheus exposition -----------------------------------------------------


def render_prometheus_planes(
    planes: "list[tuple[str, SloPlane | None]]",
) -> str:
    """The ``kss_slo_*`` / ``kss_alert_*`` families for the serving
    layer's scrape (server/httpserver.py): one labeled series per
    (objective, session) from each live plane, plus the process-wide
    alert-ring counters. Planes are evaluated first so alert states
    are current at scrape time. Empty-plane entries contribute
    nothing; the global counters always render."""
    from .metrics import _fmt_value

    rows: "list[tuple[str, str, dict]]" = []  # (session, name, status row)
    for session_id, plane in planes:
        if plane is None:
            continue
        status = plane.status()
        for name, obj in status["objectives"].items():
            rows.append((session_id or "default", name, obj))
    lines: list[str] = []

    def family(name: str, mtype: str, help_text: str, value_of) -> None:
        if not rows:
            return
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        for session, objective, obj in rows:
            lines.append(
                f'{name}{{objective="{objective}",session="{session}"}} '
                f"{_fmt_value(value_of(obj))}"
            )

    family(
        "kss_slo_objective_target",
        "gauge",
        "The objective's promised good-event fraction.",
        lambda o: o["target"],
    )
    family(
        "kss_slo_compliance",
        "gauge",
        "Good-event fraction over the slow window (1.0 with no events).",
        lambda o: o["compliance"],
    )
    family(
        "kss_slo_burn_rate_fast",
        "gauge",
        "Error-budget burn rate over the fast window.",
        lambda o: o["windows"]["fast"]["burnRate"],
    )
    family(
        "kss_slo_burn_rate_slow",
        "gauge",
        "Error-budget burn rate over the slow window.",
        lambda o: o["windows"]["slow"]["burnRate"],
    )
    family(
        "kss_alert_state",
        "gauge",
        "Alert state machine: 0 inactive, 1 pending, 2 firing.",
        lambda o: _ALERT_STATE_VALUES.get(o["alert"]["state"], 0),
    )
    if rows:
        name = "kss_slo_events_total"
        lines.append(
            f"# HELP {name} Good/bad events observed per objective."
        )
        lines.append(f"# TYPE {name} counter")
        for session, objective, obj in rows:
            for result in ("good", "bad"):
                lines.append(
                    f'{name}{{objective="{objective}",result="{result}",'
                    f'session="{session}"}} '
                    f"{_fmt_value(obj['events'][result])}"
                )
    log = alert_log()
    counters = log.counters()
    for name, help_text, value in (
        (
            "kss_alert_transitions_total",
            "Alert state transitions recorded in the history ring.",
            counters["transitions"],
        ),
        (
            "kss_alerts_fired_total",
            "Alerts that reached the firing state.",
            counters["fired"],
        ),
    ):
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {_fmt_value(value)}")
    return "\n".join(lines) + "\n"
