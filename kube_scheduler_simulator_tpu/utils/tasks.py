"""Host-side task utilities at the framework's I/O boundaries.

The reference's equivalents: a GOMAXPROCS-bounded errgroup used for bulk
CRUD during export/import (simulator/util/semaphored_errgroup.go:17-40)
and an exponential-backoff retry helper (simulator/util/retry.go:8-26,
100ms base, factor 3, 6 steps). The TPU framework is single-process and
mostly pure, so these apply only at real I/O boundaries — `retry` guards
the replicate-existing-cluster HTTP fetch (server/replicate.py),
`bounded_map` fans out host-bound batch jobs (scenario/batch.py
run_batch(max_workers=...)) — never inside compiled programs.
"""

from __future__ import annotations

import os
import time
from concurrent.futures import ThreadPoolExecutor


class RetryError(Exception):
    """All attempts failed; `.last` is the final exception."""

    def __init__(self, attempts: int, last: BaseException):
        super().__init__(f"failed after {attempts} attempts: {last}")
        self.attempts = attempts
        self.last = last


def retry(
    fn,
    *,
    steps: int = 6,
    base_delay: float = 0.1,
    factor: float = 3.0,
    retryable=lambda e: True,
    sleep=time.sleep,
):
    """Call `fn()` with exponential backoff (reference retry.go defaults:
    100ms x 3^n, 6 steps). Raises RetryError when every attempt fails or
    immediately re-raises a non-retryable exception."""
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    delay = base_delay
    last: "BaseException | None" = None
    for attempt in range(steps):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — boundary helper
            if not retryable(e):
                raise
            last = e
            if attempt < steps - 1:
                sleep(delay)
                delay *= factor
    raise RetryError(steps, last)


def bounded_map(fn, items, *, max_workers: "int | None" = None) -> list:
    """Run `fn` over `items` on a bounded thread pool, preserving order —
    the semaphored-errgroup analogue. The first exception is raised after
    all tasks finish (errgroup semantics); results of failed items are
    not returned."""
    if not items:
        return []
    workers = max_workers or min(len(items), os.cpu_count() or 4)
    with ThreadPoolExecutor(max_workers=workers) as ex:
        futures = [ex.submit(fn, it) for it in items]
        results, first_err = [], None
        for f in futures:
            try:
                results.append(f.result())
            except Exception as e:  # noqa: BLE001
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err
        return results
