"""The unified telemetry plane: a span flight recorder over every
concurrent machinery in the serving stack.

Three machineries interleave on the serving path — the async
double-buffered pipeline (dispatch→resolve passes), the CompileBroker's
background workers (speculative builds, watchdog-abandoned compiles),
and the lifecycle engine's discrete-event loop — and `/api/v1/metrics`
only ever showed their *aggregate* counters. This module records the
interleavings themselves: structured spans in a lock-cheap bounded ring
buffer (a flight recorder: always the most recent window, never
unbounded growth), exported as Chrome trace-event JSON that Perfetto /
`chrome://tracing` load directly, and streamed live over SSE
(`GET /api/v1/events`).

Span model
----------

  * `span(name, **attrs)` — context manager; emits a `B` (begin) event
    at entry and a matching `E` (end) at exit on the calling thread's
    track. Nesting follows the `with` structure, so B/E are balanced
    per thread by construction (test-pinned).
  * `complete(name, start_s, end_s, *, tid=...)` — one `X` (complete)
    event over an explicit interval, usable for windows that do NOT
    nest on a host thread: the async pipeline's device-execute window
    (dispatch→resolve) lands on the synthetic `DEVICE_TID` track, where
    its overlap with host-side event application is *visible* as
    overlapping tracks in Perfetto and *assertable* from the exported
    intervals (tests/test_async_pipeline.py).
  * `instant(name, **attrs)` — a point event (`i`), used for injected
    faults (utils/faultinject.py) and sim-time correlation marks.

Causality: every span/instant carries the current **pass id** — a
monotonic per-service counter threaded through `SchedulerService` via
the thread-local `pass_context`. Background work triggered *by* a pass
(the broker's speculative builds, eager fallbacks) re-enters the arming
pass's context on the worker thread, so a speculative compile's spans
name the pass that armed it.

Cost model: tracing is **off by default** and near-zero-cost when off —
`span()` returns a shared no-op context manager after one env probe
(`KSS_TRACE`, cached on the raw string exactly like
utils/faultinject.py), no allocation, no lock on the ring.
`tools/perf_smoke.py` gates the disabled-path overhead. The ring
capacity is `KSS_TRACE_RING_CAP` events (default 65536); past it the
oldest events are overwritten — the flight-recorder contract.

Timestamps are `time.perf_counter()` microseconds: monotonic, shared
across threads, the unit Chrome trace events use (`ts`/`dur`).

Distributed tracing (docs/observability.md "Distributed tracing"): the
fleet router mints a W3C-traceparent-style context per inbound request
and stamps it on every proxied hop; workers adopt the header at the
HTTP chokepoint via `trace_context`, so every span a request causes —
across processes — carries one ``args["trace"]`` id, and
`merged_chrome_trace` joins per-process exports into one Perfetto
document with per-track monotonic-clock offsets. Propagation rides the
same arming as the recorder (`KSS_TRACE_PROPAGATE`, default on when
KSS_TRACE is truthy): with tracing off nothing is minted, parsed, or
stamped.
"""

from __future__ import annotations

import json
import os
import secrets
import threading
import time

from . import locking
# the shared boolean vocabulary (envcheck.TRUTHY): KSS_TRACE honors
# every spelling startup validation accepts — a 'validated' tracing run
# must never silently record nothing
from .envcheck import TRUTHY as _TRUE

ENV_VAR = "KSS_TRACE"
CAP_VAR = "KSS_TRACE_RING_CAP"
PROPAGATE_VAR = "KSS_TRACE_PROPAGATE"
DEFAULT_RING_CAP = 65536

# the synthetic track for non-thread-shaped intervals (the async
# pipeline's in-flight device-execute windows). Python thread idents are
# CPython object addresses and never 0, so 0 is collision-free.
DEVICE_TID = 0

_PID = os.getpid()


def _now_us() -> float:
    return time.perf_counter() * 1e6


def ring_capacity_from_env() -> int:
    """Ring capacity from KSS_TRACE_RING_CAP; malformed or non-positive
    values fall back to the default — a typo must never disable the
    flight recorder or blow its bound."""
    raw = os.environ.get(CAP_VAR, "")
    try:
        cap = int(raw) if raw else DEFAULT_RING_CAP
    except ValueError:
        return DEFAULT_RING_CAP
    return cap if cap >= 1 else DEFAULT_RING_CAP


@locking.guard_inferred
class SpanRecorder:
    """A bounded ring buffer of Chrome-trace events + live subscribers.

    `emit` is the hot path: one short lock hold to place the event and
    advance the sequence (the bound holds under concurrent writers —
    test-pinned), then subscriber callbacks OUTSIDE the lock. Snapshots
    return the retained window oldest-first."""

    def __init__(self, capacity: "int | None" = None):
        cap = ring_capacity_from_env() if capacity is None else int(capacity)
        if cap < 1:
            raise ValueError(f"ring capacity must be >= 1, got {cap}")
        self.capacity = cap
        self._lock = locking.make_lock("telemetry.ring")
        self._ring: "list[dict | None]" = [None] * cap
        self._seq = 0  # monotonic count of events ever emitted
        self._subs: list = []

    # -- writing ------------------------------------------------------------

    def emit(self, ev: dict) -> None:
        with self._lock:
            self._ring[self._seq % self.capacity] = ev
            self._seq += 1
            subs = tuple(self._subs) if self._subs else ()
        for fn in subs:
            try:
                fn(ev)
            except Exception:  # noqa: BLE001 — a dead subscriber never breaks a pass
                pass

    # -- reading ------------------------------------------------------------

    @property
    def emitted(self) -> int:
        """Events ever emitted (>= len(self): the ring drops the oldest)."""
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self._seq - self.capacity)

    def __len__(self) -> int:
        with self._lock:
            return min(self._seq, self.capacity)

    def snapshot(self) -> list[dict]:
        """The retained events, oldest first."""
        with self._lock:
            n = self._seq
            if n <= self.capacity:
                return list(self._ring[:n])
            i = n % self.capacity
            return self._ring[i:] + self._ring[:i]

    # -- live streaming (the SSE route's feed) ------------------------------

    def subscribe(self, fn) -> None:
        with self._lock:
            self._subs.append(fn)

    def unsubscribe(self, fn) -> None:
        with self._lock:
            try:
                self._subs.remove(fn)
            except ValueError:
                pass


# -- the process-global active recorder --------------------------------------

_lock = locking.make_lock("telemetry.config")
# (KSS_TRACE, KSS_TRACE_RING_CAP) raw strings -> recorder parsed from
# them; an explicit `activate` overrides the environment (tests, the
# lifecycle CLI's --perfetto-out) until `deactivate`. Both globals are
# read WITHOUT the lock on the hot path (single-reference loads are
# atomic under the GIL; each holds one immutable tuple swapped whole),
# so every span site across request threads doesn't serialize on one
# process-global mutex just to learn tracing is off.
_cached: "tuple[tuple[str, str], SpanRecorder | None] | None" = None
_override_state: "tuple[bool, SpanRecorder | None]" = (False, None)


def active() -> "SpanRecorder | None":
    """The active recorder, or None (the default: tracing off). Reads
    KSS_TRACE / KSS_TRACE_RING_CAP each call but re-builds the recorder
    only when they change — the disabled path is two dict probes and a
    tuple compare, no lock, cheap enough for every span site."""
    global _cached
    overridden, override = _override_state
    if overridden:
        return override
    key = (os.environ.get(ENV_VAR, ""), os.environ.get(CAP_VAR, ""))
    cached = _cached
    if cached is not None and cached[0] == key:
        return cached[1]
    with _lock:
        overridden, override = _override_state
        if overridden:
            return override
        cached = _cached
        if cached is not None and cached[0] == key:
            return cached[1]
        rec = (
            SpanRecorder(ring_capacity_from_env())
            if key[0].strip().lower() in _TRUE
            else None
        )
        _cached = (key, rec)
        return rec


def enabled() -> bool:
    return active() is not None


def activate(recorder: "SpanRecorder | None") -> None:
    """Install `recorder` as the active one regardless of the
    environment (None = tracing explicitly off). Until `deactivate`,
    the env vars are not consulted."""
    global _override_state
    with _lock:
        _override_state = (True, recorder)


def deactivate() -> None:
    """Drop any `activate` override; the environment rules again."""
    global _override_state
    with _lock:
        _override_state = (False, None)


# -- pass-id / session causality ----------------------------------------------

_ctx = threading.local()


def current_pass_id() -> "int | None":
    """The pass id of the innermost `pass_context` on this thread."""
    return getattr(_ctx, "pass_id", None)


def current_session_id() -> "str | None":
    """The session id of the innermost `session_context` on this thread
    (the multi-tenant session plane, docs/sessions.md)."""
    return getattr(_ctx, "session_id", None)


class session_context:
    """Thread-local session causality: spans/instants emitted inside
    carry ``args["session"] = session_id`` — the label the SSE route
    filters on and the Prometheus exposition keys by. Re-entered on
    broker worker threads for work a session's pass armed, exactly like
    `pass_context`."""

    __slots__ = ("_session_id", "_prev")

    def __init__(self, session_id: "str | None"):
        self._session_id = session_id
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_ctx, "session_id", None)
        _ctx.session_id = self._session_id
        return self

    def __exit__(self, *exc):
        _ctx.session_id = self._prev
        return False


class pass_context:
    """Thread-local causal context: spans/instants emitted inside carry
    `args["pass"] = pass_id`. Re-entered on worker threads for work a
    pass *armed* (speculative compiles), so background spans name their
    triggering pass. A plain class (not @contextmanager) keeps the
    disabled-tracing cost to two attribute writes."""

    __slots__ = ("_pass_id", "_prev")

    def __init__(self, pass_id: "int | None"):
        self._pass_id = pass_id
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_ctx, "pass_id", None)
        _ctx.pass_id = self._pass_id
        return self

    def __exit__(self, *exc):
        _ctx.pass_id = self._prev
        return False


# -- distributed trace context (docs/observability.md) ------------------------


def propagate_enabled() -> bool:
    """Whether trace-context propagation is armed: tracing must be on
    (no recorder = nothing to correlate) and KSS_TRACE_PROPAGATE not
    spelled falsy (default on — arming KSS_TRACE arms the fleet's
    causal joins too)."""
    if active() is None:
        return False
    raw = os.environ.get(PROPAGATE_VAR, "")
    if not raw:
        return True
    from .envcheck import FALSY

    return raw.strip().lower() not in FALSY


def new_trace_id() -> str:
    """A fresh 128-bit trace id, W3C trace-context shaped (32 lowercase
    hex chars, never all-zero)."""
    return secrets.token_hex(16)


def new_span_id() -> str:
    """A fresh 64-bit parent span id for a traceparent hop."""
    return secrets.token_hex(8)


def make_traceparent(trace_id: str, span_id: "str | None" = None) -> str:
    """The ``traceparent`` header value for one outbound hop:
    ``00-<32hex trace id>-<16hex parent span id>-01`` (version 00,
    sampled flag set — everything this plane propagates is recorded)."""
    return f"00-{trace_id}-{span_id or new_span_id()}-01"


def parse_traceparent(header: "str | None") -> "str | None":
    """The trace id carried by a ``traceparent`` header, or None for
    anything malformed — a bad header must degrade to an untraced
    request, never an error on the serving path."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, _flags = parts
    if version != "00" or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16)
        int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32:
        return None
    return trace_id


def current_trace_id() -> "str | None":
    """The trace id of the innermost `trace_context` on this thread."""
    return getattr(_ctx, "trace_id", None)


class trace_context:
    """Thread-local distributed-trace causality: spans/instants emitted
    inside carry ``args["trace"] = trace_id`` — the id the fleet router
    minted for the originating request (or None to run untraced). Same
    plain-class shape as `pass_context`; re-entered on broker worker
    threads and async-pass resolution for work a traced request armed."""

    __slots__ = ("_trace_id", "_prev")

    def __init__(self, trace_id: "str | None"):
        self._trace_id = trace_id
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_ctx, "trace_id", None)
        _ctx.trace_id = self._trace_id
        return self

    def __exit__(self, *exc):
        _ctx.trace_id = self._prev
        return False


# -- emission -----------------------------------------------------------------


def _args(pass_id, attrs: dict) -> dict:
    if pass_id is None:
        pass_id = current_pass_id()
    session_id = attrs.get("session", current_session_id())
    # explicit trace=None (an untraced async handle) must not leave a
    # null key behind — strip it and fall back to the thread-local id
    trace_id = attrs.get("trace") or current_trace_id()
    if (
        pass_id is not None
        or session_id is not None
        or trace_id is not None
        or "trace" in attrs
    ):
        attrs = dict(attrs)
        if pass_id is not None:
            attrs["pass"] = pass_id
        if session_id is not None:
            attrs["session"] = session_id
        if trace_id is not None:
            attrs["trace"] = trace_id
        else:
            attrs.pop("trace", None)
    return attrs


class _NullSpan:
    """The shared no-op span: what `span()` hands out when tracing is
    off — no allocation beyond the call itself."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    __slots__ = ("_rec", "_name", "_a")

    def __init__(self, rec: SpanRecorder, name: str, args: dict):
        self._rec = rec
        self._name = name
        self._a = args

    def __enter__(self):
        self._rec.emit(
            {
                "ph": "B",
                "name": self._name,
                "cat": "kss",
                "ts": _now_us(),
                "pid": _PID,
                "tid": threading.get_ident(),
                "args": self._a,
            }
        )
        return self

    def __exit__(self, *exc):
        self._rec.emit(
            {
                "ph": "E",
                "name": self._name,
                "cat": "kss",
                "ts": _now_us(),
                "pid": _PID,
                "tid": threading.get_ident(),
                "args": self._a,
            }
        )
        return False


def span(name: str, pass_id: "int | None" = None, **attrs):
    """A context manager recording `name` as a B/E span on the calling
    thread's track, stamped with the current (or given) pass id. When
    tracing is off this returns a shared no-op immediately."""
    rec = active()
    if rec is None:
        return _NULL_SPAN
    return _LiveSpan(rec, name, _args(pass_id, attrs))


def complete(
    name: str,
    start_s: float,
    end_s: float,
    *,
    tid: "int | None" = None,
    pass_id: "int | None" = None,
    **attrs,
) -> None:
    """One `X` (complete) event over [start_s, end_s] perf_counter
    seconds, on `tid` (default: the calling thread; pass `DEVICE_TID`
    for the synthetic device track). The async pipeline emits its
    dispatch→resolve windows through this at resolve time — the one
    span shape that can OVERLAP host spans instead of nesting."""
    rec = active()
    if rec is None:
        return
    rec.emit(
        {
            "ph": "X",
            "name": name,
            "cat": "kss",
            "ts": start_s * 1e6,
            "dur": max(0.0, (end_s - start_s) * 1e6),
            "pid": _PID,
            "tid": threading.get_ident() if tid is None else tid,
            "args": _args(pass_id, attrs),
        }
    )


def counter(name: str, value: float, tid: "int | None" = None) -> None:
    """A Chrome counter-track sample (`C` event): Perfetto renders the
    series of `value`s under `name` as a stepped area alongside the
    span tracks — load (pending pods, cumulative stall/dispatch
    seconds) next to the work that caused it. One series per name (the
    args key is always ``value``), no-op when tracing is off."""
    rec = active()
    if rec is None:
        return
    rec.emit(
        {
            "ph": "C",
            "name": name,
            "cat": "kss",
            "ts": _now_us(),
            "pid": _PID,
            "tid": threading.get_ident() if tid is None else tid,
            "args": {"value": float(value)},
        }
    )


def instant(name: str, pass_id: "int | None" = None, **attrs) -> None:
    """A point event on the calling thread's track (injected faults,
    sim-time marks)."""
    rec = active()
    if rec is None:
        return
    rec.emit(
        {
            "ph": "i",
            "name": name,
            "cat": "kss",
            "s": "t",
            "ts": _now_us(),
            "pid": _PID,
            "tid": threading.get_ident(),
            "args": _args(pass_id, attrs),
        }
    )


# -- Chrome trace-event export -----------------------------------------------


def _thread_names() -> dict:
    return {t.ident: t.name for t in threading.enumerate() if t.ident}


def chrome_trace(events: list[dict], *, dropped: int = 0) -> dict:
    """The Chrome trace-event JSON document (the JSON Object Format:
    https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU)
    for `events`, with process/thread metadata so Perfetto names the
    tracks. Loadable as-is in https://ui.perfetto.dev."""
    names = _thread_names()
    meta: list[dict] = [
        {
            "ph": "M",
            "name": "process_name",
            "pid": _PID,
            "tid": DEVICE_TID,
            "args": {"name": "kube-scheduler-simulator-tpu"},
        }
    ]
    seen_tids: set = set()
    for ev in events:
        tid = ev.get("tid")
        if tid in seen_tids:
            continue
        seen_tids.add(tid)
        if tid == DEVICE_TID:
            label = "device (in-flight passes)"
        else:
            label = names.get(tid, f"thread-{tid}")
        meta.append(
            {
                "ph": "M",
                "name": "thread_name",
                "pid": _PID,
                "tid": tid,
                "args": {"name": label},
            }
        )
    return {
        "traceEvents": meta + list(events),
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "kube_scheduler_simulator_tpu.utils.telemetry",
            "droppedEvents": dropped,
        },
    }


def dump_chrome_trace(path: str, recorder: "SpanRecorder | None" = None) -> int:
    """Write the recorder's retained window as a Chrome trace JSON file
    (the lifecycle CLI's --perfetto-out); returns the event count
    written. With no active recorder, writes an empty (still loadable)
    document."""
    rec = recorder if recorder is not None else active()
    events = rec.snapshot() if rec is not None else []
    doc = chrome_trace(events, dropped=rec.dropped if rec is not None else 0)
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(events)


def clock_us() -> float:
    """This process's monotonic trace clock (perf_counter µs) — the
    value worker exports report in ``otherData.clockUs`` so the fleet
    router's merge handshake can estimate per-process clock offsets
    (`merged_chrome_trace`)."""
    return _now_us()


def merged_chrome_trace(tracks: "list[dict]", *, dropped: int = 0) -> dict:
    """Join per-process Chrome-trace exports into ONE Perfetto document:
    each `tracks` entry is ``{"pid": int, "name": str, "events": [...],
    "offset_us": float, "thread_names": {tid: label} | None}``. Every
    event's ``ts`` is shifted by its track's monotonic-clock offset
    (estimated NTP-style by the caller's probe handshake: the midpoint
    of the fetch window minus the export's ``otherData.clockUs``) and
    its ``pid`` remapped to the track's — one process lane per worker
    plus the router lane. A constant per-track shift preserves each
    process's B/E ordering, so merged intervals stay well-formed
    (`check_nesting` holds iff it held per export)."""
    meta: list[dict] = []
    out: list[dict] = []
    for track in tracks:
        pid = int(track["pid"])
        names = track.get("thread_names") or {}
        offset = float(track.get("offset_us") or 0.0)
        meta.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": DEVICE_TID,
                "args": {"name": str(track.get("name") or f"process {pid}")},
            }
        )
        seen_tids: set = set()
        for ev in track.get("events") or []:
            if ev.get("ph") == "M":
                # per-export metadata is rebuilt here with the merged
                # pids; carry the thread labels over instead
                if ev.get("name") == "thread_name":
                    names.setdefault(
                        ev.get("tid"), (ev.get("args") or {}).get("name")
                    )
                continue
            ev = dict(ev)
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) + offset
            out.append(ev)
            tid = ev.get("tid")
            if tid not in seen_tids:
                seen_tids.add(tid)
                meta.append(
                    {
                        "ph": "M",
                        "name": "thread_name",
                        "pid": pid,
                        "tid": tid,
                        "args": {
                            "name": names.get(tid) or f"thread-{tid}"
                        },
                    }
                )
    return {
        "traceEvents": meta + out,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "kube_scheduler_simulator_tpu.utils.telemetry",
            "droppedEvents": dropped,
            "merged": True,
            "tracks": [
                {
                    "pid": int(t["pid"]),
                    "name": str(t.get("name") or ""),
                    "offsetUs": round(float(t.get("offset_us") or 0.0), 3),
                }
                for t in tracks
            ],
        },
    }


# -- span-interval utilities (tests, smoke tooling) ---------------------------


def span_intervals(events: list[dict]) -> list[dict]:
    """Reconstruct closed spans from a trace-event list: each `X` event
    directly, each per-thread balanced B/E pair as one interval. Returns
    dicts ``{"name", "pid", "tid", "start_us", "end_us", "args"}``;
    unmatched B/E (ring-evicted partners) are skipped. Stacks are keyed
    (pid, tid): in a MERGED document (`merged_chrome_trace`) thread ids
    can collide across process tracks — same-process exports carry one
    constant pid, so the grouping is unchanged there. Also the well-
    formedness checker's engine: `check_nesting` raises on interleaved
    pairs."""
    out: list[dict] = []
    stacks: "dict[tuple, list[dict]]" = {}
    for ev in events:
        ph = ev.get("ph")
        if ph == "X":
            out.append(
                {
                    "name": ev["name"],
                    "pid": ev.get("pid"),
                    "tid": ev.get("tid"),
                    "start_us": float(ev["ts"]),
                    "end_us": float(ev["ts"]) + float(ev.get("dur", 0.0)),
                    "args": ev.get("args", {}),
                }
            )
        elif ph == "B":
            stacks.setdefault(
                (ev.get("pid"), ev.get("tid")), []
            ).append(ev)
        elif ph == "E":
            stack = stacks.get((ev.get("pid"), ev.get("tid")))
            if stack and stack[-1]["name"] == ev["name"]:
                b = stack.pop()
                out.append(
                    {
                        "name": b["name"],
                        "pid": b.get("pid"),
                        "tid": b.get("tid"),
                        "start_us": float(b["ts"]),
                        "end_us": float(ev["ts"]),
                        "args": b.get("args", {}),
                    }
                )
    return out


def check_nesting(events: list[dict], *, dropped: int = 0) -> None:
    """Raise ValueError unless every thread's B/E events form balanced,
    properly-nested pairs (E matches the innermost open B by name).
    With `dropped` > 0 (a ring-wrapped window: pass the recorder's
    `dropped` count, or the export's `otherData.droppedEvents`), E
    events arriving on an empty stack are tolerated — their B partners
    were evicted; proper LIFO closing means such orphans always land on
    an empty stack, so interleaving detection is unaffected. Stacks are
    keyed (pid, tid), so merged multi-process documents check each
    process track independently."""
    stacks: "dict[tuple, list[str]]" = {}
    for ev in events:
        ph = ev.get("ph")
        tid = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            stacks.setdefault(tid, []).append(ev["name"])
        elif ph == "E":
            stack = stacks.setdefault(tid, [])
            if not stack:
                if dropped > 0:
                    continue  # B partner evicted from the ring
                raise ValueError(
                    f"unmatched E {ev['name']!r} on tid {tid} (no open span)"
                )
            if stack[-1] != ev["name"]:
                raise ValueError(
                    f"interleaved spans on tid {tid}: E {ev['name']!r} "
                    f"closes innermost B {stack[-1]!r}"
                )
            stack.pop()
    open_spans = {t: s for t, s in stacks.items() if s}
    if open_spans:
        raise ValueError(f"unclosed spans at end of window: {open_spans}")
