"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip hardware isn't available in CI; all sharding tests run over a
virtual 8-device CPU mesh, which exercises the same pjit/shard_map
partitioning XLA applies on a real TPU slice.
"""

import os

# The test suite builds hundreds of small services; ambient speculative
# background compiles would add nondeterministic work (and wall time) to
# every one of them. Tests that exercise the predictive-compile path opt
# back in by constructing CompileBroker(speculative=True) explicitly.
os.environ.setdefault("KSS_NO_SPECULATIVE_COMPILE", "1")

# Ambient run-supervision settings must not leak into the suite: a shell
# with fault injection or a compile deadline exported would skew every
# test. Tests that exercise the ladder set these with monkeypatch.
for _var in (
    "KSS_FAULT_INJECT",
    "KSS_FAULT_INJECT_SEED",
    "KSS_COMPILE_DEADLINE_S",
    "KSS_COMPILE_RETRIES",
    "KSS_COMPILE_BACKOFF_S",
    "KSS_COMPILE_COOLDOWN_PASSES",
    "KSS_COMPILE_COOLDOWN_TTL_S",
    # the execution ladder + graceful drain (docs/resilience.md):
    # ambient dispatch deadlines / retries / drain budgets would skew
    # every pass; ladder tests set them with monkeypatch
    "KSS_DISPATCH_DEADLINE_S",
    "KSS_DISPATCH_RETRIES",
    "KSS_DRAIN_DEADLINE_S",
    # the flight recorder (utils/telemetry.py): an ambient KSS_TRACE=1
    # would make every test pay span emission (and the off-by-default
    # zero-emission test would fail for the wrong reason)
    "KSS_TRACE",
    "KSS_TRACE_RING_CAP",
    "KSS_TRACE_PROPAGATE",
    # the fleet & memory observatory (utils/fleetstats.py): ambient
    # KSS_FLEET_STATS=1 would make every pass in the suite pay the
    # quality reduction + host fetch, and an ambient headroom floor
    # would silently veto the speculation tests; observatory tests arm
    # these explicitly
    "KSS_FLEET_STATS",
    "KSS_FLEET_RING_CAP",
    "KSS_FLEET_SAMPLE",
    "KSS_SPEC_MEM_HEADROOM_BYTES",
    # the SLO plane (utils/slo.py): ambient arming would make every
    # pass in the suite pay observation + evaluation (and ambient
    # objective/window overrides would skew the state-machine tests);
    # SLO tests arm planes explicitly. KSS_EXEMPLARS is default-ON —
    # scrubbed so a shell exporting KSS_EXEMPLARS=0 can't silently
    # empty the exemplar round-trip tests
    "KSS_SLO",
    "KSS_SLO_OBJECTIVES",
    "KSS_SLO_WINDOW_FAST_S",
    "KSS_SLO_WINDOW_SLOW_S",
    "KSS_SLO_BURN_FAST",
    "KSS_SLO_BURN_SLOW",
    "KSS_SLO_ALERT_FOR_S",
    "KSS_SLO_ALERT_RING_CAP",
    "KSS_EXEMPLARS",
    # the lock-order witness (utils/locking.py): an ambient
    # KSS_LOCK_CHECK=1 would wrap every lock the suite creates; the
    # witness tests arm it explicitly with monkeypatch
    "KSS_LOCK_CHECK",
    # the guarded-state witness + jaxpr auditor (docs/static-analysis.md
    # KSS6xx/KSS7xx): ambient arming would instrument every class /
    # re-trace every program the suite builds; their tests opt in
    "KSS_RACE_CHECK",
    "KSS_RACE_CHECK_SAMPLE",
    "KSS_JAXPR_AUDIT",
    "KSS_LINT_STRICT",
    # the program performance ledger (utils/ledger.py): ambient arming
    # would AOT-probe every program the suite compiles (and sampling
    # would synchronize the async pipeline); ledger tests opt in
    "KSS_PROGRAM_LEDGER",
    "KSS_PROGRAM_TIMING_SAMPLE",
    # the AOT bundle store (utils/bundles.py): ambient arming would
    # serialize every program the suite compiles to a shared directory
    # (and cross-test loads would hide real compile behavior); bundle
    # tests opt in with monkeypatch + tmp_path
    "KSS_AOT_BUNDLES",
    "KSS_BUNDLE_DIR",
    # the continuous-batching plane (server/batchplane.py): ambient
    # KSS_BATCH=1 would route every suite pass through collection
    # windows (latency + a vmapped compile per shape); batching tests
    # arm planes explicitly
    "KSS_BATCH",
    "KSS_BATCH_WINDOW_MS",
    "KSS_BATCH_MAX_WAIT_MS",
    "KSS_BATCH_MAX_SESSIONS",
    # the encoded-cluster dtype policy (engine/encode.py): an ambient
    # KSS_DTYPE_POLICY=packed would re-key every encoding and compile
    # signature the suite pins; packed-policy tests pass the policy (or
    # set the knob) explicitly
    "KSS_DTYPE_POLICY",
    # the gang serving chunk (server/service.py gang_chunk): an ambient
    # override would re-key every gang engine the suite builds (the
    # chunk is part of the compile signature) and skew the dispatch-
    # count pins; chunk tests pass it explicitly
    "KSS_GANG_CHUNK",
    # the session plane (server/sessions.py): ambient admission knobs
    # would change quota/limit behavior under test
    "KSS_MAX_SESSIONS",
    "KSS_MAX_PENDING_PODS_PER_SESSION",
    "KSS_MAX_CONCURRENT_PASSES",
    "KSS_SESSION_IDLE_EVICT_S",
    "KSS_SESSION_DIR",
    "KSS_SSE_MAX_SUBSCRIBERS",
    # the serving fleet (fleet/router.py): an ambient KSS_WORKER_ID
    # would stamp a worker label on every exposition the suite parses;
    # fleet tests set identities with monkeypatch + tmp_path
    "KSS_WORKER_ID",
    "KSS_FLEET_WORKERS",
    "KSS_FLEET_DIR",
    "KSS_FLEET_BASE_PORT",
    "KSS_FLEET_PROBE_INTERVAL_S",
    # the fleet durability plane + router resilience (docs/fleet.md,
    # docs/resilience.md): ambient journaling would add disk writes to
    # every acknowledged mutation in the suite, and ambient breaker/
    # retry/transport overrides would skew the state-machine and
    # re-home tests; durability tests arm these explicitly
    "KSS_FLEET_JOURNAL",
    "KSS_FLEET_JOURNAL_SYNC",
    "KSS_FLEET_REPLICAS",
    "KSS_FLEET_REPLICATE_EVERY_S",
    "KSS_FLEET_REQUEST_TIMEOUT_S",
    "KSS_FLEET_ADOPT_TIMEOUT_S",
    "KSS_FLEET_RETRIES",
    "KSS_FLEET_RETRY_BACKOFF_S",
    "KSS_FLEET_BREAKER_FAILURES",
    "KSS_FLEET_BREAKER_OPEN_S",
    "KSS_FLEET_TRANSPORT",
    "KSS_FLEET_REQUEST_RING_CAP",
):
    os.environ.pop(_var, None)

# Force-set (not setdefault): the image's shell env pins JAX_PLATFORMS=axon
# (the real TPU), which would silently move the whole suite onto the single
# real chip — slow compiles and no 8-device mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Belt and braces: the axon sitecustomize registers the TPU plugin at
# interpreter start; pin the platform at the config level too.
jax.config.update("jax_platforms", "cpu")
# The EXACT dtype policy (engine/encode.py) needs 64-bit ints/floats for
# bit-parity with the pure-Python oracle on arbitrary quantities.
jax.config.update("jax_enable_x64", True)
# Persistent compilation cache: many tests build fresh engines whose
# programs are HLO-identical (different BatchedScheduler instances can't
# share the in-process jit cache) — dedupe them across tests AND runs.
# Single definition (incl. the KSS_JAX_CACHE_DIR override) lives in
# utils/compilecache.py, shared with bench.py and tools/.
from kube_scheduler_simulator_tpu.utils.compilecache import (  # noqa: E402
    enable_compile_cache,
)

enable_compile_cache(min_compile_time_secs=0.3)
