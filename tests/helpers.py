"""Manifest builders shared by oracle and kernel tests."""


def node(name, cpu="4", mem="8Gi", pods="110", labels=None, taints=None,
         unschedulable=False, images=None):
    n = {
        "metadata": {"name": name, "labels": labels or {}},
        "spec": {},
        "status": {"allocatable": {"cpu": cpu, "memory": mem, "pods": pods}},
    }
    if taints:
        n["spec"]["taints"] = taints
    if unschedulable:
        n["spec"]["unschedulable"] = True
    if images:
        n["status"]["images"] = images
    return n


def pod(name, cpu="100m", mem="128Mi", ns="default", labels=None, node_name=None,
        node_selector=None, affinity=None, tolerations=None, priority=None,
        priority_class=None, spread=None, ports=None, images=None, volumes=None):
    containers = []
    if images:
        for i, img in enumerate(images):
            containers.append({"name": f"c{i}", "image": img})
        if cpu or mem:
            containers[0]["resources"] = {"requests": {"cpu": cpu, "memory": mem}}
        if ports:
            containers[0]["ports"] = ports
    else:
        c = {"name": "c", "resources": {"requests": {"cpu": cpu, "memory": mem}}}
        if ports:
            c["ports"] = ports
        containers = [c]
    p = {
        "metadata": {"name": name, "namespace": ns, "labels": labels or {}},
        "spec": {"containers": containers},
    }
    if node_name:
        p["spec"]["nodeName"] = node_name
    if node_selector:
        p["spec"]["nodeSelector"] = node_selector
    if affinity:
        p["spec"]["affinity"] = affinity
    if tolerations:
        p["spec"]["tolerations"] = tolerations
    if priority is not None:
        p["spec"]["priority"] = priority
    if priority_class:
        p["spec"]["priorityClassName"] = priority_class
    if spread:
        p["spec"]["topologySpreadConstraints"] = spread
    if volumes:
        p["spec"]["volumes"] = volumes
    return p
