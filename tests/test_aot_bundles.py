"""The persistent AOT bundle store (utils/bundles.py) + the fused
mega-pass dispatch (docs/performance.md).

The contract under test:

* a warm bundle dir serves a fresh engine's programs by DESERIALIZING
  executables (bundleLoads, zero compile), with placements byte-
  identical to the unbundled run — the parity pin;
* every invalidation rung falls back SILENTLY to a fresh compile with
  identical placements: KSS715 fingerprint drift, a device-epoch bump
  in the broker key, a jax-version mismatch (key-level and
  header-level), and a truncated/corrupt bundle file;
* `CompileBroker.quiesce`/`drain` flush in-flight bundle writes
  (atomic tmp-file + rename — no torn bundle for the next boot);
* the fused programs cut per-pass broker dispatch counts (asserted
  from program-ledger call counts) with records/trace unchanged:
  `seq.step` halves the extender loop's per-pod dispatches for pods no
  extender touches, and `gang.replay_round` folds the record replay's
  eval+bind pair into one dispatch per round chunk.
"""

import json
import os

import numpy as np
import pytest

from kube_scheduler_simulator_tpu.engine import TPU32, encode_cluster
from kube_scheduler_simulator_tpu.engine.engine import BatchedScheduler
from kube_scheduler_simulator_tpu.engine.gang import GangScheduler
from kube_scheduler_simulator_tpu.sched.config import SchedulerConfiguration
from kube_scheduler_simulator_tpu.utils import broker as broker_mod
from kube_scheduler_simulator_tpu.utils import bundles as bundles_mod
from kube_scheduler_simulator_tpu.utils import ledger as ledger_mod

from helpers import node, pod

# a deliberately small compile class: one filter, one score — the
# bundle machinery is what's under test, not the kernels
TINY_CFG = SchedulerConfiguration.from_dict(
    {
        "profiles": [
            {
                "schedulerName": "default-scheduler",
                "plugins": {
                    "preFilter": {"disabled": [{"name": "*"}]},
                    "filter": {
                        "disabled": [{"name": "*"}],
                        "enabled": [{"name": "NodeResourcesFit"}],
                    },
                    "postFilter": {"disabled": [{"name": "*"}]},
                    "preScore": {"disabled": [{"name": "*"}]},
                    "score": {
                        "disabled": [{"name": "*"}],
                        "enabled": [{"name": "NodeResourcesFit"}],
                    },
                },
            }
        ]
    }
)


def _tiny_enc(n_nodes=2, n_pods=6):
    nodes = [node(f"n{i}", cpu="8", mem="16Gi") for i in range(n_nodes)]
    pods = [pod(f"p{i}", cpu="500m") for i in range(n_pods)]
    return encode_cluster(nodes, pods, TINY_CFG, policy=TPU32)


@pytest.fixture
def store(monkeypatch, tmp_path):
    """A fresh, isolated bundle store armed via the real env switch
    (read at jit-WRAP time), swapped in for the process global so
    engine builds inside the test hit it."""
    monkeypatch.setenv(bundles_mod.ENV_VAR, "1")
    monkeypatch.setenv(bundles_mod.DIR_VAR, str(tmp_path / "bundles"))
    fresh = bundles_mod.BundleStore()
    monkeypatch.setattr(bundles_mod, "STORE", fresh)
    yield fresh
    fresh.flush(30.0)


def _run_placements(enc):
    s = BatchedScheduler(enc, record=False)
    s.run()
    return s.placements()


def _bundle_files(store):
    d = store.directory
    try:
        return sorted(
            f for f in os.listdir(d) if f.endswith(bundles_mod.BUNDLE_SUFFIX)
        )
    except OSError:
        return []


# -- round trip + parity -------------------------------------------------------


def test_roundtrip_loads_and_placements_identical(monkeypatch, store):
    enc = _tiny_enc()
    # the unbundled truth, computed with the switch OFF
    monkeypatch.setenv(bundles_mod.ENV_VAR, "0")
    baseline = _run_placements(enc)
    monkeypatch.setenv(bundles_mod.ENV_VAR, "1")

    first = _run_placements(enc)  # compiles + saves
    assert first == baseline
    assert store.flush(30.0)
    st = store.stats()
    assert st["bundleSaves"] >= 1 and st["bundleLoads"] == 0
    assert _bundle_files(store)

    store.reset_stats()
    second = _run_placements(enc)  # a fresh engine: must deserialize
    assert second == baseline
    st = store.stats()
    assert st["bundleLoads"] >= 1
    assert st["bundleMisses"] == 0 and st["bundleBypasses"] == 0


def test_scope_keys_bundles_per_broker_key(store):
    """The broker key (incl. the PR 8 device-epoch suffix) is part of
    bundle identity: an epoch-bumped key can never resurrect the old
    epoch's executable — it misses cleanly and compiles fresh."""
    enc = _tiny_enc()
    key0 = ("seq", ("sig",))
    with bundles_mod.build_scope(key0):
        p0 = _run_placements(enc)
    assert store.flush(30.0)
    n_files = len(_bundle_files(store))
    assert n_files >= 1

    store.reset_stats()
    with bundles_mod.build_scope(key0):
        p_same = _run_placements(enc)  # same scope: loads
    assert store.stats()["bundleLoads"] >= 1
    assert p_same == p0

    store.reset_stats()
    key1 = key0 + (("devepoch", 1),)
    with bundles_mod.build_scope(key1):
        p_bumped = _run_placements(enc)  # bumped epoch: clean miss
    st = store.stats()
    assert st["bundleLoads"] == 0 and st["bundleMisses"] >= 1
    assert st["bundleBypasses"] == 0
    assert p_bumped == p0
    assert store.flush(30.0)
    assert len(_bundle_files(store)) > n_files  # its own bundle saved


# -- the invalidation matrix ---------------------------------------------------


def _warm_store(store, enc):
    p = _run_placements(enc)
    assert store.flush(30.0)
    files = _bundle_files(store)
    assert files
    store.reset_stats()
    return p, [os.path.join(store.directory, f) for f in files]


def test_fingerprint_drift_bypasses_to_fresh_compile(
    monkeypatch, tmp_path, store
):
    """A persisted KSS715 baseline that knows the site but NOT the
    bundle's fingerprint means the site's program set drifted: the
    bundle is bypassed and the pass compiles fresh — same placements."""
    from kube_scheduler_simulator_tpu.analysis import jaxpr_audit

    enc = _tiny_enc()
    baseline_placements, files = _warm_store(store, enc)
    # doctor a baseline next to the (isolated) compile cache claiming
    # every bundled site compiles a different program
    cache_dir = tmp_path / "cache"
    cache_dir.mkdir()
    monkeypatch.setenv("KSS_JAX_CACHE_DIR", str(cache_dir))
    labels = set()
    for path in files:
        with open(path, "rb") as f:
            header = json.loads(f.read().split(b"\n", 1)[0])
        labels.add(header["identity"]["label"])
    with open(jaxpr_audit.fingerprint_path(), "w") as f:
        json.dump(
            {
                "format": jaxpr_audit.FINGERPRINT_FORMAT,
                "fingerprints": {lb: ["0123456789abcdef"] for lb in labels},
            },
            f,
        )
    placements = _run_placements(enc)
    st = store.stats()
    assert st["bundleBypasses"] >= 1 and st["bundleLoads"] == 0
    assert placements == baseline_placements


def test_jax_version_mismatch_keys_and_bypasses(monkeypatch, store):
    """Version drift is caught twice: a DIFFERENT running version keys
    to different filenames (clean miss), and a doctored header claiming
    another version under the same key is bypassed by verification."""
    enc = _tiny_enc()
    baseline_placements, files = _warm_store(store, enc)

    # header-level: rewrite one bundle's identity to a foreign jax
    for path in files:
        with open(path, "rb") as f:
            head, payload = f.read().split(b"\n", 1)
        header = json.loads(head)
        header["identity"]["env"]["jax"] = "0.0.0-foreign"
        with open(path, "wb") as f:
            f.write(json.dumps(header).encode() + b"\n" + payload)
    placements = _run_placements(enc)
    st = store.stats()
    assert st["bundleBypasses"] >= 1 and st["bundleLoads"] == 0
    assert placements == baseline_placements

    # key-level: a process running a foreign jax computes different
    # digests and never even opens the old files
    assert store.flush(30.0)
    store.reset_stats()
    foreign = dict(bundles_mod._environment_identity(), jax="0.0.0-foreign")
    monkeypatch.setattr(bundles_mod, "_env_digest_cache", foreign)
    placements = _run_placements(enc)
    st = store.stats()
    assert st["bundleMisses"] >= 1 and st["bundleLoads"] == 0
    assert placements == baseline_placements


def test_truncated_and_corrupt_bundles_bypass(store):
    enc = _tiny_enc()
    baseline_placements, files = _warm_store(store, enc)
    # truncate to half: the payload checksum (or the unpickler) rejects
    for path in files:
        with open(path, "rb") as f:
            blob = f.read()
        with open(path, "wb") as f:
            f.write(blob[: len(blob) // 2])
    placements = _run_placements(enc)
    st = store.stats()
    assert st["bundleBypasses"] >= 1 and st["bundleLoads"] == 0
    assert placements == baseline_placements

    # pure garbage: no parseable header
    assert store.flush(30.0)
    store.reset_stats()
    for path in _bundle_files(store):
        with open(os.path.join(store.directory, path), "wb") as f:
            f.write(b"\x00\xff garbage not a bundle")
    placements = _run_placements(enc)
    st = store.stats()
    assert st["bundleBypasses"] >= 1 and st["bundleLoads"] == 0
    assert placements == baseline_placements


# -- quiesce/drain flushes writes ---------------------------------------------


def test_broker_drain_flushes_inflight_bundle_writes(monkeypatch, store):
    """A quiescing broker must out-wait the bundle writer: after
    `quiesce()` returns True there are zero pending writes and every
    bundle landed via tmp-file + rename (no torn siblings)."""
    import threading
    import time as time_mod

    import jax

    gate = threading.Event()
    real_write = bundles_mod.BundleStore._write_atomic

    def slow_write(path, blob):
        gate.wait(5.0)
        real_write(path, blob)

    monkeypatch.setattr(
        bundles_mod.BundleStore, "_write_atomic", staticmethod(slow_write)
    )
    jitted = jax.jit(lambda x: x + 1)
    args = (np.arange(4, dtype=np.int32),)
    compiled = jitted.trace(*args).lower().compile()
    digest, doc = bundles_mod.bundle_key("t.prog", None, {}, args, {})
    assert store.save("t.prog", digest, doc, compiled, "fp")
    assert store.stats()["pendingWrites"] == 1

    broker = broker_mod.CompileBroker(speculative=False)
    done = {}

    def drain():
        done["ok"] = broker.quiesce(timeout=10.0)

    t = threading.Thread(target=drain)
    t.start()
    time_mod.sleep(0.05)
    assert not done  # drain is genuinely blocked on the bundle write
    gate.set()
    t.join(10.0)
    assert done.get("ok") is True
    st = store.stats()
    assert st["pendingWrites"] == 0 and st["bundleSaves"] == 1
    files = os.listdir(store.directory)
    assert any(f.endswith(bundles_mod.BUNDLE_SUFFIX) for f in files)
    assert not any(".tmp." in f for f in files)  # rename, not in-place


# -- fused mega-pass dispatch counts ------------------------------------------


@pytest.fixture
def ledger(monkeypatch):
    monkeypatch.setenv(ledger_mod.ENV_VAR, "1")
    ledger_mod.LEDGER.reset()
    yield ledger_mod.LEDGER
    ledger_mod.LEDGER.reset()


def _ledger_calls(ledger):
    return {
        p["label"]: p["calls"] for p in ledger.snapshot()["programs"]
    }


def test_fused_step_halves_extender_loop_dispatches(ledger):
    """Pods no extender touches ride the fused seq.step program: ONE
    dispatch per pod instead of attempt+bind — asserted from the
    ledger's per-program call counts — with records identical to the
    split path."""
    from kube_scheduler_simulator_tpu.engine.extender_loop import (
        ExtenderScheduler,
    )
    from kube_scheduler_simulator_tpu.sched.extender import ExtenderService

    # an extender managing a resource no pod requests: configured, but
    # interested in nothing — every pod takes the fused fast path
    service = ExtenderService(
        [
            {
                "urlPrefix": "http://127.0.0.1:1",  # never called
                "filterVerb": "filter",
                "managedResources": [{"name": "example.com/phantom"}],
            }
        ]
    )
    enc = _tiny_enc(n_nodes=2, n_pods=5)

    es = ExtenderScheduler(enc, service, strict=False)
    fused_results = es.run()
    fused_placements = es.placements()
    fused_calls = _ledger_calls(ledger)
    assert fused_calls.get("seq.step") == 5
    assert fused_calls.get("seq.attempt", 0) == 0
    assert fused_calls.get("seq.bind", 0) == 0

    # force the split path on a fresh engine: same records, 2x the
    # per-pod dispatches for the pods that placed
    ledger_mod.LEDGER.reset()
    es2 = ExtenderScheduler(enc, service, strict=False)
    es2._extender_touches = lambda pod: True
    split_results = es2.run()
    split_calls = _ledger_calls(ledger)
    assert split_calls.get("seq.step", 0) == 0
    assert split_calls.get("seq.attempt") == 5
    placed = sum(1 for r in split_results if r.status == "Scheduled")
    assert split_calls.get("seq.bind") == placed
    fused_total = sum(fused_calls.values())
    split_total = sum(split_calls.values())
    assert fused_total < split_total

    assert es2.placements() == fused_placements
    assert [r.to_annotations() if hasattr(r, "to_annotations") else vars(r)
            for r in split_results] == [
        r.to_annotations() if hasattr(r, "to_annotations") else vars(r)
        for r in fused_results
    ]


def test_gang_replay_round_fuses_eval_and_bind(ledger):
    """The record replay dispatches ONE fused program per round chunk
    (gang.replay_round) — the old separate bind_all dispatch per round
    is gone — and recorded placements still match the plain run."""
    enc = _tiny_enc(n_nodes=2, n_pods=6)
    g = GangScheduler(enc, strict=False, chunk=8)
    g.run_recorded()
    results = g.results()
    assert results
    recorded_placements = g.placements()
    calls = _ledger_calls(ledger)
    assert calls.get("gang.replay_round", 0) >= 1
    assert "gang.bind_all" not in calls

    g2 = GangScheduler(enc, strict=False, chunk=8)
    g2.run()
    assert g2.placements() == recorded_placements
