"""Stall-free serving path: CompileBroker semantics + async pipelined
lifecycle parity (the perf_opt PR's acceptance criteria).

* `CompileBroker.get` dedupes concurrent requests: two threads, ONE
  compile; the loser shares the winner's engine and books a hit.
* `speculate` builds on a background worker and the result serves later
  `get`s warm; KSS_NO_SPECULATIVE_COMPILE=1 disables it.
* `adjacent_bucket_targets` is the watermark policy: up past 80%
  occupancy, down when the next bucket down has the same headroom.
* The async pipelined lifecycle run emits a BYTE-IDENTICAL JSONL trace
  and identical deterministic SchedulingMetrics counters vs the
  synchronous path, across seeded chaos timelines with arrivals and
  binding-reading faults (fail / drain / cordon) in both scheduler
  modes — the tentpole's correctness contract.
"""

from __future__ import annotations

import threading
import time

import pytest

from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec
from kube_scheduler_simulator_tpu.utils.broker import (
    CompileBroker,
    adjacent_bucket_targets,
)
from kube_scheduler_simulator_tpu.utils.metrics import SchedulingMetrics

from helpers import node, pod


class TestCompileBrokerDedupe:
    def test_two_threads_one_compile(self):
        broker = CompileBroker(speculative=False)
        builds = []
        release = threading.Event()

        def build():
            builds.append(threading.get_ident())
            release.wait(timeout=10)
            return object()

        got = []

        def worker():
            got.append(broker.get(("k",), build))

        threads = [threading.Thread(target=worker) for _ in range(2)]
        threads[0].start()
        # let thread 0 enter the build before thread 1 asks
        for _ in range(200):
            if builds:
                break
            time.sleep(0.005)
        threads[1].start()
        time.sleep(0.05)
        release.set()
        for th in threads:
            th.join(timeout=10)
        assert len(builds) == 1  # ONE compile
        assert len(got) == 2 and got[0] is got[1]
        assert broker.compile_misses == 1
        assert broker.compile_hits == 1
        assert broker.stall_seconds > 0

    def test_failed_build_retried_by_waiter(self):
        broker = CompileBroker(speculative=False)
        calls = []

        def failing():
            calls.append(1)
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError):
            broker.get(("k",), failing)
        # the key is not poisoned: the next caller builds fresh
        eng = broker.get(("k",), lambda: "engine")
        assert eng == "engine"
        assert len(calls) == 1

    def test_lru_capacity_bound(self):
        broker = CompileBroker(speculative=False, capacity=2)
        for i in range(4):
            broker.get(("k", i), lambda i=i: f"e{i}")
        assert len(broker._engines) == 2
        # oldest evicted, newest retained
        assert broker.peek(("k", 3)) == "e3"
        assert broker.peek(("k", 0)) is None


class TestSpeculation:
    def test_background_build_serves_get_warm(self):
        m = SchedulingMetrics()
        broker = CompileBroker(metrics=m, speculative=True)
        built = []

        def task():
            def build():
                built.append(1)
                return "warm-engine"

            return ("key",), build

        assert broker.speculate("token", task)
        assert broker.drain(timeout=10)
        assert built == [1]
        assert broker.get(("key",), lambda: pytest.fail("should be warm")) == (
            "warm-engine"
        )
        phases = m.snapshot()["phases"]
        assert phases["speculativeCompiles"] == 1
        assert phases["compileMisses"] == 0
        assert phases["compileHits"] == 1

    def test_token_dedupes_pending_tasks(self):
        broker = CompileBroker(speculative=True)
        ran = []
        gate = threading.Event()

        def task():
            gate.wait(timeout=10)
            ran.append(1)
            return None

        assert broker.speculate("t", task)
        assert not broker.speculate("t", task)  # pending: deduped
        gate.set()
        assert broker.drain(timeout=10)
        assert ran == [1]

    def test_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("KSS_NO_SPECULATIVE_COMPILE", "1")
        broker = CompileBroker()
        assert broker.speculative is False
        assert not broker.speculate("t", lambda: None)

    def test_task_failure_is_contained(self):
        broker = CompileBroker(speculative=True)

        def bad_task():
            raise RuntimeError("speculation must never take the run down")

        assert broker.speculate("t", bad_task)
        assert broker.drain(timeout=10)
        assert broker.speculative_compiles == 0


class TestWatermark:
    def test_up_speculation_past_80_percent(self):
        assert adjacent_bucket_targets(52, 64) == [128]
        assert adjacent_bucket_targets(51, 64) == []  # 51 < 51.2
        assert adjacent_bucket_targets(64, 64) == [128]

    def test_down_speculation_with_headroom(self):
        # 20 live in a 128-bucket: fits 64 with < 80% occupancy
        assert adjacent_bucket_targets(20, 128) == [64]
        # 60 live: would occupy 94% of 64 — stay put
        assert adjacent_bucket_targets(60, 128) == []

    def test_never_below_floor(self):
        assert adjacent_bucket_targets(1, 8) == []
        assert adjacent_bucket_targets(3, 16, lo=8) == [8]
        assert adjacent_bucket_targets(3, 8, lo=8) == []

    def test_steady_state_arms_nothing(self):
        assert adjacent_bucket_targets(40, 64) == []


# -- async pipelined lifecycle parity ---------------------------------------


def _chaos_dict(mode: str, pipeline: str) -> dict:
    nodes = [node(f"n{i}", cpu="16", mem="32Gi", pods="110") for i in range(6)]
    # same shapes as tests/test_lifecycle_perf.py so the compiled
    # programs come from the shared persistent cache
    pods = [
        pod(f"seed-{i}", cpu="100m", node_name=f"n{i % 6}") for i in range(33)
    ]
    return {
        "name": "parity",
        "seed": 11,
        "horizon": 60.0,
        "schedulerMode": mode,
        "pipeline": pipeline,
        "snapshot": {"nodes": nodes, "pods": pods},
        "arrivals": [
            {
                "kind": "poisson",
                "rate": 0.8,
                "count": 18,
                "template": {
                    "metadata": {"name": "churn"},
                    "spec": {
                        "containers": [
                            {
                                "name": "c",
                                "resources": {
                                    "requests": {
                                        "cpu": "100m",
                                        "memory": "64Mi",
                                    }
                                },
                            }
                        ]
                    },
                },
            }
        ],
        # binding-reading faults: each forces the async pipeline's
        # resolve fence, covering eviction + re-enqueue mid-pipeline
        "faults": [
            {"at": 8.0, "action": "cordon", "node": "n0"},
            {"at": 14.0, "action": "fail", "node": "n1"},
            {"at": 20.0, "action": "recover", "node": "n1"},
            {"at": 26.0, "action": "uncordon", "node": "n0"},
            {"at": 32.0, "action": "drain", "node": "n2"},
            {"at": 40.0, "action": "uncordon", "node": "n2"},
        ],
    }


def _deterministic_counters(snapshot: dict) -> dict:
    """The SchedulingMetrics fields the parity contract pins: everything
    except wall-clock (which no two runs share)."""
    phases = snapshot["phases"]
    return {
        "passes": snapshot["passes"],
        "totalPods": snapshot["totalPods"],
        "totalScheduled": snapshot["totalScheduled"],
        "disruption": snapshot["disruption"],
        "deltaEncodes": phases["deltaEncodes"],
        "fullEncodes": phases["fullEncodes"],
        "cachedEncodes": phases["cachedEncodes"],
        "emptyEncodes": phases["emptyEncodes"],
        "engineBuilds": phases["engineBuilds"],
    }


class TestAsyncPipelineParity:
    @pytest.mark.parametrize("mode", ["gang", "sequential"])
    def test_trace_byte_identical_and_counters_equal(self, mode):
        sync_eng = LifecycleEngine(
            ChaosSpec.from_dict(_chaos_dict(mode, "sync"))
        )
        sync_res = sync_eng.run()
        async_eng = LifecycleEngine(
            ChaosSpec.from_dict(_chaos_dict(mode, "async"))
        )
        async_res = async_eng.run()
        assert sync_res["phase"] == "Succeeded"
        assert async_res["phase"] == "Succeeded"
        # the tentpole contract: byte-identical replayable JSONL
        assert sync_eng.trace_jsonl() == async_eng.trace_jsonl()
        assert _deterministic_counters(
            sync_res["metrics"]
        ) == _deterministic_counters(async_res["metrics"])
        # the run did real work (faults evicted, churn re-bound)
        assert async_res["pods"]["evicted"] > 0
        assert async_res["pods"]["arrived"] >= 10

    def test_async_timings_resolved_and_stamped(self):
        eng = LifecycleEngine(
            ChaosSpec.from_dict(_chaos_dict("gang", "async"))
        )
        res = eng.run()
        assert res["phase"] == "Succeeded"
        assert all("wallSeconds" in x for x in eng.timings)
        assert any(x.get("encodeMode") == "delta" for x in eng.timings)
        # no unresolved placeholder leaked into the trace
        assert all(ev.get("type") for ev in eng.trace)

    def test_spec_rejects_bad_pipeline(self):
        with pytest.raises(ValueError, match="pipeline"):
            ChaosSpec.from_dict(
                dict(_chaos_dict("gang", "sync"), pipeline="turbo")
            )
        with pytest.raises(ValueError, match="pipeline"):
            LifecycleEngine(
                ChaosSpec.from_dict(_chaos_dict("gang", "sync")),
                pipeline="turbo",
            )


class TestPipelineOverlapTrace:
    def test_device_execute_overlaps_next_pass_host_span(self):
        """The telemetry tentpole's acceptance criterion: in the
        exported flight recording of an async run, a `device.execute`
        X span of pass k (the dispatch→resolve in-flight window on the
        synthetic device track) measurably OVERLAPS a host-side
        `lifecycle.events` span stamped with pass k+1 — the pipeline's
        overlap asserted from the data, not eyeballed in Perfetto."""
        from kube_scheduler_simulator_tpu.utils import telemetry

        rec = telemetry.SpanRecorder(capacity=65536)
        telemetry.activate(rec)
        try:
            eng = LifecycleEngine(
                ChaosSpec.from_dict(_chaos_dict("gang", "async"))
            )
            res = eng.run()
        finally:
            telemetry.deactivate()
        assert res["phase"] == "Succeeded"
        events = rec.snapshot()
        telemetry.check_nesting(events)  # well-formed even interleaved
        intervals = telemetry.span_intervals(events)
        device = {
            iv["args"]["pass"]: iv
            for iv in intervals
            if iv["name"] == "device.execute"
            and iv["tid"] == telemetry.DEVICE_TID
        }
        assert device, "no device-execute windows recorded"
        best = 0.0
        for h in intervals:
            if h["name"] != "lifecycle.events":
                continue
            d = device.get(h["args"].get("pass", 0) - 1)
            if d is None:
                continue
            best = max(
                best,
                min(d["end_us"], h["end_us"])
                - max(d["start_us"], h["start_us"]),
            )
        assert best > 0.0, (
            "no device-execute span of pass k overlaps a host "
            "lifecycle.events span of pass k+1 — the async pipeline "
            "left no overlap in the flight recording"
        )


class TestEncodingCacheCap:
    def test_env_override(self, monkeypatch):
        from kube_scheduler_simulator_tpu.models.store import ResourceStore
        from kube_scheduler_simulator_tpu.server.service import SchedulerService

        monkeypatch.setenv("KSS_ENCODING_CACHE_CAP", "3")
        svc = SchedulerService(ResourceStore())
        assert svc.encoding_cache_capacity == 3
        assert svc._enc_cache.capacity == 3

    def test_bad_values_fall_back_to_default(self, monkeypatch):
        from kube_scheduler_simulator_tpu.models.store import ResourceStore
        from kube_scheduler_simulator_tpu.server.service import SchedulerService

        for bad in ("nope", "0", "-2"):
            monkeypatch.setenv("KSS_ENCODING_CACHE_CAP", bad)
            assert SchedulerService(ResourceStore()).encoding_cache_capacity == 8

    def test_metrics_route_surfaces_capacity(self):
        import json
        import urllib.request

        from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
        from kube_scheduler_simulator_tpu.server.service import SimulatorService

        server = SimulatorServer(SimulatorService(), port=0).start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/api/v1/metrics"
            ) as resp:
                doc = json.loads(resp.read())
            assert doc["encodingCacheCapacity"] == 8
            assert "stallSeconds" in doc["phases"]
        finally:
            server.shutdown()
