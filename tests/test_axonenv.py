"""The boot-time device probe + CPU re-exec path (utils/axonenv.py) —
previously zero unit coverage (ISSUE 9 satellite): the watchdog against
a fake WEDGED backend, the scrub/re-exec environment contract, the
re-exec loop guard, and the lifecycle CLI honoring the same probe the
serving shell runs."""

from __future__ import annotations

import json
import os
import time

import pytest

from kube_scheduler_simulator_tpu.utils import axonenv


class TestProbeDevices:
    def test_healthy_backend_returns_devices(self):
        devices, error = axonenv.probe_devices(
            timeout_s=5.0, get_devices=lambda: ["dev0", "dev1"]
        )
        assert devices == ["dev0", "dev1"]
        assert error is None

    def test_wedged_backend_hangs_past_the_watchdog(self):
        """The observed failure mode: enumeration itself hangs. The
        probe must return ([], None) at the timeout — the daemon thread
        is abandoned, never joined."""

        def wedged():
            time.sleep(30)
            return ["never"]

        t0 = time.monotonic()
        devices, error = axonenv.probe_devices(
            timeout_s=0.1, get_devices=wedged
        )
        assert devices == []
        assert error is None
        assert time.monotonic() - t0 < 5.0  # returned at the watchdog

    def test_failing_backend_reports_its_exception(self):
        def broken():
            raise RuntimeError("plugin init failed")

        devices, error = axonenv.probe_devices(
            timeout_s=5.0, get_devices=broken
        )
        assert devices == []
        assert isinstance(error, RuntimeError)

    def test_probe_why_wording(self):
        assert "failed" in axonenv.probe_why(RuntimeError("x"), 10.0)
        assert ">180s" in axonenv.probe_why(None, 180.0)


class TestReexecOnCpu:
    def test_reexec_scrubs_shim_and_sets_marker(self, monkeypatch):
        recorded = {}

        def fake_execve(path, argv, env):
            recorded.update(path=path, argv=argv, env=env)
            raise SystemExit(0)  # execve never returns; emulate that

        monkeypatch.setattr(os, "execve", fake_execve)
        monkeypatch.setenv("AXON_CHIP", "3")
        monkeypatch.setenv("PALLAS_AXON_MODE", "on")
        monkeypatch.setenv(
            "PYTHONPATH", f"/opt/.axon_site{os.pathsep}/keepme"
        )
        monkeypatch.delenv("_KSS_TEST_MARKER", raising=False)
        with pytest.raises(SystemExit):
            axonenv.reexec_on_cpu(
                "test", "_KSS_TEST_MARKER", ["python", "-m", "x"], "why"
            )
        env = recorded["env"]
        assert env["_KSS_TEST_MARKER"] == "1"
        assert env["JAX_PLATFORMS"] == "cpu"
        assert "AXON_CHIP" not in env
        assert "PALLAS_AXON_MODE" not in env
        assert ".axon_site" not in env["PYTHONPATH"]
        assert "/keepme" in env["PYTHONPATH"]
        assert recorded["argv"] == ["python", "-m", "x"]

    def test_marker_present_refuses_the_reexec_loop(self, monkeypatch):
        """The loop guard (the satellite bugfix): a probe that fails
        even on the scrubbed CPU re-exec must raise, not execve again
        forever."""
        monkeypatch.setenv("_KSS_TEST_MARKER", "1")
        called = {}
        monkeypatch.setattr(
            os, "execve", lambda *a: called.setdefault("execve", True)
        )
        with pytest.raises(RuntimeError, match="refusing a re-exec loop"):
            axonenv.reexec_on_cpu(
                "test", "_KSS_TEST_MARKER", ["python", "-m", "x"], "why"
            )
        assert "execve" not in called


class TestScrubbedCpuEnv:
    def test_virtual_devices_flag(self):
        env = axonenv.scrubbed_cpu_env(
            {"XLA_FLAGS": "--xla_force_host_platform_device_count=2 --keep"},
            virtual_devices=8,
        )
        assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
        assert "--keep" in env["XLA_FLAGS"]
        assert env["JAX_PLATFORMS"] == "cpu"


class TestLifecycleCliProbe:
    """The lifecycle CLI honors the serving shell's boot probe."""

    def _spec_file(self, tmp_path):
        from helpers import node, pod

        spec = {
            "name": "probe",
            "seed": 1,
            "horizon": 1.0,
            "snapshot": {"nodes": [node("n0")], "pods": [pod("p0")]},
            "faults": [{"at": 0.5, "action": "cordon", "node": "n0"}],
        }
        path = tmp_path / "spec.json"
        path.write_text(json.dumps(spec))
        return str(path)

    def test_wedged_probe_triggers_cpu_reexec(self, monkeypatch, tmp_path):
        from kube_scheduler_simulator_tpu.lifecycle.__main__ import (
            main as lifecycle_cli,
        )

        recorded = {}

        def fake_probe(timeout_s=axonenv.PROBE_TIMEOUT_S, get_devices=None):
            return [], None  # the wedged backend

        def fake_reexec(label, marker, argv, why):
            recorded.update(label=label, marker=marker, argv=argv, why=why)
            raise SystemExit(77)  # execve replaces the image; emulate

        monkeypatch.setattr(axonenv, "probe_devices", fake_probe)
        monkeypatch.setattr(axonenv, "reexec_on_cpu", fake_reexec)
        monkeypatch.delenv("_KSS_LIFECYCLE_CPU_FALLBACK", raising=False)
        with pytest.raises(SystemExit, match="77"):
            lifecycle_cli(["--spec", self._spec_file(tmp_path)])
        assert recorded["label"] == "lifecycle"
        assert recorded["marker"] == "_KSS_LIFECYCLE_CPU_FALLBACK"
        assert recorded["argv"][-2:] == ["--spec", self._spec_file(tmp_path)]
        assert "hung" in recorded["why"]

    def test_marker_skips_the_probe(self, monkeypatch, tmp_path, capsys):
        from kube_scheduler_simulator_tpu.lifecycle.__main__ import (
            main as lifecycle_cli,
        )

        def must_not_probe(*a, **k):  # pragma: no cover - the assertion
            raise AssertionError("probe ran despite the fallback marker")

        monkeypatch.setattr(axonenv, "probe_devices", must_not_probe)
        monkeypatch.setenv("_KSS_LIFECYCLE_CPU_FALLBACK", "1")
        rc = lifecycle_cli(["--spec", self._spec_file(tmp_path)])
        assert rc == 0

    def test_no_device_probe_flag_skips(self, monkeypatch, tmp_path):
        from kube_scheduler_simulator_tpu.lifecycle.__main__ import (
            main as lifecycle_cli,
        )

        def must_not_probe(*a, **k):  # pragma: no cover - the assertion
            raise AssertionError("probe ran despite --no-device-probe")

        monkeypatch.setattr(axonenv, "probe_devices", must_not_probe)
        monkeypatch.delenv("_KSS_LIFECYCLE_CPU_FALLBACK", raising=False)
        rc = lifecycle_cli(
            ["--no-device-probe", "--spec", self._spec_file(tmp_path)]
        )
        assert rc == 0
