"""Batch runner (KEP-159/184): scenario + sweep jobs, file-based in/out."""

import json

from kube_scheduler_simulator_tpu.scenario.batch import (
    BatchJob,
    load_jobs,
    run_batch,
)

from helpers import node, pod


def _scenario_spec():
    return {
        "kind": "scenario",
        "operations": [
            {"majorStep": 0, "create": {"kind": "nodes", "object": node("n0")}},
            {"majorStep": 0, "create": {"kind": "pods", "object": pod("p0")}},
            {"majorStep": 1, "done": True},
        ],
    }


def _sweep_spec():
    return {
        "kind": "sweep",
        "snapshot": {
            "nodes": [node(f"n{i}", cpu=str(2 + i)) for i in range(3)],
            "pods": [pod(f"p{i}", cpu="500m") for i in range(6)],
        },
        "schedulerConfig": {
            "profiles": [
                {
                    "schedulerName": "default-scheduler",
                    "plugins": {
                        "preFilter": {"disabled": [{"name": "*"}],
                                      "enabled": [{"name": "NodeResourcesFit"}]},
                        "filter": {"disabled": [{"name": "*"}],
                                   "enabled": [{"name": "NodeResourcesFit"}]},
                        "postFilter": {"disabled": [{"name": "*"}], "enabled": []},
                        "preScore": {
                            "disabled": [{"name": "*"}],
                            "enabled": [
                                {"name": "NodeResourcesFit"},
                                {"name": "NodeResourcesBalancedAllocation"},
                            ],
                        },
                        "score": {
                            "disabled": [{"name": "*"}],
                            "enabled": [
                                {"name": "NodeResourcesFit", "weight": 1},
                                {"name": "NodeResourcesBalancedAllocation",
                                 "weight": 1},
                            ],
                        },
                    },
                }
            ]
        },
        "weightVariants": [
            {},
            {"NodeResourcesFit": 10},
            {"NodeResourcesBalancedAllocation": 10},
        ],
    }


def test_scenario_job():
    job = BatchJob.from_spec("demo", _scenario_spec())
    results = run_batch([job])
    r = results["demo"]
    assert r["phase"] == "Succeeded"
    evs = [e["type"] for e in r["timeline"]["0"]]
    assert "Create" in evs and "PodScheduled" in evs


def test_sweep_job_runs_all_variants():
    job = BatchJob.from_spec("sweep", _sweep_spec())
    r = run_batch([job])["sweep"]
    assert r["phase"] == "Succeeded"
    assert len(r["variants"]) == 3
    for v in r["variants"]:
        assert v["scheduled"] == 6
        assert set(v["placements"]) == {f"default/p{i}" for i in range(6)}
    assert r["variants"][1]["weights"]["NodeResourcesFit"] == 10


def test_sweep_job_gang_engine():
    spec = _sweep_spec()
    spec["engine"] = "gang"
    r = run_batch([BatchJob.from_spec("gsweep", spec)])["gsweep"]
    assert r["phase"] == "Succeeded"
    assert len(r["variants"]) == 3
    for v in r["variants"]:
        assert v["scheduled"] == 6


def test_bad_engine_rejected():
    spec = _sweep_spec()
    spec["engine"] = "warp"
    import pytest

    with pytest.raises(ValueError, match="unknown engine"):
        BatchJob.from_spec("bad", spec)


def test_file_based_in_out(tmp_path):
    indir, outdir = tmp_path / "in", tmp_path / "out"
    indir.mkdir()
    (indir / "a.json").write_text(json.dumps(_scenario_spec()))
    (indir / "b.json").write_text(json.dumps(_sweep_spec()))
    (indir / "ignored.txt").write_text("not a spec")
    jobs = load_jobs(str(indir))
    assert [j.name for j in jobs] == ["a", "b"]
    results = run_batch(jobs, out_dir=str(outdir))
    assert (outdir / "a.result.json").exists()
    assert (outdir / "b.result.json").exists()
    on_disk = json.loads((outdir / "b.result.json").read_text())
    assert on_disk == results["b"]


def test_malformed_spec_isolated(tmp_path):
    indir = tmp_path / "in"
    indir.mkdir()
    (indir / "good.json").write_text(json.dumps(_scenario_spec()))
    (indir / "broken.json").write_text("{not json")
    (indir / "empty.yaml").write_text("")
    jobs = load_jobs(str(indir))
    assert [j.name for j in jobs] == ["broken", "empty", "good"]
    results = run_batch(jobs)
    assert results["good"]["phase"] == "Succeeded"
    assert results["broken"]["phase"] == "Failed"
    assert results["empty"]["phase"] == "Failed"


def test_parallel_batch_matches_sequential():
    jobs = [
        BatchJob.from_spec(f"j{i}", _scenario_spec()) for i in range(4)
    ]
    seq = run_batch(jobs)
    par = run_batch(
        [BatchJob.from_spec(f"j{i}", _scenario_spec()) for i in range(4)],
        max_workers=3,
    )
    assert {n: r["phase"] for n, r in par.items()} == {
        n: r["phase"] for n, r in seq.items()
    }


def test_failed_job_isolated():
    bad = BatchJob.from_spec(
        "bad",
        {
            "kind": "scenario",
            "operations": [
                {"majorStep": 0,
                 "delete": {"kind": "pods", "name": "ghost"}},
            ],
        },
    )
    good = BatchJob.from_spec("good", _scenario_spec())
    results = run_batch([bad, good])
    assert results["bad"]["phase"] == "Failed"
    assert results["good"]["phase"] == "Succeeded"
