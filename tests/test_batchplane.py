"""Cross-tenant continuous batching (server/batchplane.py).

The parity pin: with batching armed, every session's placements and
per-pod result records are BYTE-IDENTICAL to solo dispatch — the batch
plane may change throughput and latency, never an answer. Plus the
fairness/robustness contracts: a lone tenant never waits more than one
window, semaphore waiters can't deadlock against the window timer,
drain flushes partial windows, incompatible/recorded-gang/fault-scoped
passes fall back to solo (counted), and one batched device dispatch
lands spans / ledger attribution / latency observations on the correct
session — including when a session is deleted mid-batch.

Gang passes batch too (``batch.gang.run``, the vmapped fused
`gang.fixpoint`): that half of the contract — batched gang parity
(sync + async, preemption included), the mid-batch DELETE and
batched-failure fallbacks, and per-tenant ledger attribution of the
one gang window dispatch — lives in test_gang_batchplane.py, which
shares this file's fixtures. The gang counter plumbing
(gangFixpointRounds / batchedGangPasses) stays here with the other
counter round-trips.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from kube_scheduler_simulator_tpu.server.batchplane import (
    BATCH_SEQ_LABEL,
    BatchPlane,
    from_env,
)
from kube_scheduler_simulator_tpu.server.service import SimulatorService
from kube_scheduler_simulator_tpu.server.sessions import SessionManager
from kube_scheduler_simulator_tpu.utils import ledger as ledger_mod
from kube_scheduler_simulator_tpu.utils import metrics as metrics_mod
from kube_scheduler_simulator_tpu.utils import telemetry

from helpers import node, pod

N = 3


def _snapshot(i: int, preempt: bool = False) -> dict:
    """Session i's cluster: identical SHAPES (same counts, vocab, node
    pods-capacity — one compile signature for all) with per-session
    VALUES, so each tenant's placements differ while the batch key
    matches."""
    if preempt:
        return {
            "nodes": [node(f"n{j}", cpu="2") for j in range(2)],
            "pods": [
                pod("low-a", cpu="1500m", priority=1 + i, node_name="n0"),
                pod("low-b", cpu="1500m", priority=1, node_name="n1"),
                pod("high", cpu=f"{1200 + 100 * i}m", priority=100),
                pod("filler", cpu="300m", priority=50),
            ],
        }
    return {
        "nodes": [node(f"n{j}", cpu="16") for j in range(3)],
        "pods": [
            pod(f"p{j}", cpu=f"{100 + 100 * i + 50 * j}m") for j in range(4)
        ],
    }


def _results_doc(results) -> str:
    """One canonical byte string for a pass's full record set (status,
    placement, and all 13 result annotations)."""
    return json.dumps(
        [
            {
                "ns": r.pod_namespace,
                "name": r.pod_name,
                "status": r.status,
                "node": r.selected_node,
                "ann": r.to_annotations(),
            }
            for r in results
        ],
        sort_keys=True,
    )


def _manager(max_passes: int = 8) -> SessionManager:
    return SessionManager(
        SimulatorService(), max_sessions=16, max_concurrent_passes=max_passes
    )


def _armed_manager(
    window_ms: float = 5000.0,
    max_sessions: int = N,
    max_passes: int = 8,
    max_wait_ms: "float | None" = None,
) -> "tuple[SessionManager, BatchPlane]":
    mgr = _manager(max_passes)
    plane = BatchPlane(
        window_ms=window_ms,
        max_wait_ms=max_wait_ms,
        max_sessions=max_sessions,
        metrics=mgr.get("default").service.scheduler.metrics,
    )
    mgr.batch_plane = plane
    mgr.get("default").service.scheduler.batch_plane = plane
    return mgr, plane


def _solo_docs(n: int = N, preempt: bool = False) -> "dict[int, str]":
    mgr = _manager()
    docs = {}
    try:
        for i in range(n):
            sess, errs = mgr.create(name=f"solo{i}", snapshot=_snapshot(i, preempt))
            assert not errs
            docs[i] = _results_doc(sess.service.scheduler.schedule())
    finally:
        mgr.shutdown()
    return docs


def _concurrent_schedule(mgr, sessions, mode: str = "sync"):
    """Drive every session's pass concurrently (barrier-aligned so all
    enroll in one window — the window only flushes when full, so the
    batch composition is deterministic). Returns {i: results_doc}."""
    out, errors = {}, {}
    barrier = threading.Barrier(len(sessions))

    def run(i):
        try:
            barrier.wait(timeout=30)
            svc = sessions[i].service
            with mgr.pass_slot():
                if mode == "async":
                    handle = svc.scheduler.begin_pass()
                    handle.resolve()
                    out[i] = None
                else:
                    out[i] = _results_doc(svc.scheduler.schedule())
        except Exception as e:  # noqa: BLE001 — surfaced by the assert
            errors[i] = repr(e)

    threads = [
        threading.Thread(target=run, args=(i,)) for i in range(len(sessions))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not errors, errors
    assert len(out) == len(sessions)
    return out


class TestBatchedParity:
    def test_sync_parity_and_counters(self):
        solo = _solo_docs()
        mgr, _plane = _armed_manager()
        try:
            sessions = [
                mgr.create(name=f"t{i}", snapshot=_snapshot(i))[0]
                for i in range(N)
            ]
            out = _concurrent_schedule(mgr, sessions)
            for i in range(N):
                assert out[i] == solo[i], f"session {i} diverged from solo"
            # ONE window, filled by all N passes
            default_phases = (
                mgr.get("default").service.scheduler.metrics.snapshot()
            )
            assert default_phases["phases"]["batchWindows"] == 1
            assert default_phases["phases"]["batchOccupancySum"] == N
            assert default_phases["batching"]["batchOccupancy"] == float(N)
            for s in sessions:
                phases = s.service.scheduler.metrics.snapshot()["phases"]
                assert phases["batchedPasses"] == 1
                assert phases["soloFallbacks"] == 0
        finally:
            mgr.shutdown()

    def test_preemption_parity(self):
        """The masked preempt path under the batch vmap must reproduce
        the solo cond path's records bit-for-bit — victims, nominations,
        and the retry attempt included."""
        solo = _solo_docs(preempt=True)
        assert any('"Nominated"' in d for d in solo.values()), (
            "fixture must actually exercise preemption"
        )
        mgr, _plane = _armed_manager()
        try:
            sessions = [
                mgr.create(name=f"t{i}", snapshot=_snapshot(i, True))[0]
                for i in range(N)
            ]
            out = _concurrent_schedule(mgr, sessions)
            for i in range(N):
                assert out[i] == solo[i], f"session {i} diverged from solo"
        finally:
            mgr.shutdown()

    def test_async_begin_pass_parity(self):
        """begin_pass/resolve (the async pipeline's split) through the
        batch plane: store write-backs identical to solo."""
        # solo async baseline
        mgr1 = _manager()
        solo_pods = {}
        try:
            for i in range(N):
                sess, _ = mgr1.create(name=f"s{i}", snapshot=_snapshot(i))
                h = sess.service.scheduler.begin_pass()
                h.resolve()
                solo_pods[i] = json.dumps(
                    sess.service.store.list("pods"), sort_keys=True
                )
        finally:
            mgr1.shutdown()
        mgr2, _plane = _armed_manager()
        try:
            sessions = [
                mgr2.create(name=f"t{i}", snapshot=_snapshot(i))[0]
                for i in range(N)
            ]
            _concurrent_schedule(mgr2, sessions, mode="async")
            for i, s in enumerate(sessions):
                got = json.dumps(s.service.store.list("pods"), sort_keys=True)
                assert got == solo_pods[i], f"session {i} store diverged"
                assert (
                    s.service.scheduler.metrics.snapshot()["phases"][
                        "batchedPasses"
                    ]
                    == 1
                )
        finally:
            mgr2.shutdown()


class TestFallbacks:
    def test_recorded_gang_pass_falls_back_solo(self):
        """record=True gang passes keep today's solo dispatch with the
        plane armed (their trace replay is per-session host work by
        design) — counted, never enrolled in a window, and the full
        result-record bytes stay identical to an unarmed manager."""
        solo_mgr = _manager()
        try:
            s, _ = solo_mgr.create(name="g0", snapshot=_snapshot(0))
            solo_placements, _, solo_results = (
                s.service.scheduler.schedule_gang()
            )
            solo_doc = _results_doc(solo_results)
        finally:
            solo_mgr.shutdown()
        # a small window: a wrongly-enrolled record pass would still
        # flush, but the counter pin below would catch it
        mgr, _plane = _armed_manager(window_ms=50.0)
        try:
            sess, _ = mgr.create(name="g", snapshot=_snapshot(0))
            placements, rounds, results = sess.service.scheduler.schedule_gang()
            assert placements == solo_placements
            assert _results_doc(results) == solo_doc
            phases = sess.service.scheduler.metrics.snapshot()["phases"]
            assert phases["soloFallbacks"] == 1
            assert phases["batchedPasses"] == 0
            assert phases["batchedGangPasses"] == 0
            default = mgr.get("default").service.scheduler.metrics
            assert default.snapshot()["phases"]["batchWindows"] == 0
        finally:
            mgr.shutdown()

    def test_fault_scoped_session_falls_back_solo(self):
        """A session with its own fault plane is a bulkhead: its passes
        never share a device dispatch with other tenants."""
        mgr, _plane = _armed_manager(window_ms=50.0)
        try:
            sess, _ = mgr.create(
                name="f",
                snapshot=_snapshot(0),
                fault_inject="compile_slow:0s",
            )
            results = sess.service.scheduler.schedule()
            assert results
            phases = sess.service.scheduler.metrics.snapshot()["phases"]
            assert phases["soloFallbacks"] == 1
            assert phases["batchedPasses"] == 0
        finally:
            mgr.shutdown()

    def test_incompatible_shapes_never_share_a_window(self):
        """Different compile signatures (different node-capacity bucket)
        key different windows: both sessions complete, neither batches
        with the other."""
        mgr, _plane = _armed_manager(window_ms=150.0, max_sessions=4)
        try:
            a, _ = mgr.create(name="a", snapshot=_snapshot(0))
            big = {
                "nodes": [node(f"n{j}", cpu="16") for j in range(12)],
                "pods": [pod(f"p{j}", cpu="100m") for j in range(4)],
            }
            b, _ = mgr.create(name="b", snapshot=big)
            out, errors = {}, {}
            barrier = threading.Barrier(2)

            def run(key, sess):
                try:
                    barrier.wait(timeout=30)
                    with mgr.pass_slot():
                        out[key] = _results_doc(sess.service.scheduler.schedule())
                except Exception as e:  # noqa: BLE001
                    errors[key] = repr(e)

            ts = [
                threading.Thread(target=run, args=("a", a)),
                threading.Thread(target=run, args=("b", b)),
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=180)
            assert not errors, errors
            for sess in (a, b):
                phases = sess.service.scheduler.metrics.snapshot()["phases"]
                assert phases["batchedPasses"] == 0
                assert phases["soloFallbacks"] == 1
        finally:
            mgr.shutdown()
class TestFairnessAndLiveness:
    def test_lone_tenant_bounded_by_one_window(self):
        """A lone tenant's pass waits at most ~one window before the
        solo fallback serves it warm."""
        mgr, plane = _armed_manager(window_ms=150.0, max_sessions=4)
        try:
            sess, _ = mgr.create(name="lone", snapshot=_snapshot(0))
            # warm-up: first pass pays the window AND the solo compile
            sess.service.scheduler.schedule()
            # re-pend the pods and measure the steady-state pass
            for p in _snapshot(0)["pods"]:
                nm = p["metadata"]["name"]
                sess.service.store.delete("pods", nm, "default")
            sess.service.import_({"pods": _snapshot(0)["pods"]})
            t0 = time.monotonic()
            results = sess.service.scheduler.schedule()
            elapsed = time.monotonic() - t0
            assert results
            # one 150 ms window + a warm solo pass; generous CI slack
            assert elapsed < 2.0, f"lone tenant waited {elapsed:.2f}s"
            phases = sess.service.scheduler.metrics.snapshot()["phases"]
            assert phases["soloFallbacks"] == 2
            assert phases["batchedPasses"] == 0
            default = mgr.get("default").service.scheduler.metrics
            assert default.snapshot()["phases"]["batchWindows"] == 0
        finally:
            mgr.shutdown()

    def test_max_wait_caps_the_window(self):
        plane = BatchPlane(window_ms=60000.0, max_wait_ms=100.0)
        assert plane.wait_s == pytest.approx(0.1)
        plane2 = BatchPlane(window_ms=50.0)
        assert plane2.wait_s == pytest.approx(0.05)

    def test_semaphore_waiters_do_not_deadlock_on_the_window(self):
        """KSS_MAX_CONCURRENT_PASSES=1: the second session's pass queues
        on the semaphore while the first sits out its window — the
        window MUST flush on its timer (never wait for a quorum the
        semaphore is blocking), so both complete."""
        mgr, _plane = _armed_manager(
            window_ms=200.0, max_sessions=4, max_passes=1
        )
        try:
            sessions = [
                mgr.create(name=f"t{i}", snapshot=_snapshot(i))[0]
                for i in range(2)
            ]
            # warm up the solo program OUTSIDE the timed section (the
            # lone-tenant test's pattern): the deadlock wall below must
            # measure window/semaphore interaction, not a cold compile
            # on a loaded 1-core CI box
            for i, sess in enumerate(sessions):
                sess.service.scheduler.schedule()
                for p in _snapshot(i)["pods"]:
                    sess.service.store.delete(
                        "pods", p["metadata"]["name"], "default"
                    )
                sess.service.import_({"pods": _snapshot(i)["pods"]})
            done, errors = [], {}

            def run(i):
                try:
                    # serialize on the slot like the HTTP layer: retry
                    # the 503-shaped shed until a slot frees
                    for _ in range(400):
                        try:
                            with mgr.pass_slot():
                                sessions[i].service.scheduler.schedule()
                            done.append(i)
                            return
                        except Exception as e:  # noqa: BLE001
                            if "concurrent-pass" not in str(e):
                                raise
                            time.sleep(0.02)
                    errors[i] = "never got a slot"
                except Exception as e:  # noqa: BLE001
                    errors[i] = repr(e)

            ts = [threading.Thread(target=run, args=(i,)) for i in range(2)]
            t0 = time.monotonic()
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=120)
            assert not errors, errors
            assert sorted(done) == [0, 1]
            assert time.monotonic() - t0 < 60
        finally:
            mgr.shutdown()

    def test_drain_flushes_a_partial_window(self):
        """A pass sitting out a long window must be flushed by drain —
        the drain path can't afford to sit out collection windows, and
        new enrollments shed straight to solo."""
        mgr, plane = _armed_manager(window_ms=30000.0, max_sessions=4)
        try:
            sess, _ = mgr.create(name="d", snapshot=_snapshot(0))
            # pre-warm the solo program so the flushed pass is fast
            plane.begin_drain()  # temporarily shed to warm solo
            sess.service.scheduler.schedule()
            with plane._lock:
                plane._draining = False  # re-arm for the real assertion
            for p in _snapshot(0)["pods"]:
                sess.service.store.delete(
                    "pods", p["metadata"]["name"], "default"
                )
            sess.service.import_({"pods": _snapshot(0)["pods"]})

            state = {}

            def run():
                t0 = time.monotonic()
                with mgr.pass_slot():
                    sess.service.scheduler.schedule()
                state["elapsed"] = time.monotonic() - t0

            th = threading.Thread(target=run)
            th.start()
            time.sleep(0.4)  # let the pass enroll and sit in its window
            result = mgr.drain(deadline_s=30)
            th.join(timeout=30)
            assert "elapsed" in state, "drain left the enrolled pass stuck"
            assert state["elapsed"] < 10.0, state
            assert "d" not in result.get("errors", {})
        finally:
            mgr.shutdown()


class TestAttribution:
    def test_one_dispatch_attributes_to_every_tenant(self, monkeypatch):
        """One batched device dispatch serving N pass ids must land the
        ledger call attribution, telemetry spans, and latency
        observations on the correct sessions."""
        monkeypatch.setenv("KSS_PROGRAM_LEDGER", "1")
        ledger_mod.LEDGER.reset()
        recorder = telemetry.SpanRecorder(8192)
        telemetry.activate(recorder)
        try:
            mgr, _plane = _armed_manager()
            try:
                sessions = [
                    mgr.create(name=f"t{i}", snapshot=_snapshot(i))[0]
                    for i in range(N)
                ]
                sids = [s.id for s in sessions]
                _concurrent_schedule(mgr, sessions)
                # -- ledger: ONE device dispatch, N tenants attributed
                recs = [
                    rec
                    for rec in ledger_mod.LEDGER.snapshot()["programs"]
                    if rec["label"] == BATCH_SEQ_LABEL
                ]
                assert len(recs) == 1
                assert recs[0]["calls"] == 1
                for sid in sids:
                    assert sid in recs[0]["sessions"], (
                        f"{sid} missing from {recs[0]['sessions']}"
                    )
                # passes served == window fill
                assert sum(recs[0]["sessions"].values()) == N
                # -- spans: every session's pass spans carry its id
                events = recorder.snapshot()
                span_sessions = {
                    e["args"].get("session")
                    for e in events
                    if e.get("name", "").startswith("pass.sequential")
                    and e.get("args")
                }
                for sid in sids:
                    assert sid in span_sessions
                assert any(
                    e.get("name") == "batch.execute" for e in events
                )
                # -- per-session latency observation (the SLO plane's
                # passLatency signal reads this histogram)
                for s in sessions:
                    snap = s.service.scheduler.metrics.snapshot()
                    hist = snap["histograms"]["passLatencySeconds"]
                    assert hist["count"] == 1
                # -- DELETE purges the dead tenant's attribution
                mgr.delete(sids[0])
                recs = [
                    rec
                    for rec in ledger_mod.LEDGER.snapshot()["programs"]
                    if rec["label"] == BATCH_SEQ_LABEL
                ]
                assert sids[0] not in recs[0]["sessions"]
                for sid in sids[1:]:
                    assert sid in recs[0]["sessions"]
            finally:
                mgr.shutdown()
        finally:
            ledger_mod.LEDGER.reset()
            telemetry.deactivate()

    def test_mid_batch_session_delete(self):
        """A session DELETEd while its pass waits in a window: the pass
        still completes (write-backs land on the orphaned store), and
        every other enrollee's results stay byte-identical to solo."""
        solo = _solo_docs(2)
        # max_sessions=3 so a 2-enrollee window stays OPEN (timer flush)
        mgr, _plane = _armed_manager(window_ms=1500.0, max_sessions=3)
        try:
            a, _ = mgr.create(name="a", snapshot=_snapshot(0))
            b, _ = mgr.create(name="b", snapshot=_snapshot(1))
            out, errors = {}, {}
            barrier = threading.Barrier(3)

            def run(i, sess):
                try:
                    barrier.wait(timeout=30)
                    with mgr.pass_slot():
                        out[i] = _results_doc(sess.service.scheduler.schedule())
                except Exception as e:  # noqa: BLE001
                    errors[i] = repr(e)

            def deleter():
                barrier.wait(timeout=30)
                time.sleep(0.2)  # mid-window: both passes enrolled
                mgr.delete(b.id)

            ts = [
                threading.Thread(target=run, args=(0, a)),
                threading.Thread(target=run, args=(1, b)),
                threading.Thread(target=deleter),
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=120)
            assert not errors, errors
            assert out[0] == solo[0]
            assert out[1] == solo[1]  # the orphaned pass still answered
            with pytest.raises(Exception):
                mgr.get(b.id)
        finally:
            mgr.shutdown()


class TestPlumbing:
    def test_from_env(self, monkeypatch):
        monkeypatch.delenv("KSS_BATCH", raising=False)
        assert from_env() is None
        monkeypatch.setenv("KSS_BATCH", "1")
        plane = from_env()
        assert plane is not None
        assert plane.window_s == pytest.approx(0.005)
        assert plane.max_sessions == 8
        monkeypatch.setenv("KSS_BATCH_WINDOW_MS", "25")
        monkeypatch.setenv("KSS_BATCH_MAX_WAIT_MS", "10")
        monkeypatch.setenv("KSS_BATCH_MAX_SESSIONS", "4")
        plane = from_env()
        assert plane.window_s == pytest.approx(0.025)
        assert plane.wait_s == pytest.approx(0.010)
        assert plane.max_sessions == 4
        # malformed values fall back (boot-time envcheck is the strict
        # gate; library reads must not take the stack down)
        monkeypatch.setenv("KSS_BATCH_WINDOW_MS", "nope")
        assert from_env().window_s == pytest.approx(0.005)

    def test_session_manager_arms_from_env(self, monkeypatch):
        monkeypatch.setenv("KSS_BATCH", "1")
        mgr = _manager()
        try:
            assert mgr.batch_plane is not None
            assert (
                mgr.get("default").service.scheduler.batch_plane
                is mgr.batch_plane
            )
            sess, _ = mgr.create(name="t")
            assert sess.service.scheduler.batch_plane is mgr.batch_plane
            assert mgr.stats()["batching"]["armed"] is True
        finally:
            mgr.shutdown()

    def test_stats_unarmed(self):
        mgr = _manager()
        try:
            assert mgr.stats()["batching"] == {"armed": False}
        finally:
            mgr.shutdown()

    def test_batching_counters_roundtrip(self):
        m = metrics_mod.SchedulingMetrics()
        m.record_batching(batched_passes=3, windows=2, occupancy=5,
                          solo_fallbacks=1)
        snap = m.snapshot()
        assert snap["phases"]["batchedPasses"] == 3
        assert snap["phases"]["batchWindows"] == 2
        assert snap["phases"]["batchOccupancySum"] == 5
        assert snap["phases"]["soloFallbacks"] == 1
        assert snap["batching"]["batchOccupancy"] == 2.5
        # checkpoint round trip
        m2 = metrics_mod.SchedulingMetrics()
        m2.load_state(m.state_dict())
        assert m2.snapshot()["phases"]["batchOccupancySum"] == 5
        # exposition round trip through the strict parser
        text = metrics_mod.render_prometheus(snap)
        fams = metrics_mod.parse_prometheus_text(text)
        for name, want in (
            ("kss_batched_passes_total", 3),
            ("kss_batch_windows_total", 2),
            ("kss_batch_occupancy_total", 5),
            ("kss_solo_fallbacks_total", 1),
        ):
            samples = fams[name]["samples"]
            assert samples and samples[0][2] == want

    def test_gang_counters_roundtrip(self):
        m = metrics_mod.SchedulingMetrics()
        m.record_gang(fixpoint_rounds=7, batched_passes=2)
        m.record_gang(fixpoint_rounds=3)
        snap = m.snapshot()
        assert snap["phases"]["gangFixpointRounds"] == 10
        assert snap["phases"]["batchedGangPasses"] == 2
        # checkpoint round trip
        m2 = metrics_mod.SchedulingMetrics()
        m2.load_state(m.state_dict())
        assert m2.snapshot()["phases"]["gangFixpointRounds"] == 10
        assert m2.snapshot()["phases"]["batchedGangPasses"] == 2
        # exposition round trip through the strict parser
        text = metrics_mod.render_prometheus(snap)
        fams = metrics_mod.parse_prometheus_text(text)
        for name, want in (
            ("kss_gang_fixpoint_rounds_total", 10),
            ("kss_batched_gang_passes_total", 2),
        ):
            samples = fams[name]["samples"]
            assert samples and samples[0][2] == want
        m.reset()
        phases = m.snapshot()["phases"]
        assert phases["gangFixpointRounds"] == 0
        assert phases["batchedGangPasses"] == 0
