"""bench.py's wedge-containment contract (VERDICT r4 #3).

The round-4 postmortem: killing one in-flight axon compile wedged the
TPU tunnel for the rest of the session and cost the round its benchmark
artifact (BASELINE.md round-4 session log). The contract under test:

  * no code path in bench.py may SIGKILL a child that may hold an axon
    compile — a timed-out DEVICE probe abandons its child and flips a
    persistent wedge marker instead;
  * every later device probe reads the marker and skips (CPU probes are
    unaffected, and ARE killed on timeout — nothing a CPU process holds
    can wedge anything);
  * the marker ages out (wedges outlast sessions, not days) and is
    cleared when a device probe succeeds again.

bench.py is loaded from the repo root by path (it is a script, not a
package module).
"""

from __future__ import annotations

import importlib.util
import json
import os
import time

import pytest

_BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


@pytest.fixture()
def bench(monkeypatch, tmp_path):
    spec = importlib.util.spec_from_file_location("_bench_under_test", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # isolate the marker: these tests must never clobber (or be confused
    # by) a real wedge marker left by an actual chip campaign
    monkeypatch.setattr(mod, "TUNNEL_MARKER", str(tmp_path / "wedged.json"))
    return mod


class TestWedgeMarker:
    def test_roundtrip(self, bench):
        assert bench._tunnel_wedged_since() is None
        bench._mark_tunnel_wedged("--gang-probe=static bench")
        since = bench._tunnel_wedged_since()
        assert since is not None and abs(since - time.time()) < 5.0
        bench._clear_tunnel_marker()
        assert bench._tunnel_wedged_since() is None

    def test_keeps_oldest_since(self, bench):
        bench._mark_tunnel_wedged("first")
        first = bench._tunnel_wedged_since()
        bench._mark_tunnel_wedged("second")
        assert bench._tunnel_wedged_since() == pytest.approx(first)
        with open(bench.TUNNEL_MARKER) as f:
            assert json.load(f)["class"] == "second"

    def test_fresh_evidence_renews_ttl(self, bench):
        """A new wedge event near an old marker's TTL edge must renew the
        skip protection (staleness gates on `last`, not `since`)."""
        old = time.time() - bench.TUNNEL_MARKER_TTL_S + 30
        with open(bench.TUNNEL_MARKER, "w") as f:
            json.dump({"since": old, "last": old}, f)
        bench._mark_tunnel_wedged("fresh evidence")
        with open(bench.TUNNEL_MARKER) as f:
            data = json.load(f)
        assert data["since"] == pytest.approx(old)  # honesty preserved
        assert time.time() - data["last"] < 5.0  # clock renewed
        assert bench._tunnel_wedged_since() == pytest.approx(old)

    def test_stale_marker_ignored(self, bench):
        with open(bench.TUNNEL_MARKER, "w") as f:
            json.dump(
                {"since": time.time() - bench.TUNNEL_MARKER_TTL_S - 60}, f
            )
        assert bench._tunnel_wedged_since() is None

    def test_garbage_marker_ignored(self, bench):
        with open(bench.TUNNEL_MARKER, "w") as f:
            f.write("not json")
        assert bench._tunnel_wedged_since() is None


class TestProbeContainment:
    def test_device_timeout_abandons_child_and_marks(self, bench, tmp_path):
        """A timed-out device probe must NOT kill its child (the child
        may hold an in-flight axon compile): the child's post-sleep
        touch file appearing after the window proves it survived."""
        touch = tmp_path / "survived.txt"
        t0 = time.time()
        out = bench._probe_json_subprocess(
            [f"--probe-sleep=3:{touch}"], 1.0, "probe_sleep_done", device=True
        )
        assert out is None
        assert time.time() - t0 < 3.0  # returned at the window, no wait
        assert bench._tunnel_wedged_since() is not None
        with open(bench.TUNNEL_MARKER) as f:
            assert "--probe-sleep" in json.load(f)["class"]
        deadline = time.time() + 15.0
        while not touch.exists() and time.time() < deadline:
            time.sleep(0.2)
        assert touch.exists(), "abandoned child was killed (or never ran)"

    def test_device_timeout_banks_measurement_printed_before_hang(
        self, bench, tmp_path
    ):
        """A probe that measured, printed its line, and THEN hung (e.g.
        in a post-measurement telemetry compile) must not lose the
        number: the parent recovers it from the temp file, explicitly
        marked, and the wedge marker still flips."""
        # window must cover interpreter+sitecustomize startup (~2.5s
        # idle, much worse under parallel test load) so the child
        # reaches its print before the parent's timeout; the sleep then
        # models the hang
        out = bench._probe_json_subprocess(
            ["--probe-sleep=30", "--probe-emit-first"],
            10.0,
            "probe_sleep_done",
            device=True,
        )
        assert out == {
            "probe_sleep_done": True,
            "banked_before_timeout": True,
        }
        assert bench._tunnel_wedged_since() is not None

    def test_cpu_timeout_kills_child(self, bench, tmp_path):
        """CPU probes keep the kill: nothing they hold can wedge, and
        orphan CPU processes must not pile up."""
        touch = tmp_path / "survived.txt"
        out = bench._probe_json_subprocess(
            [f"--probe-sleep=3:{touch}"], 1.0, "probe_sleep_done", device=False
        )
        assert out is None
        assert bench._tunnel_wedged_since() is None
        time.sleep(4.0)
        assert not touch.exists(), "CPU child should have been killed"

    def test_device_probe_skips_while_marker_active(self, bench, tmp_path):
        bench._mark_tunnel_wedged("earlier probe")
        t0 = time.time()
        out = bench._probe_json_subprocess(
            [f"--probe-sleep=0:{tmp_path / 'x'}"],
            30.0,
            "probe_sleep_done",
            device=True,
        )
        assert out is None and time.time() - t0 < 1.0

    def test_cpu_probe_ignores_marker(self, bench, tmp_path):
        bench._mark_tunnel_wedged("earlier probe")
        out = bench._probe_json_subprocess(
            ["--probe-sleep=0"], 30.0, "probe_sleep_done", device=False
        )
        assert out == {"probe_sleep_done": True}

    def test_success_returns_last_json_line(self, bench):
        out = bench._probe_json_subprocess(
            ["--probe-sleep=0"], 30.0, "probe_sleep_done", device=False
        )
        assert out == {"probe_sleep_done": True}
