"""utils/compilecache.py: the default-directory resolution — repo-local
when the checkout is writable, per-user fallback when not (site-packages
installs, ADVICE r5)."""

import os

from kube_scheduler_simulator_tpu.utils import compilecache


def test_writable_root_uses_repo_local_dir(tmp_path):
    assert compilecache.default_cache_dir(str(tmp_path)) == str(
        tmp_path / ".jax_cache"
    )


def test_unwritable_root_falls_back_to_user_cache(tmp_path):
    ro = tmp_path / "ro"
    ro.mkdir()
    ro.chmod(0o555)
    try:
        got = compilecache.default_cache_dir(str(ro))
    finally:
        ro.chmod(0o755)
    expect = os.path.join(os.path.expanduser("~"), ".cache", "kss-jax")
    # root runs bypass permission bits; accept either resolution there
    if os.access(str(ro), os.W_OK):
        assert got == str(ro / ".jax_cache")
    else:
        assert got == expect


def test_missing_root_falls_back_to_user_cache(tmp_path):
    assert compilecache.default_cache_dir(
        str(tmp_path / "nope")
    ) == os.path.join(os.path.expanduser("~"), ".cache", "kss-jax")


def test_env_override_wins(monkeypatch):
    seen = {}

    class _Cfg:
        @staticmethod
        def update(key, value):
            seen[key] = value

    import types

    fake_jax = types.SimpleNamespace(config=_Cfg())
    monkeypatch.setitem(
        __import__("sys").modules, "jax", fake_jax
    )
    monkeypatch.setenv("KSS_JAX_CACHE_DIR", "/tmp/elsewhere")
    compilecache.enable_compile_cache()
    assert seen["jax_compilation_cache_dir"] == "/tmp/elsewhere"
