from kube_scheduler_simulator_tpu.sched.config import (
    SchedulerConfiguration,
    convert_plugins_for_simulator,
    default_plugins,
    merge_plugin_set,
    new_plugin_config,
)

# The full default plugin list pinned by the reference's golden test
# (simulator/scheduler/plugin/plugins_test.go:852-884).
GOLDEN_REGISTERED = [
    ("NodeResourcesBalancedAllocation", 1),
    ("ImageLocality", 1),
    ("InterPodAffinity", 1),
    ("NodeResourcesFit", 1),
    ("NodeAffinity", 1),
    ("PodTopologySpread", 2),
    ("TaintToleration", 1),
    ("DefaultBinder", None),
    ("VolumeBinding", None),
    ("NodePorts", None),
    ("VolumeRestrictions", None),
    ("NodeUnschedulable", None),
    ("NodeName", None),
    ("EBSLimits", None),
    ("GCEPDLimits", None),
    ("NodeVolumeLimits", None),
    ("AzureDiskLimits", None),
    ("VolumeZone", None),
    ("DefaultPreemption", None),
]


def test_golden_registered_plugin_set():
    """Union of score + other default plugins matches the reference golden list."""
    d = default_plugins()
    seen = []
    for p in d["score"]:
        seen.append((p["name"], p.get("weight")))
    for ep in ("bind", "reserve", "preFilter", "filter", "postFilter"):
        for p in d[ep]:
            if all(p["name"] != n for n, _ in seen):
                seen.append((p["name"], p.get("weight")))
    assert set(seen) == set(GOLDEN_REGISTERED)


def test_merge_disable_star():
    merged = merge_plugin_set(default_plugins()["filter"], {"disabled": [{"name": "*"}]})
    assert merged == []


def test_merge_disable_one():
    merged = merge_plugin_set(
        default_plugins()["filter"], {"disabled": [{"name": "NodeResourcesFit"}]}
    )
    names = [p["name"] for p in merged]
    assert "NodeResourcesFit" not in names
    assert "NodeName" in names


def test_merge_replace_in_place_preserves_order():
    defaults = default_plugins()["score"]
    merged = merge_plugin_set(defaults, {"enabled": [{"name": "NodeResourcesFit", "weight": 5}]})
    names = [p["name"] for p in merged]
    # order unchanged, weight replaced
    assert names == [p["name"] for p in defaults]
    fit = next(p for p in merged if p["name"] == "NodeResourcesFit")
    assert fit["weight"] == 5


def test_merge_appends_custom():
    merged = merge_plugin_set(
        default_plugins()["score"], {"enabled": [{"name": "MyPlugin", "weight": 3}]}
    )
    assert merged[-1] == {"name": "MyPlugin", "weight": 3}


def test_convert_disables_star_everywhere():
    out = convert_plugins_for_simulator(None)
    for ep, ps in out.items():
        assert ps["disabled"] == [{"name": "*"}]


def test_plugin_config_defaults_and_override():
    pc = new_plugin_config(None)
    by_name = {p["name"]: p["args"] for p in pc}
    assert by_name["DefaultPreemption"]["minCandidateNodesPercentage"] == 10
    assert by_name["InterPodAffinity"]["hardPodAffinityWeight"] == 1
    assert by_name["NodeResourcesFit"]["scoringStrategy"]["type"] == "LeastAllocated"
    assert by_name["VolumeBinding"]["bindTimeoutSeconds"] == 600

    pc2 = new_plugin_config(
        [
            {"name": "InterPodAffinity", "args": {"hardPodAffinityWeight": 7}},
            {"name": "Custom", "args": {"x": 1}},
        ]
    )
    by_name2 = {p["name"]: p["args"] for p in pc2}
    assert by_name2["InterPodAffinity"]["hardPodAffinityWeight"] == 7
    # untouched defaults survive the override
    assert by_name2["InterPodAffinity"]["kind"] == "InterPodAffinityArgs"
    assert by_name2["Custom"] == {"x": 1}


def test_from_yaml_only_profiles_honored():
    cfg = SchedulerConfiguration.from_yaml(
        """
apiVersion: kubescheduler.config.k8s.io/v1beta2
kind: KubeSchedulerConfiguration
parallelism: 999
profiles:
  - schedulerName: my-sched
    plugins:
      score:
        disabled:
          - name: "*"
        enabled:
          - name: NodeResourcesFit
            weight: 10
"""
    )
    # non-profile field forced back to default
    assert cfg.raw["parallelism"] == 16
    assert cfg.score_plugins("my-sched") == [("NodeResourcesFit", 10)]
    # filter set untouched by score changes
    assert "PodTopologySpread" in cfg.enabled("filter", "my-sched")


def test_empty_config_gets_default_profile():
    cfg = SchedulerConfiguration.default()
    assert cfg.score_plugins() == [
        ("NodeResourcesBalancedAllocation", 1),
        ("ImageLocality", 1),
        ("InterPodAffinity", 1),
        ("NodeResourcesFit", 1),
        ("NodeAffinity", 1),
        ("PodTopologySpread", 2),
        ("TaintToleration", 1),
    ]
    assert cfg.enabled("postFilter") == ["DefaultPreemption"]
    assert cfg.enabled("queueSort") == ["PrioritySort"]


def test_bad_kind_rejected():
    import pytest

    with pytest.raises(ValueError):
        SchedulerConfiguration.from_yaml("kind: Deployment")
