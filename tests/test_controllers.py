"""Controller step functions: deterministic reconcile over the store
(reference: the kube-controller-manager subset,
simulator/controller/controller.go:77-86)."""

import pytest

from kube_scheduler_simulator_tpu.controllers import (
    pv_controller_step,
    replicaset_controller_step,
    run_to_fixpoint,
)
from kube_scheduler_simulator_tpu.models import ResourceStore


def deployment(name, replicas, labels=None, cpu="100m", ns="default"):
    labels = labels or {"app": name}
    return {
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "containers": [
                        {"name": "c", "resources": {"requests": {"cpu": cpu}}}
                    ]
                },
            },
        },
    }


def pvc(name, storage="1Gi", sc=""):
    return {
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "storageClassName": sc,
            "resources": {"requests": {"storage": storage}},
        },
    }


def pv(name, capacity="1Gi", sc=""):
    return {
        "metadata": {"name": name},
        "spec": {
            "storageClassName": sc,
            "capacity": {"storage": capacity},
            "accessModes": ["ReadWriteOnce"],
        },
    }


class TestDeploymentReplicaSet:
    def test_expansion_to_pods(self):
        store = ResourceStore()
        store.apply("deployments", deployment("web", 3))
        rounds = run_to_fixpoint(store)
        assert rounds >= 2
        rses = store.list("replicasets")
        assert len(rses) == 1
        assert rses[0]["spec"]["replicas"] == 3
        pods = sorted(p["metadata"]["name"] for p in store.list("pods"))
        rs_name = rses[0]["metadata"]["name"]
        assert pods == [f"{rs_name}-{i}" for i in range(3)]
        # template labels propagate to pods
        assert all(
            p["metadata"]["labels"] == {"app": "web"} for p in store.list("pods")
        )

    def test_scale_down_deletes_highest_ordinals(self):
        store = ResourceStore()
        store.apply("deployments", deployment("web", 4))
        run_to_fixpoint(store)
        store.apply(
            "deployments",
            {"metadata": {"name": "web", "namespace": "default"},
             "spec": {"replicas": 2}},
        )
        run_to_fixpoint(store)
        pods = sorted(p["metadata"]["name"] for p in store.list("pods"))
        rs_name = store.list("replicasets")[0]["metadata"]["name"]
        assert pods == [f"{rs_name}-0", f"{rs_name}-1"]

    def test_template_change_replaces_replicaset(self):
        store = ResourceStore()
        store.apply("deployments", deployment("web", 2, cpu="100m"))
        run_to_fixpoint(store)
        old_rs = store.list("replicasets")[0]["metadata"]["name"]
        store.apply("deployments", deployment("web", 2, cpu="200m"))
        run_to_fixpoint(store)
        rses = store.list("replicasets")
        assert len(rses) == 1 and rses[0]["metadata"]["name"] != old_rs
        for p in store.list("pods"):
            req = p["spec"]["containers"][0]["resources"]["requests"]
            assert req["cpu"] == "200m"

    def test_orphaned_pods_left_alone_no_ambient_gc(self):
        """Pods carrying ownerReferences to an absent ReplicaSet must
        survive reconciles (the reference's controller subset runs no
        garbage collector; ambient GC destroyed imported snapshots)."""
        store = ResourceStore()
        store.apply(
            "pods",
            {
                "metadata": {
                    "name": "adopted",
                    "namespace": "default",
                    "ownerReferences": [
                        {"kind": "ReplicaSet", "name": "long-gone"}
                    ],
                },
                "spec": {"containers": [{"name": "c"}]},
            },
        )
        run_to_fixpoint(store)
        assert store.get("pods", "adopted") is not None

    def test_delete_deployment_cascades_via_store(self):
        store = ResourceStore()
        store.apply("deployments", deployment("web", 2))
        run_to_fixpoint(store)
        assert len(store.list("pods")) == 2
        store.delete("deployments", "web", "default")
        assert store.list("replicasets") == []
        assert store.list("pods") == []

    def test_malformed_replicas_skipped(self):
        store = ResourceStore()
        d = deployment("bad", 2)
        d["spec"]["replicas"] = None
        store.apply("deployments", d)
        rounds = run_to_fixpoint(store)  # must not raise
        assert rounds >= 1
        assert store.list("replicasets") == []
        # string digits are tolerated (YAML hand-edits)
        d2 = deployment("ok", 2)
        d2["spec"]["replicas"] = "2"
        store.apply("deployments", d2)
        run_to_fixpoint(store)
        assert len(store.list("pods")) == 2

    def test_determinism_two_runs_identical(self):
        def run():
            store = ResourceStore()
            store.apply("deployments", deployment("a", 3))
            store.apply("deployments", deployment("b", 2))
            run_to_fixpoint(store)
            return sorted(
                (p["metadata"]["name"],
                 tuple(sorted(p["metadata"].get("labels", {}).items())))
                for p in store.list("pods")
            )

        assert run() == run()


class TestPVController:
    def test_binds_smallest_adequate(self):
        store = ResourceStore()
        store.apply("pvs", pv("big", "10Gi"))
        store.apply("pvs", pv("small", "2Gi"))
        store.apply("pvcs", pvc("claim", "1Gi"))
        assert pv_controller_step(store) is True
        got_pvc = store.get("pvcs", "claim")
        assert got_pvc["spec"]["volumeName"] == "small"
        assert got_pvc["status"]["phase"] == "Bound"
        got_pv = store.get("pvs", "small")
        assert got_pv["spec"]["claimRef"]["name"] == "claim"
        assert got_pv["status"]["phase"] == "Bound"
        # second round: nothing left to do
        assert pv_controller_step(store) is False

    def test_two_claims_do_not_share_a_pv(self):
        store = ResourceStore()
        store.apply("pvs", pv("only", "5Gi"))
        store.apply("pvcs", pvc("c1", "1Gi"))
        store.apply("pvcs", pvc("c2", "1Gi"))
        pv_controller_step(store)
        bound = [
            store.get("pvcs", n)["spec"].get("volumeName") for n in ("c1", "c2")
        ]
        assert sorted(b or "" for b in bound) == ["", "only"]

    def test_statically_prebound_pv_not_double_bound(self):
        store = ResourceStore()
        store.apply("pvs", pv("only", "5Gi"))
        # claim-a statically pre-binds 'only' via volumeName (no claimRef)
        a = pvc("a", "1Gi")
        a["spec"]["volumeName"] = "only"
        store.apply("pvcs", a)
        store.apply("pvcs", pvc("b", "1Gi"))
        pv_controller_step(store)
        assert "volumeName" not in store.get("pvcs", "b")["spec"]
        assert "claimRef" not in store.get("pvs", "only")["spec"]

    def test_storage_class_must_match(self):
        store = ResourceStore()
        store.apply("pvs", pv("fast", "5Gi", sc="ssd"))
        store.apply("pvcs", pvc("claim", "1Gi", sc="hdd"))
        assert pv_controller_step(store) is False
        assert "volumeName" not in store.get("pvcs", "claim")["spec"]


class TestOrdinalCollision:
    def test_unrelated_pod_not_adopted(self):
        store = ResourceStore()
        store.apply(
            "pods",
            {"metadata": {"name": "web-0", "namespace": "default"},
             "spec": {"containers": [{"name": "mine"}]}},
        )
        store.apply(
            "replicasets",
            {
                "metadata": {"name": "web", "namespace": "default"},
                "spec": {
                    "replicas": 1,
                    "template": {"spec": {"containers": [{"name": "rs-c"}]}},
                },
            },
        )
        replicaset_controller_step(store)
        # the user's pod is untouched; the RS takes the next ordinal
        mine = store.get("pods", "web-0")
        assert mine["spec"]["containers"][0]["name"] == "mine"
        assert "ownerReferences" not in mine["metadata"]
        assert store.get("pods", "web-1") is not None


class TestFixpoint:
    def test_diverging_controller_raises(self):
        store = ResourceStore()
        counter = {"n": 0}

        def restless(_):
            counter["n"] += 1
            return True

        with pytest.raises(RuntimeError):
            run_to_fixpoint(store, controllers=(restless,), max_rounds=5)
        assert counter["n"] == 5
