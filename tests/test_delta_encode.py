"""Delta-vs-full encoding equivalence (the tentpole's correctness
contract): for ANY store event sequence, the `DeltaEncoder`'s retained
encoding must be ARRAY-IDENTICAL to a from-scratch `encode_cluster` of
the same store state at the same capacity buckets — whether the pass
took the incremental path or any fallback.

The property tests drive randomized `ChaosSpec` timelines (plus
synthetic scheduling write-backs and evictions between events, so the
binding delta path is exercised) and assert equality after EVERY event
batch. Separate cases pin the fallback triggers: stale resourceVersion,
capacity-bucket crossing, config identity change, vocabulary growth,
inter-pod affinity pods, PVC pods, taint flaps, deletions, and the
dirty-fraction threshold.
"""

from __future__ import annotations

import random

import jax
import numpy as np
import pytest

from kube_scheduler_simulator_tpu.engine.delta import DeltaEncoder
from kube_scheduler_simulator_tpu.engine.encode import PACKED, TPU32, encode_cluster
from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
from kube_scheduler_simulator_tpu.models.store import ResourceStore
from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec
from kube_scheduler_simulator_tpu.sched.config import SchedulerConfiguration
from kube_scheduler_simulator_tpu.utils.compilecache import capacity_buckets

from helpers import node, pod


def full_encode(store, config, *, node_lo=8, pod_lo=8, policy=TPU32):
    """The from-scratch reference: exactly what the service's full path
    builds for this store state."""
    nodes = store.list("nodes")
    pods = store.list("pods")
    ncap, pcap = capacity_buckets(
        len(nodes), len(pods), node_lo=node_lo, pod_lo=pod_lo
    )
    return encode_cluster(
        nodes,
        pods,
        config,
        policy=policy,
        priorityclasses=store.list("priorityclasses"),
        namespaces=store.list("namespaces"),
        pvcs=store.list("pvcs"),
        pvs=store.list("pvs"),
        storageclasses=store.list("storageclasses"),
        node_capacity=ncap,
        pod_capacity=pcap,
    )


def assert_enc_equal(got, want, ctx=""):
    """Every array leaf (ClusterArrays + SchedState), the queue, and the
    host decode metadata must match exactly."""
    assert got.node_names == want.node_names, ctx
    assert got.pod_keys == want.pod_keys, ctx
    assert got.resource_names == want.resource_names, ctx
    assert (got.n_nodes, got.n_pods) == (want.n_nodes, want.n_pods), ctx
    np.testing.assert_array_equal(
        np.asarray(got.queue), np.asarray(want.queue), err_msg=f"queue {ctx}"
    )
    g_leaves = jax.tree_util.tree_flatten_with_path((got.arrays, got.state0))[0]
    w_leaves = jax.tree_util.tree_flatten_with_path((want.arrays, want.state0))[0]
    assert len(g_leaves) == len(w_leaves)
    for (gp, gx), (_, wx) in zip(g_leaves, w_leaves):
        path = jax.tree_util.keystr(gp)
        assert gx.shape == wx.shape, f"{path} shape {gx.shape}!={wx.shape} {ctx}"
        np.testing.assert_array_equal(
            np.asarray(gx), np.asarray(wx), err_msg=f"{path} {ctx}"
        )


def check(delta, store, config, ctx=""):
    """One delta pass + one from-scratch pass, compared. Returns the
    pass's info dict (mode/reason) for coverage accounting."""
    enc, info = delta.encode(store, config)
    retained = delta._st.enc if delta._st is not None else None
    if enc is not None:
        assert retained is enc
    if retained is not None:
        # the reference is a from-scratch encode under the encoder's OWN
        # policy, so under PACKED the comparison covers the packed words
        # and narrowed dtypes bit-for-bit
        assert_enc_equal(
            retained, full_encode(store, config, policy=delta.policy), ctx
        )
    else:
        # nothing retained: legitimately nothing schedulable right now
        pods = store.list("pods")
        pending = [
            p for p in pods if not (p.get("spec", {}) or {}).get("nodeName")
        ]
        assert not store.list("nodes") or not pods or not pending, ctx
    return info


# -- randomized chaos timelines ---------------------------------------------

_TEMPLATES = [
    {"metadata": {"name": "plain"}, "spec": {"containers": [
        {"name": "c", "resources": {"requests": {"cpu": "100m", "memory": "64Mi"}}}]}},
    {"metadata": {"name": "tol"}, "spec": {
        "tolerations": [{"key": "flaky", "operator": "Exists", "effect": "NoSchedule"}],
        "containers": [{"name": "c", "resources": {"requests": {"cpu": "50m"}}}]}},
    {"metadata": {"name": "lab", "labels": {"app": "web", "tier": "fe"}}, "spec": {
        "containers": [{"name": "c", "resources": {"requests": {"memory": "32Mi"}}}]}},
    {"metadata": {"name": "sel"}, "spec": {
        "nodeSelector": {"zone": "a"},
        "containers": [{"name": "c", "resources": {"requests": {"cpu": "25m"}}}]}},
    {"metadata": {"name": "spread", "labels": {"app": "web"}}, "spec": {
        "topologySpreadConstraints": [{
            "maxSkew": 1, "topologyKey": "kubernetes.io/hostname",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "web"}}}],
        "containers": [{"name": "c", "resources": {"requests": {"cpu": "10m"}}}]}},
]


def _snapshot(n_nodes=5):
    nodes = [
        node(
            f"n{i}",
            cpu="8",
            mem="16Gi",
            labels={"zone": "a" if i % 2 else "b", "kubernetes.io/hostname": f"n{i}"},
        )
        for i in range(n_nodes)
    ]
    # one primer pod per template flavor so the first full encode interns
    # the recurring vocabulary (later arrivals of the same flavors can
    # then take the delta path)
    pods = []
    for t in _TEMPLATES:
        p = {"metadata": dict(t["metadata"]), "spec": dict(t["spec"])}
        p["metadata"] = {**p["metadata"], "name": p["metadata"]["name"] + "-seed"}
        pods.append(p)
    return {"nodes": nodes, "pods": pods}


def _chaos_spec(seed: int) -> ChaosSpec:
    return ChaosSpec.from_dict(
        {
            "seed": seed,
            "horizon": 30.0,
            "name": f"delta-prop-{seed}",
            "snapshot": _snapshot(),
            "arrivals": [
                {"kind": "poisson", "rate": 1.0, "count": 12, "template": t}
                for t in _TEMPLATES
            ],
            "faults": [
                {"at": 6.0, "action": "cordon", "node": "n1"},
                {"at": 9.0, "action": "taint", "node": "n2",
                 "taint": {"key": "flaky", "effect": "NoSchedule"}},
                {"at": 12.0, "action": "uncordon", "node": "n1"},
                {"at": 15.0, "action": "fail", "node": "n3"},
                {"at": 18.0, "action": "untaint", "node": "n2",
                 "taint": {"key": "flaky", "effect": "NoSchedule"}},
                {"at": 21.0, "action": "recover", "node": "n3"},
                {"at": 24.0, "action": "drain", "node": "n0"},
            ],
        }
    )


class _AssertingEngine(LifecycleEngine):
    """LifecycleEngine whose convergence step is replaced by the
    delta-vs-full assertion plus synthetic scheduling write-backs (binds
    and occasional evictions/deletions) so the MODIFIED-pod delta path
    gets real coverage without running the scheduling engine."""

    def __init__(self, spec, config, rng, policy=TPU32):
        super().__init__(spec)
        self.cfg = config
        self.rng = rng
        self.delta = DeltaEncoder(policy=policy)
        self.infos = []

    def _converge(self, t):
        self.infos.append(check(self.delta, self.store, self.cfg, f"t={t} pre"))
        # synthetic write-backs: bind ~half the pending pods, evict an
        # occasional bound one (replace strips nodeName = MODIFIED), and
        # rarely hard-delete one (forces the deletion fallback)
        names = [n["metadata"]["name"] for n in self.store.list("nodes")]
        for p in self.store.list("pods"):
            meta = p["metadata"]
            bound = (p.get("spec") or {}).get("nodeName")
            if not bound and names and self.rng.random() < 0.6:
                self.store.apply(
                    "pods",
                    {
                        "metadata": {
                            "name": meta["name"],
                            "namespace": meta.get("namespace", "default"),
                            "annotations": {"kss/result": "Scheduled"},
                        },
                        "spec": {"nodeName": self.rng.choice(names)},
                    },
                )
            elif bound and self.rng.random() < 0.08:
                q = {k: v for k, v in p.items() if k != "status"}
                q["spec"] = {
                    k: v for k, v in (p.get("spec") or {}).items() if k != "nodeName"
                }
                self.store.replace("pods", q)
            elif self.rng.random() < 0.03:
                self.store.delete(
                    "pods", meta["name"], meta.get("namespace", "default")
                )
        self.infos.append(check(self.delta, self.store, self.cfg, f"t={t} post"))


@pytest.mark.parametrize(
    "seed, policy",
    [(0, TPU32), (1, TPU32), (2, TPU32), (0, PACKED), (1, PACKED)],
    ids=["0-i32", "1-i32", "2-i32", "0-packed", "1-packed"],
)
def test_random_chaos_delta_equals_full(seed, policy):
    spec = _chaos_spec(seed)
    eng = _AssertingEngine(
        spec, SchedulerConfiguration.default(), random.Random(seed), policy
    )
    res = eng.run()
    assert res["phase"] == "Succeeded"
    modes = [i["mode"] for i in eng.infos]
    # the property is vacuous if the delta path never engaged
    assert "delta" in modes, modes
    assert "full" in modes  # and the fallback paths were exercised too


def test_pure_arrival_churn_stays_incremental():
    """The O(Δ) claim: homogeneous arrivals + binds against a warm
    encoding never fall back to a full re-encode."""
    store = ResourceStore()
    cfg = SchedulerConfiguration.default()
    for i in range(4):
        store.apply("nodes", node(f"n{i}", cpu="16"))
    # seed the store mid-bucket (18 pods → capacity 32) so the churn
    # below never crosses the capacity bucket
    for i in range(17):
        store.apply("pods", pod(f"seed-{i}", cpu="100m", node_name=f"n{i % 4}"))
    store.apply("pods", pod("seed-pending", cpu="100m"))
    delta = DeltaEncoder()
    assert check(delta, store, cfg, "warmup")["mode"] == "full"
    modes = []
    for i in range(12):
        store.apply("pods", pod(f"churn-{i}", cpu="100m"))
        modes.append(check(delta, store, cfg, f"arrival {i}")["mode"])
        # write-back: bind the pod (what a scheduling pass does)
        store.apply(
            "pods",
            {"metadata": {"name": f"churn-{i}"}, "spec": {"nodeName": f"n{i % 4}"}},
        )
        modes.append(check(delta, store, cfg, f"bind {i}")["mode"])
    assert set(modes) == {"delta"}, modes


def test_unbind_via_replace_is_incremental():
    store = ResourceStore()
    cfg = SchedulerConfiguration.default()
    store.apply("nodes", node("n0"))
    store.apply("pods", pod("a", node_name="n0"))
    store.apply("pods", pod("b"))
    delta = DeltaEncoder()
    check(delta, store, cfg, "warm")
    a = store.get("pods", "a")
    a["spec"].pop("nodeName")
    a.pop("status", None)
    store.replace("pods", a)
    info = check(delta, store, cfg, "unbind")
    assert info["mode"] == "delta"


def test_transient_readd_appends_in_store_order():
    """add a, add b, delete a, re-add a inside ONE window: a nets to
    ADDED but moved to the END of store iteration order — the delta
    append order must match (regression for the dirty_since ordering
    bug: a kept its first-event slot and encoded before b)."""
    store = ResourceStore()
    cfg = SchedulerConfiguration.default()
    store.apply("nodes", node("n0", cpu="16"))
    store.apply("pods", pod("seed"))
    delta = DeltaEncoder()
    check(delta, store, cfg, "warm")
    store.apply("pods", pod("a"))
    store.apply("pods", pod("b"))
    store.delete("pods", "a")
    store.apply("pods", pod("a"))
    info = check(delta, store, cfg, "transient re-add")
    assert info["mode"] == "delta", info
    assert delta._st.enc.pod_keys[-2:] == [("default", "b"), ("default", "a")]


def test_stale_rv_falls_back_to_full():
    store = ResourceStore(event_log_capacity=8)
    cfg = SchedulerConfiguration.default()
    store.apply("nodes", node("n0"))
    store.apply("pods", pod("p0"))
    delta = DeltaEncoder()
    check(delta, store, cfg, "warm")
    for i in range(32):  # blow past the event log window
        store.apply("pods", pod(f"flood-{i}"))
    info = check(delta, store, cfg, "stale")
    assert info["mode"] == "full" and info["reason"] == "stale-rv"


def test_bucket_crossing_falls_back_and_grows_shapes():
    store = ResourceStore()
    cfg = SchedulerConfiguration.default()
    store.apply("nodes", node("n0", cpu="64", pods="200"))
    for i in range(7):
        store.apply("pods", pod(f"p{i}"))
    delta = DeltaEncoder()
    enc, _ = delta.encode(store, cfg)
    assert enc.P == 8
    store.apply("pods", pod("p7"))
    info = check(delta, store, cfg, "fills bucket")
    assert info["mode"] == "delta"
    store.apply("pods", pod("p8"))  # 9 pods: crosses the 8-bucket
    info = check(delta, store, cfg, "crossing")
    assert info["mode"] == "full" and "bucket" in info["reason"]
    assert delta._st.enc.P == 16


def test_config_identity_change_falls_back():
    store = ResourceStore()
    cfg = SchedulerConfiguration.default()
    store.apply("nodes", node("n0"))
    store.apply("pods", pod("p0"))
    delta = DeltaEncoder()
    check(delta, store, cfg, "warm")
    store.apply("pods", pod("p1"))
    cfg2 = SchedulerConfiguration.default()  # equal value, new identity
    info = check(delta, store, cfg2, "config swap")
    assert info["mode"] == "full" and info["reason"] == "config-change"


@pytest.mark.parametrize(
    "manifest, why",
    [
        (pod("novel-label", labels={"brand-new-key": "x"}), "label vocab"),
        (pod("novel-res") | {"spec": {"containers": [{"name": "c", "resources": {
            "requests": {"example.com/fpga": "1"}}}]}}, "resource vocab"),
        (pod("claims", volumes=[{"name": "v", "persistentVolumeClaim": {
            "claimName": "c0"}}]), "pvc pod"),
        (pod("affine", affinity={"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "topologyKey": "kubernetes.io/hostname",
                "labelSelector": {"matchLabels": {"app": "web"}}}]}}),
         "inter-pod affinity"),
    ],
)
def test_ineligible_pods_fall_back_but_stay_exact(manifest, why):
    store = ResourceStore()
    cfg = SchedulerConfiguration.default()
    store.apply("nodes", node("n0", labels={"kubernetes.io/hostname": "n0"}))
    store.apply("pods", pod("p0"))
    delta = DeltaEncoder()
    check(delta, store, cfg, "warm")
    store.apply("pods", manifest)
    info = check(delta, store, cfg, why)
    assert info["mode"] == "full", (why, info)


def test_taint_flap_and_node_delete_fall_back():
    store = ResourceStore()
    cfg = SchedulerConfiguration.default()
    for i in range(2):
        store.apply("nodes", node(f"n{i}"))
    store.apply("pods", pod("p0"))
    delta = DeltaEncoder()
    check(delta, store, cfg, "warm")
    store.apply(
        "nodes",
        {"metadata": {"name": "n1"},
         "spec": {"taints": [{"key": "k", "effect": "NoSchedule"}]}},
    )
    assert check(delta, store, cfg, "taint")["mode"] == "full"
    store.apply("pods", pod("p1"))
    assert check(delta, store, cfg, "arrival")["mode"] == "delta"
    store.delete("nodes", "n1")
    assert check(delta, store, cfg, "node delete")["mode"] == "full"


def test_cordon_uncordon_is_incremental():
    store = ResourceStore()
    cfg = SchedulerConfiguration.default()
    for i in range(2):
        store.apply("nodes", node(f"n{i}"))
    store.apply("pods", pod("p0"))
    delta = DeltaEncoder()
    check(delta, store, cfg, "warm")
    store.apply(
        "nodes", {"metadata": {"name": "n1"}, "spec": {"unschedulable": True}}
    )
    assert check(delta, store, cfg, "cordon")["mode"] == "delta"
    store.apply(
        "nodes", {"metadata": {"name": "n1"}, "spec": {"unschedulable": False}}
    )
    assert check(delta, store, cfg, "uncordon")["mode"] == "delta"


def test_dirty_fraction_threshold_falls_back():
    store = ResourceStore()
    cfg = SchedulerConfiguration.default()
    for i in range(2):
        store.apply("nodes", node(f"n{i}", cpu="64", pods="200"))
    for i in range(20):
        store.apply("pods", pod(f"p{i}"))
    delta = DeltaEncoder(max_dirty_frac=0.25)
    check(delta, store, cfg, "warm")
    # touch well past 25% of the cluster in one window
    for i in range(12):
        store.apply(
            "pods", {"metadata": {"name": f"p{i}"}, "spec": {"nodeName": "n0"}}
        )
    info = check(delta, store, cfg, "bulk rebind")
    assert info["mode"] == "full" and "dirty fraction" in info["reason"]


def test_priorityclass_event_falls_back():
    store = ResourceStore()
    cfg = SchedulerConfiguration.default()
    store.apply("nodes", node("n0"))
    store.apply("pods", pod("p0"))
    delta = DeltaEncoder()
    check(delta, store, cfg, "warm")
    store.apply(
        "priorityclasses",
        {"metadata": {"name": "high"}, "value": 1000},
    )
    info = check(delta, store, cfg, "pc event")
    assert info["mode"] == "full" and "priorityclasses" in info["reason"]
