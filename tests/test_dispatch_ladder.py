"""The runtime device-fault ladder (docs/resilience.md): dispatch
watchdog + bounded retry, mesh-shrink rebuild, mid-process CPU
failover — and the tier-1 resilience-gate parity: a chaos run under
``device_lost:1.0`` completes on a lower rung with a trace
byte-identical to a clean run's, and a graceful mid-run stop (the
``kill -TERM`` stand-in) drains with exit 0 and resumes to the same
bytes."""

from __future__ import annotations

import contextlib
import io
import json
import os

import pytest

from kube_scheduler_simulator_tpu.lifecycle.__main__ import (
    main as lifecycle_cli,
)
from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
from kube_scheduler_simulator_tpu.models.store import ResourceStore
from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec
from kube_scheduler_simulator_tpu.server.service import SchedulerService
from kube_scheduler_simulator_tpu.utils import devices as devices_mod
from kube_scheduler_simulator_tpu.utils import faultinject
from kube_scheduler_simulator_tpu.utils.metrics import SchedulingMetrics

from helpers import node, pod


def _cluster_service():
    store = ResourceStore()
    for i in range(4):
        store.apply("nodes", node(f"n{i}", cpu="16", mem="32Gi"))
    for i in range(5):
        store.apply("pods", pod(f"p{i}", cpu="100m"))
    metrics = SchedulingMetrics()
    return store, SchedulerService(store, metrics=metrics), metrics


def _chaos_dict() -> dict:
    return {
        "name": "ladder-parity",
        "seed": 5,
        "horizon": 12.0,
        "schedulerMode": "gang",
        "snapshot": {
            "nodes": [node(f"n{i}", cpu="8", mem="16Gi") for i in range(3)],
            "pods": [
                pod(f"s{i}", cpu="100m", node_name=f"n{i % 3}")
                for i in range(6)
            ],
        },
        "arrivals": [
            {
                "kind": "poisson",
                "rate": 0.5,
                "count": 5,
                "template": pod("churn", cpu="100m"),
            }
        ],
        "faults": [
            {"at": 4.0, "action": "cordon", "node": "n0"},
            {"at": 8.0, "action": "uncordon", "node": "n0"},
        ],
    }


class TestDeviceFaultClassifier:
    def test_injected_device_sites_classify(self):
        for site in ("device_error", "device_lost"):
            assert devices_mod.is_device_fault(faultinject.InjectedFault(site))

    def test_other_injected_sites_do_not(self):
        assert not devices_mod.is_device_fault(
            faultinject.InjectedFault("compile_fail")
        )

    def test_deadline_classifies_and_ordinary_errors_do_not(self):
        assert devices_mod.is_device_fault(
            devices_mod.DispatchDeadlineExceeded("late")
        )
        assert not devices_mod.is_device_fault(ValueError("bug"))

    def test_xla_runtime_error_matched_by_name(self):
        class XlaRuntimeError(RuntimeError):
            pass

        assert devices_mod.is_device_fault(XlaRuntimeError("device lost"))


class TestWatchdog:
    def test_no_deadline_runs_inline(self):
        assert devices_mod.run_with_deadline(lambda: 41 + 1, 0.0) == 42

    def test_deadline_trips_on_a_hang(self):
        import time

        with pytest.raises(devices_mod.DispatchDeadlineExceeded):
            devices_mod.run_with_deadline(lambda: time.sleep(5), 0.05)

    def test_inner_exception_relayed(self):
        def boom():
            raise ValueError("inner")

        with pytest.raises(ValueError, match="inner"):
            devices_mod.run_with_deadline(boom, 5.0)


class TestServiceLadder:
    @pytest.mark.parametrize("mode", ["gang", "sequential"])
    def test_device_lost_fails_over_with_identical_placements(
        self, monkeypatch, mode
    ):
        _, svc_ok, _ = _cluster_service()
        if mode == "gang":
            ok = svc_ok.schedule_gang(record=False)[0]
        else:
            ok = {
                (r.pod_namespace, r.pod_name): r.selected_node
                for r in svc_ok.schedule()
            }
        monkeypatch.setenv("KSS_FAULT_INJECT", "device_lost:1.0")
        monkeypatch.setenv("KSS_DISPATCH_RETRIES", "1")
        _, svc, metrics = _cluster_service()
        if mode == "gang":
            got = svc.schedule_gang(record=False)[0]
        else:
            got = {
                (r.pod_namespace, r.pod_name): r.selected_node
                for r in svc.schedule()
            }
        assert got == ok
        assert svc.device_rung == "cpu"
        phases = metrics.snapshot()["phases"]
        assert phases["dispatchRetries"] == 1
        assert phases["meshShrinks"] == 1  # 8 virtual devices: one shrink
        assert phases["deviceFailovers"] == 1

    def test_failover_latches_no_ladder_rewalk(self, monkeypatch):
        monkeypatch.setenv("KSS_FAULT_INJECT", "device_lost:1.0")
        store, svc, metrics = _cluster_service()
        svc.schedule_gang(record=False)
        retries = metrics.snapshot()["phases"]["dispatchRetries"]
        store.apply("pods", pod("late", cpu="100m"))
        placements, _, _ = svc.schedule_gang(record=False)
        assert placements  # the latched CPU rung still schedules
        after = metrics.snapshot()["phases"]
        assert after["dispatchRetries"] == retries
        assert after["deviceFailovers"] == 1  # counted once, not per pass

    def test_dispatch_hang_trips_deadline_and_escalates(self, monkeypatch):
        _, svc_ok, _ = _cluster_service()
        ok = svc_ok.schedule_gang(record=False)[0]
        monkeypatch.setenv("KSS_FAULT_INJECT", "dispatch_hang:200ms")
        monkeypatch.setenv("KSS_DISPATCH_DEADLINE_S", "0.02")
        monkeypatch.setenv("KSS_DISPATCH_RETRIES", "0")
        _, svc, metrics = _cluster_service()
        assert svc.schedule_gang(record=False)[0] == ok
        assert svc.device_rung == "cpu"
        assert metrics.snapshot()["phases"]["deviceFailovers"] == 1

    def test_transient_fault_recovers_without_escalation(self, monkeypatch):
        """A device fault that clears within the retry budget stays on
        the device rung: no shrink, no failover."""
        fired = {"n": 0}

        class OneShotPlane(faultinject.FaultPlane):
            def maybe_raise(self, site):
                if site == "device_error" and fired["n"] == 0:
                    fired["n"] = 1
                    raise faultinject.InjectedFault(site)

        faultinject.activate(OneShotPlane({}, seed=0))
        try:
            _, svc, metrics = _cluster_service()
            placements, _, _ = svc.schedule_gang(record=False)
        finally:
            faultinject.deactivate()
        assert placements
        assert svc.device_rung == "device"
        phases = metrics.snapshot()["phases"]
        assert phases["dispatchRetries"] == 1
        assert phases["deviceFailovers"] == 0
        assert phases["meshShrinks"] == 0

    def test_non_device_errors_propagate_untouched(self, monkeypatch):
        """The ladder must never retry an ordinary bug into silence."""
        _, svc, metrics = _cluster_service()
        calls = {"n": 0}

        def broken(config, record, window=None):
            calls["n"] += 1
            raise ValueError("an encode bug, not a device fault")

        monkeypatch.setattr(svc, "_gang_dispatch_once", broken)
        with pytest.raises(ValueError, match="encode bug"):
            svc.schedule_gang(record=False)
        assert calls["n"] == 1  # no retry
        assert metrics.snapshot()["phases"]["dispatchRetries"] == 0


class TestChaosRunParity:
    def test_device_lost_chaos_run_is_byte_identical(self, monkeypatch):
        """The resilience gate (ISSUE 9 acceptance): with device_lost:1.0
        injected, a chaos run completes on a lower ladder rung with a
        trace byte-identical to a clean run."""
        clean = LifecycleEngine(ChaosSpec.from_dict(_chaos_dict()))
        clean_res = clean.run()
        assert clean_res["phase"] == "Succeeded"
        monkeypatch.setenv("KSS_FAULT_INJECT", "device_lost:1.0")
        monkeypatch.setenv("KSS_DISPATCH_RETRIES", "0")
        faulted = LifecycleEngine(ChaosSpec.from_dict(_chaos_dict()))
        res = faulted.run()
        assert res["phase"] == "Succeeded", res.get("message")
        phases = res["metrics"]["phases"]
        assert phases["deviceFailovers"] >= 1
        assert faulted.trace_jsonl() == clean.trace_jsonl()

    def test_graceful_stop_drains_exit_0_and_resumes_byte_identical(
        self, tmp_path
    ):
        """`kill -TERM` mid-run (deterministic stand-in:
        --stop-after-events) drains with exit 0 — Interrupted + final
        checkpoint is the orderly zero-loss path — and the resumed
        trace is byte-identical to the uninterrupted run's."""
        spec_path = tmp_path / "spec.json"
        spec_path.write_text(json.dumps(_chaos_dict()))
        ckpt = str(tmp_path / "run.ckpt.json")
        killed = str(tmp_path / "killed.jsonl")
        resumed = str(tmp_path / "resumed.jsonl")
        clean = LifecycleEngine(ChaosSpec.from_dict(_chaos_dict()))
        clean.run()
        clean_bytes = clean.trace_jsonl().encode()
        with contextlib.redirect_stdout(io.StringIO()):
            rc = lifecycle_cli(
                [
                    "--spec", str(spec_path), "--checkpoint-to", ckpt,
                    "--stop-after-events", "3", "--trace-out", killed,
                ]
            )
        assert rc == 0  # the orderly drain reads as success
        assert os.path.exists(ckpt)
        with open(killed, "rb") as f:
            assert clean_bytes.startswith(f.read())
        with contextlib.redirect_stdout(io.StringIO()):
            rc = lifecycle_cli(["--resume", ckpt, "--trace-out", resumed])
        assert rc == 0
        with open(resumed, "rb") as f:
            assert f.read() == clean_bytes

    def test_interrupted_without_checkpoint_still_exits_1(self, tmp_path):
        """Exit 0 is the DRAIN contract: an interrupted run that wrote
        no checkpoint lost its tail and must keep reading as failure."""
        spec_path = tmp_path / "spec.json"
        spec_path.write_text(json.dumps(_chaos_dict()))
        with contextlib.redirect_stdout(io.StringIO()):
            rc = lifecycle_cli(
                ["--spec", str(spec_path), "--stop-after-events", "3"]
            )
        assert rc == 1
