"""The zero-loss graceful drain (docs/resilience.md): readyz flips to
the DISTINCT ``draining`` state, new requests shed with the structured
503, every live session — the default included — snapshots through the
``kss-session-checkpoint/v1`` path, the broker quiesces, and a manager
restarted over the same directory adopts the snapshots transparently."""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.lifecycle.checkpoint import (
    SESSION_CHECKPOINT_FORMAT,
    load_checkpoint,
)
from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
from kube_scheduler_simulator_tpu.server.service import SimulatorService
from kube_scheduler_simulator_tpu.server.sessions import SessionManager
from kube_scheduler_simulator_tpu.utils.metrics import parse_prometheus_text

from helpers import node, pod


def _req(port, method, path, body=None, timeout=300):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None, dict(resp.headers)
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else None, dict(e.headers)


@pytest.fixture()
def server(tmp_path):
    srv = SimulatorServer(
        SimulatorService(),
        port=0,
        session_config={"snapshot_dir": str(tmp_path / "sessions")},
    ).start()
    yield srv
    srv.shutdown()


class TestManagerDrain:
    def test_drain_snapshots_every_live_session_including_default(
        self, tmp_path
    ):
        mgr = SessionManager(
            SimulatorService(), snapshot_dir=str(tmp_path), idle_evict_s=0.0
        )
        default_svc = mgr.get("default").service
        default_svc.store.apply("nodes", node("dn0"))
        sess, _ = mgr.create(name="tenant")
        sess.service.store.apply("pods", pod("tp0"))
        result = mgr.drain(deadline_s=5.0)
        assert set(result["drainedSessions"]) == {"default", sess.id}
        assert result["forced"] == []
        assert mgr.draining
        assert mgr.drained == 2
        for sid in ("default", sess.id):
            doc = load_checkpoint(
                os.path.join(str(tmp_path), f"{sid}.json"),
                SESSION_CHECKPOINT_FORMAT,
            )
            assert doc["id"] == sid
        mgr.shutdown()

    def test_drain_is_idempotent(self, tmp_path):
        mgr = SessionManager(SimulatorService(), snapshot_dir=str(tmp_path))
        mgr.drain(deadline_s=1.0)
        again = mgr.drain(deadline_s=1.0)
        assert "default" in again["drainedSessions"]  # re-snapshot, no error
        mgr.shutdown()

    def test_in_flight_pass_finishes_before_snapshot(self, tmp_path):
        """A pass holding the schedule lock within the deadline is
        waited out — the snapshot carries its write-backs."""
        import threading
        import time

        mgr = SessionManager(SimulatorService(), snapshot_dir=str(tmp_path))
        svc = mgr.get("default").service
        svc.store.apply("nodes", node("n0"))
        svc.store.apply("pods", pod("p0"))
        lock = svc.scheduler._schedule_lock
        lock.acquire()

        def finish_pass():
            time.sleep(0.3)
            svc.store.apply("pods", pod("p1"))  # the "write-back"
            lock.release()

        t = threading.Thread(target=finish_pass)
        t.start()
        result = mgr.drain(deadline_s=10.0)
        t.join()
        assert result["forced"] == []
        doc = load_checkpoint(
            os.path.join(str(tmp_path), "default.json"),
            SESSION_CHECKPOINT_FORMAT,
        )
        names = {o["metadata"]["name"] for o in doc["store"]["objects"]["pods"]}
        assert names == {"p0", "p1"}
        mgr.shutdown()

    def test_wedged_pass_forces_snapshot_past_deadline(self, tmp_path):
        """Past KSS_DRAIN_DEADLINE_S the drain stops waiting: the pass
        is abandoned at its boundary and the session snapshots anyway
        (an unresolved pass has acknowledged nothing)."""
        mgr = SessionManager(SimulatorService(), snapshot_dir=str(tmp_path))
        svc = mgr.get("default").service
        svc.scheduler._schedule_lock.acquire()  # a pass that never ends
        try:
            result = mgr.drain(deadline_s=0.2)
        finally:
            svc.scheduler._schedule_lock.release()
        assert result["forced"] == ["default"]
        assert os.path.exists(os.path.join(str(tmp_path), "default.json"))
        mgr.shutdown()

    def test_restart_adopts_snapshots_transparently(self, tmp_path):
        mgr = SessionManager(
            SimulatorService(), snapshot_dir=str(tmp_path), idle_evict_s=0.0
        )
        mgr.get("default").service.store.apply("nodes", node("dn0"))
        sess, _ = mgr.create(name="tenant")
        sess.service.store.apply("pods", pod("tp0"))
        sid = sess.id
        mgr.drain(deadline_s=5.0)
        mgr.shutdown()
        # "rolling restart": a fresh manager over the same directory
        mgr2 = SessionManager(SimulatorService(), snapshot_dir=str(tmp_path))
        # the default session's state restored IN PLACE at boot (its
        # snapshot consumed), other sessions adopted as evicted
        assert mgr2.get("default").service.store.count("nodes") == 1
        assert not os.path.exists(os.path.join(str(tmp_path), "default.json"))
        assert mgr2.info(sid)["state"] == "evicted"
        restored = mgr2.get(sid)  # the transparent-restore touch
        assert restored.service.store.count("pods") == 1
        assert restored.name == "tenant"
        mgr2.shutdown()

    def test_drain_contains_per_session_snapshot_failures(
        self, tmp_path, monkeypatch
    ):
        """One tenant's failed snapshot must not cost the others theirs
        — it is recorded in the result's `errors` (which the serving
        CLI turns into a non-zero exit) while every other session still
        lands on disk and the broker still quiesces."""
        import kube_scheduler_simulator_tpu.server.sessions as sessions_mod

        mgr = SessionManager(SimulatorService(), snapshot_dir=str(tmp_path))
        bad, _ = mgr.create(name="bad")
        good, _ = mgr.create(name="good")
        real = sessions_mod.write_checkpoint

        def flaky(doc, path):
            if doc.get("id") == bad.id:
                raise OSError("disk full")
            return real(doc, path)

        monkeypatch.setattr(sessions_mod, "write_checkpoint", flaky)
        result = mgr.drain(deadline_s=5.0)
        assert list(result["errors"]) == [bad.id]
        assert "disk full" in result["errors"][bad.id]
        assert set(result["drainedSessions"]) == {"default", good.id}
        assert os.path.exists(os.path.join(str(tmp_path), f"{good.id}.json"))
        mgr.shutdown()

    def test_adopt_skips_unreadable_files(self, tmp_path):
        (tmp_path / "junk.json").write_text("{not json")
        (tmp_path / "wrong.json").write_text(json.dumps({"format": "other"}))
        mgr = SessionManager(SimulatorService(), snapshot_dir=str(tmp_path))
        assert set(mgr._sessions) == {"default"}
        mgr.shutdown()


class TestServerDrainSurface:
    def test_drain_route_readyz_and_shedding(self, server):
        port = server.port
        code, doc, _ = _req(port, "GET", "/api/v1/readyz")
        assert code == 200 and doc["state"] == "ready"
        code, doc, _ = _req(port, "POST", "/api/v1/admin/drain")
        assert code == 202 and doc["draining"]
        server.drain_done.wait(30)
        # readyz: the DISTINCT draining state, 503 + Retry-After
        code, doc, headers = _req(port, "GET", "/api/v1/readyz")
        assert code == 503
        assert doc["state"] == "draining"
        assert "Retry-After" in headers
        # new work sheds with the structured 503
        code, doc, headers = _req(port, "POST", "/api/v1/schedule")
        assert code == 503
        assert doc["kind"] == "ServerDraining"
        assert "Retry-After" in headers
        # health, drain status, and the metrics scrape stay answerable
        assert _req(port, "GET", "/api/v1/healthz")[0] == 200
        code, status, _ = _req(port, "GET", "/api/v1/admin/drain")
        assert code == 200 and status["done"]
        assert "default" in status["result"]["drainedSessions"]
        code, metrics, _ = _req(port, "GET", "/api/v1/metrics")
        assert code == 200
        assert metrics["draining"] is True
        assert metrics["drainedSessions"] >= 1

    def test_drain_state_in_prometheus(self, server):
        server.drain(timeout=30)
        code, _, _ = _req(port := server.port, "GET", "/api/v1/healthz")
        assert code == 200
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/v1/metrics?format=prometheus"
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            families = parse_prometheus_text(resp.read().decode())
        assert families["kss_server_draining"]["samples"][0][2] == 1.0
        drained = families["kss_drained_sessions_total"]["samples"][0][2]
        assert drained >= 1.0

    def test_metrics_reports_device_rung(self, server):
        code, doc, _ = _req(server.port, "GET", "/api/v1/metrics")
        assert code == 200
        assert doc["deviceRung"] == "device"
        assert doc["draining"] is False
