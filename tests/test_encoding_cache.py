"""EncodingCache LRU bound + ResourceStore.dirty_since classification."""

from __future__ import annotations

import pytest

from kube_scheduler_simulator_tpu.engine.encode import EncodingCache
from kube_scheduler_simulator_tpu.models.store import (
    ResourceStore,
    StaleResourceVersion,
)

from helpers import node, pod


class TestEncodingCacheLRU:
    def test_hit_and_miss(self):
        c = EncodingCache(capacity=2)
        cfg = object()
        assert c.get((1,), cfg) is EncodingCache.MISS
        c.put((1,), cfg, "enc1")
        assert c.get((1,), cfg) == "enc1"
        # same key, different config identity: miss
        assert c.get((1,), object()) is EncodingCache.MISS

    def test_none_is_cacheable(self):
        c = EncodingCache(capacity=2)
        cfg = object()
        c.put((5,), cfg, None)
        assert c.get((5,), cfg) is None

    def test_eviction_is_lru_not_fifo(self):
        # the LRU axis is config identity at ONE store key (the live
        # alternates; older keys are superseded eagerly — see below)
        c = EncodingCache(capacity=2)
        cfg_a, cfg_b, cfg_c = object(), object(), object()
        c.put((1,), cfg_a, "a")
        c.put((1,), cfg_b, "b")
        assert c.get((1,), cfg_a) == "a"  # refresh cfg_a
        c.put((1,), cfg_c, "c")  # evicts cfg_b, the least recently used
        assert c.get((1,), cfg_b) is EncodingCache.MISS
        assert c.get((1,), cfg_a) == "a"
        assert c.get((1,), cfg_c) == "c"
        assert len(c) == 2

    def test_put_supersedes_older_keys(self):
        # the store key is monotonic: entries at any older key can never
        # hit again, so a put at a newer key drops them immediately
        # instead of pinning dead encodings for the LRU window
        c = EncodingCache(capacity=8)
        cfg = object()
        c.put((1,), cfg, "a")
        c.put((2,), cfg, "b")
        assert len(c) == 1
        assert c.get((1,), cfg) is EncodingCache.MISS
        assert c.get((2,), cfg) == "b"

    def test_many_config_identities_stay_bounded(self):
        c = EncodingCache(capacity=4)
        configs = [object() for _ in range(64)]
        for i, cfg in enumerate(configs):
            c.put((7,), cfg, f"enc{i}")  # one rv, many configs
            assert len(c) <= 4
        # only the newest survive
        assert c.get((7,), configs[63]) == "enc63"
        assert c.get((7,), configs[0]) is EncodingCache.MISS

    def test_put_same_key_replaces(self):
        c = EncodingCache(capacity=2)
        cfg = object()
        c.put((1,), cfg, "a")
        c.put((1,), cfg, "a2")
        assert c.get((1,), cfg) == "a2"
        assert len(c) == 1

    def test_capacity_validated(self):
        with pytest.raises(ValueError):
            EncodingCache(capacity=0)

    def test_invalidate_clears(self):
        c = EncodingCache(capacity=2)
        cfg = object()
        c.put((1,), cfg, "a")
        c.invalidate()
        assert c.get((1,), cfg) is EncodingCache.MISS
        assert len(c) == 0


class TestDirtySince:
    def test_added_and_modified(self):
        s = ResourceStore()
        rv0 = s.latest_rv()
        s.apply("nodes", node("n0"))
        s.apply("pods", pod("a"))
        s.apply("pods", {"metadata": {"name": "a"}, "spec": {"nodeName": "n0"}})
        d = s.dirty_since(rv0)
        assert d["nodes"] == {"n0": "ADDED"}
        assert d["pods"] == {"default/a": "ADDED"}  # mods fold into ADDED

    def test_modified_only(self):
        s = ResourceStore()
        s.apply("pods", pod("a"))
        rv = s.latest_rv()
        s.apply("pods", {"metadata": {"name": "a"}, "spec": {"nodeName": "x"}})
        assert s.dirty_since(rv) == {"pods": {"default/a": "MODIFIED"}}

    def test_deleted_and_transient(self):
        s = ResourceStore()
        s.apply("pods", pod("a"))
        rv = s.latest_rv()
        s.delete("pods", "a")
        s.apply("pods", pod("b"))
        s.delete("pods", "b")
        d = s.dirty_since(rv)["pods"]
        assert d["default/a"] == "DELETED"
        assert d["default/b"] == "TRANSIENT"

    def test_replaced(self):
        s = ResourceStore()
        s.apply("pods", pod("a"))
        rv = s.latest_rv()
        s.delete("pods", "a")
        s.apply("pods", pod("a"))
        assert s.dirty_since(rv)["pods"]["default/a"] == "REPLACED"
        # replaced then deleted nets to deleted
        s2 = ResourceStore()
        s2.apply("pods", pod("a"))
        rv2 = s2.latest_rv()
        s2.delete("pods", "a")
        s2.apply("pods", pod("a"))
        s2.delete("pods", "a")
        assert s2.dirty_since(rv2)["pods"]["default/a"] == "DELETED"

    def test_no_changes_is_empty(self):
        s = ResourceStore()
        s.apply("pods", pod("a"))
        assert s.dirty_since(s.latest_rv()) == {}

    def test_stale_raises(self):
        s = ResourceStore(event_log_capacity=4)
        s.apply("pods", pod("a"))
        rv = s.latest_rv()
        for i in range(16):
            s.apply("pods", pod(f"p{i}"))
        with pytest.raises(StaleResourceVersion):
            s.dirty_since(rv)

    def test_readd_moves_key_to_end_of_iteration_order(self):
        # add a, add b, delete a, re-add a: the store iterates [b, a],
        # and the delta encoder appends rows in dirty-dict order — the
        # dict must agree with the store (regression: the key used to
        # keep its first-event slot, encoding a before b)
        s = ResourceStore()
        rv = s.latest_rv()
        s.apply("pods", pod("a"))
        s.apply("pods", pod("b"))
        s.delete("pods", "a")
        s.apply("pods", pod("a"))
        d = s.dirty_since(rv)["pods"]
        assert list(d) == ["default/b", "default/a"], d
        assert d == {"default/b": "ADDED", "default/a": "ADDED"}
        assert [p["metadata"]["name"] for p in s.list("pods")] == ["b", "a"]

    def test_cascade_deletes_are_recorded(self):
        s = ResourceStore()
        s.apply("nodes", node("n0"))
        s.apply("pods", pod("a", node_name="n0"))
        rv = s.latest_rv()
        s.delete("nodes", "n0")  # cascades the bound pod away
        d = s.dirty_since(rv)
        assert d["nodes"]["n0"] == "DELETED"
        assert d["pods"]["default/a"] == "DELETED"
