"""Chunked-trace execution: run_chunked == run at full record depth.

The at-scale record=True strategy (engine.py run_chunked): segment the
scan, offload per-segment traces to host, keep preemption victim masks
sparsely. These tests pin that the chunked path produces bit-identical
records and placements to the single-scan path, including across chunk
boundaries and with preemption firing, and that selective decode
(`results(pods=...)`) matches the corresponding full-decode records.
"""

from kube_scheduler_simulator_tpu.engine import (
    EXACT,
    BatchedScheduler,
    encode_cluster,
)
from kube_scheduler_simulator_tpu.sched.config import SchedulerConfiguration

from helpers import node, pod
from test_engine_parity import restricted_config


def _records(sched_results):
    return [r.to_annotations() | {"_status": r.status} for r in sched_results]


def test_chunked_matches_full_no_preempt():
    nodes = [node(f"n{i}", cpu=str(2 + i % 2)) for i in range(5)]
    pods = [pod(f"p{i}", cpu=f"{200 + 90 * (i % 7)}m") for i in range(23)]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    full = BatchedScheduler(enc, record=True)
    full.run()
    chunked = BatchedScheduler(
        encode_cluster(nodes, pods, cfg, policy=EXACT), record=True
    )
    chunked.run_chunked(chunk=7)  # 23 pods -> 3 full chunks + remainder 2
    assert _records(full.results()) == _records(chunked.results())
    assert full.placements() == chunked.placements()


def test_chunked_matches_full_with_preemption():
    # low-priority pods fill the only node; a high-priority pod later in
    # input order preempts — the dry-run fires inside a later chunk and
    # its sparse victim mask must decode identically
    defaults = SchedulerConfiguration.default()
    nodes = [node("n0", cpu="2")]
    pods = [
        pod("victim-a", cpu="1", priority=1),
        pod("victim-b", cpu="1", priority=1),
        pod("pusher", cpu="2", priority=100),
    ]
    # PrioritySort runs pusher first; give it a pre-filled cluster instead:
    # victims pre-bound so the queue is just the pusher
    pods[0]["spec"]["nodeName"] = "n0"
    pods[1]["spec"]["nodeName"] = "n0"
    enc = encode_cluster(nodes, pods, defaults, policy=EXACT)
    full = BatchedScheduler(enc, record=True, strict=False)
    full.run()
    chunked = BatchedScheduler(
        encode_cluster(nodes, pods, defaults, policy=EXACT),
        record=True,
        strict=False,
    )
    chunked.run_chunked(chunk=1)
    fr, cr = full.results(), chunked.results()
    assert _records(fr) == _records(cr)
    assert any(r.status == "Nominated" for r in cr)
    assert full.placements() == chunked.placements()


def test_selective_decode_with_preemption_victim_ordering():
    # the skip path must still clear evicted victims' bind chronology so
    # later decoded pods order their victim lists correctly: decode ONLY
    # the second preemptor and compare with its record from a full decode
    defaults = SchedulerConfiguration.default()
    nodes = [node("n0", cpu="2"), node("n1", cpu="2")]
    pods = [
        pod("va", cpu="1", priority=1), pod("vb", cpu="1", priority=2),
        pod("vc", cpu="1", priority=1), pod("vd", cpu="1", priority=2),
        pod("pusher1", cpu="2", priority=100),
        pod("pusher2", cpu="2", priority=100),
    ]
    for i, nn in enumerate(["n0", "n0", "n1", "n1"]):
        pods[i]["spec"]["nodeName"] = nn
    enc = encode_cluster(nodes, pods, defaults, policy=EXACT)
    s = BatchedScheduler(enc, record=True, strict=False)
    s.run_chunked(chunk=1)
    full = {
        (r.pod_namespace, r.pod_name, r.status): r.to_annotations()
        for r in s.results()
    }
    only2 = [
        r for r in s.results(pods={("default", "pusher2")})
    ]
    assert only2, "pusher2 must decode"
    for r in only2:
        assert full[(r.pod_namespace, r.pod_name, r.status)] == r.to_annotations()
    assert any(r.status == "Nominated" for r in only2)


def test_selective_decode_matches_full():
    nodes = [node(f"n{i}") for i in range(4)]
    pods = [pod(f"p{i}", cpu=f"{100 + 50 * i}m") for i in range(9)]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    s = BatchedScheduler(enc, record=True)
    s.run()
    all_recs = {(r.pod_namespace, r.pod_name): r.to_annotations() for r in s.results()}
    subset = {("default", "p3"), ("default", "p7")}
    sel = s.results(pods=subset)
    assert {(r.pod_namespace, r.pod_name) for r in sel} == subset
    for r in sel:
        assert r.to_annotations() == all_recs[(r.pod_namespace, r.pod_name)]
