"""Randomized cross-feature parity fuzz: oracle vs engine, full default set.

Each seed generates a cluster mixing resources, zone/disk labels, node
selectors, pod affinity/anti-affinity, taints + tolerations, priorities
(preemption pressure included — the full default set enables
DefaultPreemption), topology spread, host ports, and image locality, then
asserts the vectorized engine reproduces the sequential oracle's complete
13-annotation wire record for every pod (`assert_parity`).

Random workloads are the cheap defense against correlated misreadings
between the oracle and the kernels (VERDICT r3 weak #4): both sides share
one author's reading of upstream, and hand-written cases only pin the
interactions that author thought of. Seeds are fixed so failures
reproduce; when one fails, minimize it into a named case in the relevant
test_engine_parity_* file.
"""

import random

import pytest

from kube_scheduler_simulator_tpu.engine.engine import supported_config

from helpers import node, pod
from test_engine_parity import assert_parity

ZONES = ("z0", "z1")
DISKS = ("ssd", "hdd")
APPS = ("a0", "a1", "a2")
IMAGES = ("img0", "img1", "img2", "img3")


def _rand_cluster(rng: random.Random, rel_scale: float = 1.0):
    """`rel_scale` widens the REQUIRED-affinity branches (anti 15% →
    15*s %, positive 12% → 12*s %): the adversarial carrier-density
    knob (VERDICT r4 weak #5 — the 22%-capacity-loss class lived at
    high carrier density, so the fuzz must keep visiting it)."""
    nodes = []
    for i in range(rng.randint(4, 10)):
        labels = {"zone": rng.choice(ZONES), "disk": rng.choice(DISKS)}
        kw = {}
        if rng.random() < 0.2:
            kw["taints"] = [
                {"key": "dedicated", "value": "infra", "effect": "NoSchedule"}
            ]
        if rng.random() < 0.1:
            kw["unschedulable"] = True
        if rng.random() < 0.5:
            kw["images"] = [
                {
                    "names": [rng.choice(IMAGES)],
                    "sizeBytes": rng.randint(10**6, 10**9),
                }
            ]
        nodes.append(
            node(
                f"n{i}",
                cpu=str(rng.randint(2, 8)),
                mem=f"{rng.randint(4, 16)}Gi",
                pods=str(rng.randint(8, 32)),
                labels=labels,
                **kw,
            )
        )
    pods_ = []
    for j in range(rng.randint(20, 40)):
        kw = {}
        if rng.random() < 0.3:
            kw["node_selector"] = {"disk": rng.choice(DISKS)}
        r = rng.random()
        if r < 0.15 * rel_scale:
            kw["affinity"] = {
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "labelSelector": {
                                "matchLabels": {"app": rng.choice(APPS)}
                            },
                            "topologyKey": "zone",
                        }
                    ]
                }
            }
        elif r < 0.27 * rel_scale:
            # required POSITIVE affinity — the class rel_serialize keeps
            # batched (monotone); sometimes self-matching (the
            # first-pod-in-series special case)
            want = rng.choice(APPS)
            kw["affinity"] = {
                "podAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "labelSelector": {"matchLabels": {"app": want}},
                            "topologyKey": "zone",
                        }
                    ]
                }
            }
            if rng.random() < 0.5:
                kw.setdefault("force_app", want)
        elif r < 0.27 * rel_scale + 0.13:
            kw["affinity"] = {
                "podAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "weight": rng.randint(1, 100),
                            "podAffinityTerm": {
                                "labelSelector": {
                                    "matchLabels": {"app": rng.choice(APPS)}
                                },
                                "topologyKey": "zone",
                            },
                        }
                    ]
                }
            }
        if rng.random() < 0.3:
            kw["tolerations"] = [
                {
                    "key": "dedicated",
                    "operator": "Exists",
                    "effect": "NoSchedule",
                }
            ]
        if rng.random() < 0.5:
            kw["priority"] = rng.choice((0, 10, 100))
        if rng.random() < 0.25:
            kw["spread"] = [
                {
                    "maxSkew": 1,
                    "topologyKey": "zone",
                    "whenUnsatisfiable": rng.choice(
                        ("DoNotSchedule", "ScheduleAnyway")
                    ),
                    "labelSelector": {"matchLabels": {"app": rng.choice(APPS)}},
                }
            ]
        if rng.random() < 0.25:
            # hostPort is what NodePorts conflicts key on (containerPort
            # alone can never conflict)
            kw["ports"] = [
                {"hostPort": rng.choice((80, 443, 8080)), "protocol": "TCP"}
            ]
        if rng.random() < 0.4:
            kw["images"] = [rng.choice(IMAGES)]
        app = kw.pop("force_app", None) or rng.choice(APPS)
        pods_.append(
            pod(
                f"p{j}",
                cpu=f"{rng.randint(100, 1500)}m",
                mem=f"{rng.randint(64, 2048)}Mi",
                labels={"app": app},
                **kw,
            )
        )
    return nodes, pods_


@pytest.mark.parametrize("policy_name", ["exact", "tpu32"])
@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_fuzz_full_default_set_parity(seed, policy_name):
    """Both dtype policies (VERDICT r4 weak #7: TPU32 — the policy that
    actually runs on the chip — previously got no fuzz). The generator
    is Mi/milli-granular throughout, where EXACT == TPU32 must hold
    bit-for-bit, so one oracle run pins both."""
    from kube_scheduler_simulator_tpu.engine import EXACT, TPU32

    rng = random.Random(seed)
    nodes, pods_ = _rand_cluster(rng)
    assert_parity(
        nodes,
        pods_,
        supported_config(),
        policy=EXACT if policy_name == "exact" else TPU32,
    )


@pytest.mark.parametrize("window", [None, 24])
@pytest.mark.parametrize(
    "seed,rel_scale",
    # rel_scale 2.5 ~ 37% anti-affinity carriers + 30% positive: the
    # adversarial density where the 22%-capacity-loss class lived
    [(2, 1.0), (4, 1.0), (2, 2.5), (4, 2.5)],
)
def test_fuzz_gang_invariants(seed, window, rel_scale):
    """The gang scheduler over the same random mixed-feature clusters:
    its divergence-policy invariants must survive arbitrary feature
    interactions, not just the hand-built contention shapes —
    determinism, node capacity (pod count), placements only on
    schedulable nodes, and nonzero progress whenever the sequential
    engine makes progress.

    The load-bearing pinned property is rel_serialize's soundness
    theorem, checked INDEPENDENTLY against the manifests: no bound
    pod's required anti-affinity is violated by any other bound pod in
    the final state. (Without queue-prefix batching, same-round commits
    could both bunch anti-affinity carriers across every zone — seed 2
    measured 22% fewer placements than sequential from the symmetric
    blocking that follows — and leave carriers whose requirement a
    same-round peer violated.)

    Deliberately NOT asserted: set or count equality vs the sequential
    engine — packing orders can strand capacity in either direction,
    and same-round topology-spread commits still read shared counts;
    the exact-parity claims live in the no-contention and
    all-pods-need-eviction tests (test_engine_gang.py)."""
    from collections import Counter

    import numpy as np

    from kube_scheduler_simulator_tpu.engine import TPU32, encode_cluster
    from kube_scheduler_simulator_tpu.engine.engine import BatchedScheduler
    from kube_scheduler_simulator_tpu.engine.gang import GangScheduler

    rng = random.Random(seed)
    nodes, pods_ = _rand_cluster(rng, rel_scale=rel_scale)
    cfg = supported_config()
    enc = encode_cluster(nodes, pods_, cfg, policy=TPU32)
    gang = GangScheduler(enc, chunk=16, eval_window=window)
    gang.run()
    got = gang.placements()
    again = GangScheduler(enc, chunk=16, eval_window=window)
    again.run()
    assert got == again.placements(), "gang must be deterministic"
    seq = BatchedScheduler(
        encode_cluster(nodes, pods_, cfg, policy=TPU32), record=False
    )
    seq.run()
    n_gang = sum(1 for v in got.values() if v)
    n_seq = sum(
        1 for v in seq._final_state.assignment[np.asarray(enc.queue)] if v >= 0
    )
    if n_seq > 0:
        assert n_gang > 0, (n_gang, n_seq)

    zone = {
        n["metadata"]["name"]: n["metadata"]["labels"]["zone"] for n in nodes
    }
    by_name = {p["metadata"]["name"]: p for p in pods_}

    # soundness (see docstring): recheck REQUIRED terms over the final
    # placements by hand — generator terms are all
    # {matchLabels: {app: X}, topologyKey: zone}. Anti-affinity: no
    # matching peer may share the pod's zone. Positive affinity: some
    # matching pod (self included — the bound pod itself satisfies a
    # self-matching series) must share it.
    def violations(placed: dict) -> list:
        def matching_in_zone(want_app, z, exclude=None):
            return [
                name2
                for (ns2, name2), nn2 in placed.items()
                if nn2
                and name2 != exclude
                and by_name[name2]["metadata"]["labels"].get("app") == want_app
                and zone[nn2] == z
            ]

        out = []
        for (ns, name), nn in placed.items():
            if not nn:
                continue
            aff = by_name[name]["spec"].get("affinity", {})
            for t in aff.get("podAntiAffinity", {}).get(
                "requiredDuringSchedulingIgnoredDuringExecution", []
            ):
                want = t["labelSelector"]["matchLabels"]["app"]
                hits = matching_in_zone(want, zone[nn], exclude=name)
                if hits:
                    out.append(("anti", name, hits[0], want, zone[nn]))
            for t in aff.get("podAffinity", {}).get(
                "requiredDuringSchedulingIgnoredDuringExecution", []
            ):
                want = t["labelSelector"]["matchLabels"]["app"]
                if not matching_in_zone(want, zone[nn]):
                    out.append(("affinity", name, None, want, zone[nn]))
        return out

    assert violations(got) == [], violations(got)[:5]
    # the sequential engine satisfies the same property by construction
    sp = enc.decode_assignment(seq._final_state.assignment)
    in_q = {k for k in got}
    assert violations({k: v for k, v in sp.items() if k in in_q}) == []

    # the REMAINING same-round divergence class, measured (not
    # asserted): hard topology-spread constraints evaluated against
    # round-start counts can exceed maxSkew once same-round peers land.
    # Even the sequential engine shows nonzero final-state excess
    # (upstream's check is at-schedule-time only; later selector-
    # matching pods shift counts unchecked), so this is a report, not an
    # invariant. Measured on these seeds: gang 0/0, sequential 1/0.
    # Caveat: min is taken over all ZONES, not k8s's eligible-domain
    # set, so a matching app pinned off one zone reads as excess here
    # that real DoNotSchedule semantics would not count — fine for a
    # conservative report.
    def spread_violations(placed: dict) -> int:
        n_viol = 0
        for (ns, name), nn in placed.items():
            if not nn:
                continue
            for c in by_name[name]["spec"].get(
                "topologySpreadConstraints", []
            ):
                if c["whenUnsatisfiable"] != "DoNotSchedule":
                    continue
                want = c["labelSelector"]["matchLabels"]["app"]
                counts = {z: 0 for z in ZONES}
                for (ns2, name2), nn2 in placed.items():
                    if nn2 and by_name[name2]["metadata"]["labels"].get(
                        "app"
                    ) == want:
                        counts[zone[nn2]] += 1
                skew = counts[zone[nn]] - min(counts.values())
                if skew > c["maxSkew"]:
                    n_viol += 1
        return n_viol

    print(
        f"seed {seed}: final-state hard-spread skew excess — gang "
        f"{spread_violations(got)}, sequential "
        f"{spread_violations({k: v for k, v in sp.items() if k in in_q})}"
    )

    per_node = Counter(v for v in got.values() if v)
    caps = {
        n["metadata"]["name"]: int(n["status"]["allocatable"]["pods"])
        for n in nodes
    }
    unsched = {
        n["metadata"]["name"]
        for n in nodes
        if n["spec"].get("unschedulable")
    }
    assert all(per_node[nn] <= caps[nn] for nn in per_node)
    assert not (set(per_node) & unsched), "placed onto unschedulable node"

    # the static loop with carriers places exactly like the dynamic one
    # (equal inner depth — pins the carrier epilogue in the scan path)
    stat = GangScheduler(enc, chunk=16, loop="static")
    stat.run()
    assert stat.placements() == got

    # rel_serialize=False is the documented batched-with-divergence
    # escape hatch: deterministic and capacity-safe, soundness NOT
    # guaranteed (that's the trade)
    loose = GangScheduler(enc, chunk=16, rel_serialize=False)
    assert loose.rel_serialize is False
    loose.run()
    lp = loose.placements()
    loose2 = GangScheduler(enc, chunk=16, rel_serialize=False)
    loose2.run()
    assert lp == loose2.placements()
    per_node_l = Counter(v for v in lp.values() if v)
    assert all(per_node_l[nn] <= caps[nn] for nn in per_node_l)


@pytest.mark.parametrize("policy_name", ["exact", "tpu32"])
@pytest.mark.parametrize("seed", [11, 12, 13])
def test_fuzz_volume_stack_parity(seed, policy_name):
    """The volume kernel family under random pressure: bound and unbound
    PVCs across Immediate/WaitForFirstConsumer storage classes, PV node
    affinity pinning volumes to zones, shared access modes (incl.
    ReadWriteOncePod single-winner claims), and more claimants than
    volumes — against the full default set so VolumeBinding/Zone/
    Restrictions/limits all run. Both dtype policies (VERDICT r4 #9)."""
    from kube_scheduler_simulator_tpu.engine import EXACT, TPU32

    from test_engine_parity_vol import claim_vol, pv, pvc, storageclass

    policy = EXACT if policy_name == "exact" else TPU32

    rng = random.Random(seed)
    nodes = [
        node(f"n{i}", cpu="8", labels={"zone": rng.choice(ZONES)})
        for i in range(rng.randint(3, 6))
    ]
    scs = [storageclass("fast"), storageclass("lazy", mode="WaitForFirstConsumer")]
    pvs, pvcs, pods_ = [], [], []
    for k in range(rng.randint(4, 8)):
        sc = rng.choice(("fast", "lazy"))
        zone = rng.choice(ZONES)
        aff = (
            {
                "required": {
                    "nodeSelectorTerms": [
                        {
                            "matchExpressions": [
                                {
                                    "key": "zone",
                                    "operator": "In",
                                    "values": [zone],
                                }
                            ]
                        }
                    ]
                }
            }
            if rng.random() < 0.5
            else None
        )
        modes = rng.choice(
            (("ReadWriteOnce",), ("ReadWriteMany",), ("ReadWriteOncePod",))
        )
        pvs.append(pv(f"pv{k}", sc=sc, modes=modes, node_affinity=aff))
        pvcs.append(
            pvc(
                f"c{k}",
                sc=sc,
                modes=modes,
                volume_name=f"pv{k}" if rng.random() < 0.6 else None,
            )
        )
    for j in range(rng.randint(8, 16)):
        kw = {}
        if rng.random() < 0.7:
            claims = rng.sample(range(len(pvcs)), k=rng.choice((1, 1, 2)))
            kw["volumes"] = [claim_vol(f"c{k}") for k in claims]
        if rng.random() < 0.4:
            kw["priority"] = rng.choice((0, 50))
        pods_.append(pod(f"p{j}", cpu=f"{rng.randint(100, 900)}m", **kw))
    assert_parity(
        nodes,
        pods_,
        supported_config(),
        policy=policy,
        pvcs=pvcs,
        pvs=pvs,
        storageclasses=scs,
    )
