"""Randomized cross-feature parity fuzz: oracle vs engine, full default set.

Each seed generates a cluster mixing resources, zone/disk labels, node
selectors, pod affinity/anti-affinity, taints + tolerations, priorities
(preemption pressure included — the full default set enables
DefaultPreemption), topology spread, host ports, and image locality, then
asserts the vectorized engine reproduces the sequential oracle's complete
13-annotation wire record for every pod (`assert_parity`).

Random workloads are the cheap defense against correlated misreadings
between the oracle and the kernels (VERDICT r3 weak #4): both sides share
one author's reading of upstream, and hand-written cases only pin the
interactions that author thought of. Seeds are fixed so failures
reproduce; when one fails, minimize it into a named case in the relevant
test_engine_parity_* file.
"""

import random

import pytest

from kube_scheduler_simulator_tpu.engine.engine import supported_config

from helpers import node, pod
from test_engine_parity import assert_parity

ZONES = ("z0", "z1")
DISKS = ("ssd", "hdd")
APPS = ("a0", "a1", "a2")
IMAGES = ("img0", "img1", "img2", "img3")


def _rand_cluster(rng: random.Random):
    nodes = []
    for i in range(rng.randint(4, 10)):
        labels = {"zone": rng.choice(ZONES), "disk": rng.choice(DISKS)}
        kw = {}
        if rng.random() < 0.2:
            kw["taints"] = [
                {"key": "dedicated", "value": "infra", "effect": "NoSchedule"}
            ]
        if rng.random() < 0.1:
            kw["unschedulable"] = True
        if rng.random() < 0.5:
            kw["images"] = [
                {
                    "names": [rng.choice(IMAGES)],
                    "sizeBytes": rng.randint(10**6, 10**9),
                }
            ]
        nodes.append(
            node(
                f"n{i}",
                cpu=str(rng.randint(2, 8)),
                mem=f"{rng.randint(4, 16)}Gi",
                pods=str(rng.randint(8, 32)),
                labels=labels,
                **kw,
            )
        )
    pods_ = []
    for j in range(rng.randint(20, 40)):
        kw = {}
        if rng.random() < 0.3:
            kw["node_selector"] = {"disk": rng.choice(DISKS)}
        r = rng.random()
        if r < 0.2:
            kw["affinity"] = {
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "labelSelector": {
                                "matchLabels": {"app": rng.choice(APPS)}
                            },
                            "topologyKey": "zone",
                        }
                    ]
                }
            }
        elif r < 0.35:
            kw["affinity"] = {
                "podAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "weight": rng.randint(1, 100),
                            "podAffinityTerm": {
                                "labelSelector": {
                                    "matchLabels": {"app": rng.choice(APPS)}
                                },
                                "topologyKey": "zone",
                            },
                        }
                    ]
                }
            }
        if rng.random() < 0.3:
            kw["tolerations"] = [
                {
                    "key": "dedicated",
                    "operator": "Exists",
                    "effect": "NoSchedule",
                }
            ]
        if rng.random() < 0.5:
            kw["priority"] = rng.choice((0, 10, 100))
        if rng.random() < 0.25:
            kw["spread"] = [
                {
                    "maxSkew": 1,
                    "topologyKey": "zone",
                    "whenUnsatisfiable": rng.choice(
                        ("DoNotSchedule", "ScheduleAnyway")
                    ),
                    "labelSelector": {"matchLabels": {"app": rng.choice(APPS)}},
                }
            ]
        if rng.random() < 0.25:
            # hostPort is what NodePorts conflicts key on (containerPort
            # alone can never conflict)
            kw["ports"] = [
                {"hostPort": rng.choice((80, 443, 8080)), "protocol": "TCP"}
            ]
        if rng.random() < 0.4:
            kw["images"] = [rng.choice(IMAGES)]
        pods_.append(
            pod(
                f"p{j}",
                cpu=f"{rng.randint(100, 1500)}m",
                mem=f"{rng.randint(64, 2048)}Mi",
                labels={"app": rng.choice(APPS)},
                **kw,
            )
        )
    return nodes, pods_


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_fuzz_full_default_set_parity(seed):
    rng = random.Random(seed)
    nodes, pods_ = _rand_cluster(rng)
    assert_parity(nodes, pods_, supported_config())


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_fuzz_volume_stack_parity(seed):
    """The volume kernel family under random pressure: bound and unbound
    PVCs across Immediate/WaitForFirstConsumer storage classes, PV node
    affinity pinning volumes to zones, shared access modes (incl.
    ReadWriteOncePod single-winner claims), and more claimants than
    volumes — against the full default set so VolumeBinding/Zone/
    Restrictions/limits all run."""
    from test_engine_parity_vol import claim_vol, pv, pvc, storageclass

    rng = random.Random(seed)
    nodes = [
        node(f"n{i}", cpu="8", labels={"zone": rng.choice(ZONES)})
        for i in range(rng.randint(3, 6))
    ]
    scs = [storageclass("fast"), storageclass("lazy", mode="WaitForFirstConsumer")]
    pvs, pvcs, pods_ = [], [], []
    for k in range(rng.randint(4, 8)):
        sc = rng.choice(("fast", "lazy"))
        zone = rng.choice(ZONES)
        aff = (
            {
                "required": {
                    "nodeSelectorTerms": [
                        {
                            "matchExpressions": [
                                {
                                    "key": "zone",
                                    "operator": "In",
                                    "values": [zone],
                                }
                            ]
                        }
                    ]
                }
            }
            if rng.random() < 0.5
            else None
        )
        modes = rng.choice(
            (("ReadWriteOnce",), ("ReadWriteMany",), ("ReadWriteOncePod",))
        )
        pvs.append(pv(f"pv{k}", sc=sc, modes=modes, node_affinity=aff))
        pvcs.append(
            pvc(
                f"c{k}",
                sc=sc,
                modes=modes,
                volume_name=f"pv{k}" if rng.random() < 0.6 else None,
            )
        )
    for j in range(rng.randint(8, 16)):
        kw = {}
        if rng.random() < 0.7:
            claims = rng.sample(range(len(pvcs)), k=rng.choice((1, 1, 2)))
            kw["volumes"] = [claim_vol(f"c{k}") for k in claims]
        if rng.random() < 0.4:
            kw["priority"] = rng.choice((0, 50))
        pods_.append(pod(f"p{j}", cpu=f"{rng.randint(100, 900)}m", **kw))
    assert_parity(
        nodes,
        pods_,
        supported_config(),
        pvcs=pvcs,
        pvs=pvs,
        storageclasses=scs,
    )
