"""Gang (fixpoint) scheduler: correctness, determinism, divergence policy.

Gang mode (engine/gang.py) trades the sequential engine's bit-parity for
round-parallel throughput. Its contract (documented in the module):

  * one pod per node commits per round, earliest queue position wins;
  * committed placements are always feasible against the state they were
    evaluated on, and node-local constraints (resources, ports) can never
    be violated by same-round peers;
  * unschedulable pods are retried next round (the event-driven re-queue
    analogue), so affinity chains resolve across rounds;
  * no-contention workloads place identically to the sequential engine.
"""

import numpy as np
import pytest

from kube_scheduler_simulator_tpu.engine import (
    EXACT,
    BatchedScheduler,
    encode_cluster,
)
from kube_scheduler_simulator_tpu.engine.gang import GangScheduler

from helpers import node, pod
from test_engine_parity import restricted_config


def _placements(sched):
    sched.run()
    return sched.placements()


def test_no_contention_matches_sequential():
    # each pod nodeSelector-pinned to its own node: one round, and the
    # placements must equal the sequential engine's exactly
    nodes = [node(f"n{i}", labels={"k": f"v{i}"}) for i in range(6)]
    pods = [pod(f"p{i}", node_selector={"k": f"v{i}"}) for i in range(6)]
    cfg = restricted_config(
        filters=("NodeUnschedulable", "NodeName", "NodeAffinity", "NodeResourcesFit"),
    )
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    gang = GangScheduler(enc)
    seq = BatchedScheduler(encode_cluster(nodes, pods, cfg, policy=EXACT), record=False)
    assert _placements(gang) == _placements(seq)
    assert int(np.asarray(gang._rounds)) == 2  # 1 committing + 1 empty


def test_contended_node_priority_order_and_capacity():
    # 4 pods all fit only n0 (n1 unschedulable); n0 holds exactly 2.
    # Queue order (PrioritySort) must win the contention rounds.
    nodes = [node("n0", cpu="2"), node("n1", cpu="8", unschedulable=True)]
    pods = [
        pod("lo1", cpu="1", priority=1),
        pod("hi", cpu="1", priority=10),
        pod("lo2", cpu="1", priority=1),
        pod("lo3", cpu="1", priority=1),
    ]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    gang = GangScheduler(enc)
    got = _placements(gang)
    assert got[("default", "hi")] == "n0"
    # exactly one of the priority-1 pods (the earliest in queue order,
    # which is input order among equals) fits next
    assert got[("default", "lo1")] == "n0"
    assert got[("default", "lo2")] == ""
    assert got[("default", "lo3")] == ""
    # matches the sequential engine bit-for-bit on this workload
    seq = BatchedScheduler(encode_cluster(nodes, pods, cfg, policy=EXACT), record=False)
    assert got == _placements(seq)


def test_random_cluster_contended_invariants():
    # moderately contended random cluster: under contention gang is a
    # deterministic greedy fixpoint, not sequential-identical (gang.py
    # divergence policy) — but it must (a) never violate capacity,
    # (b) schedule at least as many pods as the sequential pass (losers
    # are retried), (c) be deterministic.
    rng = np.random.default_rng(3)
    nodes = [node(f"n{i}", cpu=str(2 + int(rng.integers(3)))) for i in range(8)]
    pods = [
        pod(f"p{i}", cpu=f"{int(rng.integers(200, 900))}m",
            priority=int(rng.integers(3)))
        for i in range(40)
    ]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    gang = GangScheduler(enc, chunk=16)
    seq = BatchedScheduler(encode_cluster(nodes, pods, cfg, policy=EXACT), record=False)
    g, s = _placements(gang), _placements(seq)
    assert sum(1 for v in g.values() if v) >= sum(1 for v in s.values() if v)
    assert g == _placements(GangScheduler(enc, chunk=16))
    # capacity safety, independently recomputed
    used = {}
    for (ns, name), nn in g.items():
        if nn:
            p = next(pp for pp in pods if pp["metadata"]["name"] == name)
            req = p["spec"]["containers"][0]["resources"]["requests"]["cpu"]
            used[nn] = used.get(nn, 0) + int(req[:-1])
    for n_, total in used.items():
        alloc = next(nn for nn in nodes if nn["metadata"]["name"] == n_)
        assert total <= int(alloc["status"]["allocatable"]["cpu"]) * 1000


def test_determinism():
    rng = np.random.default_rng(7)
    nodes = [node(f"n{i}") for i in range(5)]
    pods = [pod(f"p{i}", cpu=f"{int(rng.integers(100, 500))}m") for i in range(20)]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    a = _placements(GangScheduler(enc))
    b = _placements(GangScheduler(enc))
    assert a == b


def test_affinity_chain_resolves_across_rounds():
    # backend requires affinity to frontend, but frontend sits LATER in
    # the queue (lower priority listed first in input order? — no:
    # equal priority, input order backend-first). Sequential: backend
    # fails (peer not bound yet). Gang: backend schedules in round 2 —
    # the documented retry divergence.
    nodes = [node(f"n{i}", labels={"zone": "z"}) for i in range(2)]
    aff = {
        "podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {
                    "labelSelector": {"matchLabels": {"app": "frontend"}},
                    "topologyKey": "zone",
                }
            ]
        }
    }
    pods = [
        pod("backend", affinity=aff),
        pod("frontend", labels={"app": "frontend"}),
    ]
    cfg = restricted_config(
        filters=("NodeUnschedulable", "NodeResourcesFit", "InterPodAffinity"),
        prefilters=("NodeResourcesFit", "InterPodAffinity"),
    )
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    gang = GangScheduler(enc)
    got = _placements(gang)
    assert got[("default", "frontend")] != ""
    assert got[("default", "backend")] != ""  # retried after peer bound
    seq = BatchedScheduler(encode_cluster(nodes, pods, cfg, policy=EXACT), record=False)
    assert _placements(seq)[("default", "backend")] == ""  # sequential can't


def test_infeasible_pods_terminate_quickly():
    nodes = [node("n0", cpu="1")]
    pods = [pod(f"p{i}", cpu="4") for i in range(10)]  # none fit
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    gang = GangScheduler(enc)
    state, rounds = gang.run()
    assert int(np.asarray(rounds)) == 1  # one empty round, then fixpoint
    assert all(v == "" for v in gang.placements().values())


def test_weight_sweep_vmap_matches_per_variant_runs():
    import jax
    import jax.numpy as jnp

    nodes = [node(f"n{i}", cpu=str(2 + i % 3)) for i in range(6)]
    pods = [pod(f"p{i}", cpu=f"{300 + 40 * (i % 5)}m") for i in range(18)]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    gang = GangScheduler(enc, chunk=8)
    order, _ = gang.order_arrays()
    wbase = np.asarray(gang.weights)
    variants = jnp.asarray(np.stack([wbase, wbase * 3, wbase + 7]), wbase.dtype)
    vrun = jax.jit(jax.vmap(gang.run_fn, in_axes=(None, None, None, 0)))
    vstate, vrounds = vrun(enc.arrays, enc.state0, order, variants)
    for i in range(3):
        state_i, _ = jax.jit(gang.run_fn)(
            enc.arrays, enc.state0, order, variants[i]
        )
        np.testing.assert_array_equal(
            np.asarray(vstate.assignment[i]), np.asarray(state_i.assignment)
        )


def test_rwop_claim_single_winner_per_round():
    # cluster-global constraint: two pods share a ReadWriteOncePod claim
    # and could win DIFFERENT nodes in the same round — the per-claim
    # conflict resolution must let exactly one commit (the earlier in
    # queue order), and the next round must reject the other
    from test_engine_parity_vol import claim_vol, pv, pvc, vol_config

    nodes = [node("n0"), node("n1")]
    pods = [
        pod("first", priority=10, volumes=[claim_vol("solo")]),
        pod("second", priority=1, volumes=[claim_vol("solo")]),
    ]
    kw = dict(
        pvcs=[pvc("solo", modes=("ReadWriteOncePod",), volume_name="pv-s")],
        pvs=[pv("pv-s")],
    )
    cfg = vol_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT, **kw)
    gang = GangScheduler(enc)
    got = _placements(gang)
    assert got[("default", "first")] != ""
    assert got[("default", "second")] == ""
    # matches the sequential engine on this workload
    seq = BatchedScheduler(
        encode_cluster(nodes, pods, cfg, policy=EXACT, **kw), record=False
    )
    assert got == _placements(seq)


def test_static_loop_matches_dynamic():
    # the counted-loop (scan-only) variant must place identically to the
    # while_loop variant — no-op rounds past the fixpoint change nothing
    rng = np.random.default_rng(11)
    nodes = [node(f"n{i}", cpu=str(2 + int(rng.integers(3)))) for i in range(6)]
    pods = [
        pod(f"p{i}", cpu=f"{int(rng.integers(200, 800))}m") for i in range(30)
    ]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    # equal inner depth => provably identical placements (gang.py note)
    dyn = GangScheduler(enc, chunk=16, inner_iters=12)
    stat = GangScheduler(enc, chunk=16, loop="static", inner_iters=12)
    assert _placements(dyn) == _placements(stat)
    # rounds reported = rounds that committed something
    assert int(np.asarray(stat._rounds)) == int(np.asarray(dyn._rounds)) - 1


def test_static_loop_rwop_claims():
    from test_engine_parity_vol import claim_vol, pv, pvc, vol_config

    nodes = [node("n0"), node("n1")]
    pods = [
        pod("first", priority=10, volumes=[claim_vol("solo")]),
        pod("second", priority=1, volumes=[claim_vol("solo")]),
    ]
    kw = dict(
        pvcs=[pvc("solo", modes=("ReadWriteOncePod",), volume_name="pv-s")],
        pvs=[pv("pv-s")],
    )
    enc = encode_cluster(nodes, pods, vol_config(), policy=EXACT, **kw)
    got = _placements(GangScheduler(enc, loop="static"))
    assert got[("default", "first")] != ""
    assert got[("default", "second")] == ""


def test_full_default_config_runs_preemption_phase():
    from kube_scheduler_simulator_tpu.engine.engine import supported_config

    nodes = [node(f"n{i}") for i in range(3)]
    pods = [pod(f"p{i}") for i in range(5)]
    enc = encode_cluster(nodes, pods, supported_config(), policy=EXACT)
    gang = GangScheduler(enc)
    # DefaultPreemption has a kernel and runs as the fixpoint phase now;
    # nothing in the default set is skipped
    assert gang.skipped_postfilter == []
    assert gang.preempt_phase_fn is not None
    got = _placements(gang)
    assert all(v != "" for v in got.values())


def _preempt_cfg():
    from test_engine_parity_preempt import preempt_config

    return preempt_config()


def test_preempt_phase_matches_sequential_when_all_pending_need_eviction():
    """Every incoming pod needs preemption (nodes pre-filled by bound
    low-priority pods), so the gang rounds commit nothing and the preempt
    phase IS a sequential pass — placements must match the sequential
    engine exactly, victims included."""
    nodes = [node(f"n{i}", cpu="2", pods="8") for i in range(4)]
    pods = [
        pod(f"low-{i}", cpu="1500m", priority=1, node_name=f"n{i}")
        for i in range(4)
    ] + [pod(f"high-{i}", cpu="1200m", priority=100) for i in range(3)]
    cfg = _preempt_cfg()
    gang = GangScheduler(encode_cluster(nodes, pods, cfg, policy=EXACT))
    seq = BatchedScheduler(
        encode_cluster(nodes, pods, cfg, policy=EXACT), record=False
    )
    gg, ss = _placements(gang), _placements(seq)
    assert gg == ss
    # preemption actually happened: some high pod is placed
    assert any(gg[("default", f"high-{i}")] != "" for i in range(3))
    # and the full [P] assignment (incl. the pre-bound victims, which are
    # not in the queue/placements view) matches the sequential engine's —
    # evicted victims read -1 in both
    np.testing.assert_array_equal(
        np.asarray(gang._final_state.assignment),
        np.asarray(seq._final_state.assignment),
    )
    assert int((np.asarray(gang._final_state.assignment) < 0).sum()) > 0


def test_preempt_phase_then_rounds_resume():
    """After evictions, pods that lost earlier rounds can fill freed
    capacity: the phase loop must resume rounds and land everything that
    fits."""
    # n0/n1 full of low-priority load; two high pods must preempt, and
    # one unpinned small pod schedules normally in round 1
    nodes = [node("n0", cpu="2", pods="8"), node("n1", cpu="2", pods="8")]
    pods = [
        pod("low-0", cpu="1800m", priority=1, node_name="n0"),
        pod("low-1", cpu="1800m", priority=1, node_name="n1"),
        pod("high-0", cpu="1500m", priority=100),
        pod("high-1", cpu="1500m", priority=100),
    ]
    cfg = _preempt_cfg()
    gang = GangScheduler(encode_cluster(nodes, pods, cfg, policy=EXACT))
    got = _placements(gang)
    assert got[("default", "high-0")] != ""
    assert got[("default", "high-1")] != ""
    assert {got[("default", "high-0")], got[("default", "high-1")]} == {
        "n0",
        "n1",
    }


def test_preempt_phase_static_loop():
    nodes = [node(f"n{i}", cpu="2", pods="8") for i in range(4)]
    pods = [
        pod(f"low-{i}", cpu="1500m", priority=1, node_name=f"n{i}")
        for i in range(4)
    ] + [pod(f"high-{i}", cpu="1200m", priority=100) for i in range(3)]
    cfg = _preempt_cfg()
    stat = GangScheduler(encode_cluster(nodes, pods, cfg, policy=EXACT), loop="static")
    seq = BatchedScheduler(
        encode_cluster(nodes, pods, cfg, policy=EXACT), record=False
    )
    assert _placements(stat) == _placements(seq)


def test_divergence_rate_quantified_on_contended_hotspot():
    """VERDICT r3 #8: put a number on the gang-vs-sequential placement
    divergence under contention. A BASELINE-shaped hotspot — every pod
    competes for the same few nodes (scarce resources force losers to
    fall back every round) — is the worst case for the documented
    "deterministic greedy fixpoint" divergence. The test asserts the
    structural invariants that must survive divergence, and bounds the
    divergence rate so a regression (e.g. a matching bug that scrambles
    priority order) shows up as a number, not a vibe."""
    import json

    from kube_scheduler_simulator_tpu.synth import synthetic_cluster

    from collections import Counter

    cfg = restricted_config()

    def measure(n_nodes, n_pods, seed):
        nodes, pods = synthetic_cluster(n_nodes, n_pods, seed=seed)
        gang = GangScheduler(encode_cluster(nodes, pods, cfg, policy=EXACT))
        seq = BatchedScheduler(
            encode_cluster(nodes, pods, cfg, policy=EXACT), record=False
        )
        gg, ss = _placements(gang), _placements(seq)
        assert set(gg) == set(ss)
        # invariant 1: scheduled/unschedulable sets agree (feasibility is
        # order-independent on a resources-only config at fixpoint)
        diff_sched = {k for k in gg if bool(gg[k]) != bool(ss[k])}
        assert not diff_sched, f"schedulability diverged: {sorted(diff_sched)[:5]}"
        # invariant 2: node-local capacity never violated by gang commits
        per_node = Counter(v for v in gg.values() if v)
        caps = {
            n["metadata"]["name"]: int(n["status"]["allocatable"]["pods"])
            for n in nodes
        }
        assert all(per_node[n] <= caps[n] for n in per_node)
        moved = sum(1 for k in gg if gg[k] != ss[k]) / len(gg)
        # distribution distance: how different the per-node pod COUNTS
        # are (L1 / pods) — per-pod identity can reshuffle while the
        # shape of the schedule stays close
        sq = Counter(v for v in ss.values() if v)
        dist = sum(
            abs(per_node[k] - sq[k]) for k in set(per_node) | set(sq)
        ) / len(gg)
        return moved, dist

    # Measured on these exact workloads (seed-pinned): under ANY
    # contention the two greedy orders disagree on most per-pod
    # identities (~0.93 moved) — sequential chains each choice on all
    # prior binds, gang commits one pod per node per round — while
    # schedulability matches exactly and the per-node count distribution
    # stays much closer (hotspot distL1 ~0.17: contention pins the
    # shape; moderate ~0.59: many near-tie nodes to spread over). These
    # are the numbers behind the module's "deterministic greedy
    # fixpoint" divergence policy (VERDICT r3 #8).
    moved_m, dist_m = measure(64, 128, seed=13)   # ~2 pods/node
    moved_h, dist_h = measure(24, 256, seed=13)   # ~10.7 pods/node
    print(
        "gang placement divergence vs sequential: "
        + json.dumps(
            {
                "moderate(64nx128p)": {"moved": round(moved_m, 4), "distL1": round(dist_m, 4)},
                "hotspot(24nx256p)": {"moved": round(moved_h, 4), "distL1": round(dist_h, 4)},
            }
        )
    )
    # regression bounds just above the measured values: a matching bug
    # that breaks priority order or double-commits shows up here
    assert dist_h <= 0.30, f"hotspot distribution divergence: {dist_h:.3f}"
    assert dist_m <= 0.75, f"moderate distribution divergence: {dist_m:.3f}"
    assert moved_m < 1.0 and moved_h < 1.0


@pytest.mark.parametrize("use_mesh", [False, True], ids=["unsharded", "mesh"])
def test_gang_sweep_runs_preemption_per_variant(use_mesh):
    """GangSweep must not silently drop the preempt phase — unsharded AND
    mesh-sharded (dp over 'replicas' x node shards, the vmapped phase):
    every variant of a preemption-requiring workload must match a
    single-variant GangScheduler run with those weights (which itself
    matches the sequential engine on this all-pods-need-eviction
    shape)."""
    from kube_scheduler_simulator_tpu.parallel import GangSweep, build_mesh
    from kube_scheduler_simulator_tpu.parallel.sweep import weights_for

    mesh = build_mesh(8) if use_mesh else None  # 4 replicas x 2 node shards
    cap = 4 * mesh.shape["nodes"] if mesh else None
    nodes = [node(f"n{i}", cpu="2", pods="8") for i in range(4)]
    pods = [
        pod(f"low-{i}", cpu="1500m", priority=1, node_name=f"n{i}")
        for i in range(4)
    ] + [pod(f"high-{i}", cpu="1200m", priority=100) for i in range(3)]
    cfg = _preempt_cfg()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT, node_capacity=cap)
    sweep = GangSweep(enc, mesh=mesh, chunk=16)
    variants = [{}, {"NodeResourcesFit": 5}, {}, {"NodeResourcesFit": 7}]
    w = np.stack([weights_for(enc, ov) for ov in variants])
    assignments, _ = sweep.run(w)
    solo = GangScheduler(
        encode_cluster(nodes, pods, cfg, policy=EXACT, node_capacity=cap),
        chunk=16,
    )
    for v, ov in enumerate(variants):
        solo.run(weights=np.asarray(weights_for(enc, ov), dtype=np.int32))
        np.testing.assert_array_equal(
            np.asarray(assignments)[v],
            np.asarray(solo._final_state.assignment),
            err_msg=f"variant {v}",
        )
    # preemption really fired: every variant placed all three high pods
    placements = sweep.placements(assignments)
    for d in placements:
        assert all(d[("default", f"high-{i}")] != "" for i in range(3))


def test_match_width_topk_uncontended_equals_full():
    # pinned pods: every pod commits on its single feasible node, so even
    # the narrowest candidate list (k=1) must reproduce full-width
    # matching (and therefore the sequential engine) exactly
    nodes = [node(f"n{i}", labels={"k": f"v{i}"}) for i in range(6)]
    pods = [pod(f"p{i}", node_selector={"k": f"v{i}"}) for i in range(6)]
    cfg = restricted_config(
        filters=("NodeUnschedulable", "NodeName", "NodeAffinity", "NodeResourcesFit"),
    )
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    narrow = GangScheduler(enc, match_width=1)
    full = GangScheduler(enc, match_width=len(nodes))
    assert narrow.match_width == 1 and full.match_width == 6
    assert _placements(narrow) == _placements(full)


def test_match_width_topk_contended_invariants():
    # contended random cluster with a narrow candidate list: losers whose
    # whole list is consumed wait a round (documented depth semantics) —
    # the fixpoint must still fill the cluster exactly as deep as
    # full-width matching does (feasibility at fixpoint is depth-
    # independent on a resources config), deterministically
    rng = np.random.default_rng(9)
    nodes = [node(f"n{i}", cpu=str(2 + int(rng.integers(3)))) for i in range(8)]
    pods = [
        pod(f"p{i}", cpu=f"{int(rng.integers(200, 900))}m") for i in range(40)
    ]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    topk = GangScheduler(enc, chunk=16, match_width=2)
    full = GangScheduler(enc, chunk=16)
    g, f = _placements(topk), _placements(full)
    assert sum(1 for v in g.values() if v) == sum(1 for v in f.values() if v)
    assert g == _placements(GangScheduler(enc, chunk=16, match_width=2))
    # static loop with the same width places like its own dynamic loop
    stat = GangScheduler(
        enc, chunk=16, match_width=2, loop="static", inner_iters=64
    )
    assert _placements(stat) == g


def test_match_width_rwop_claims():
    # the per-claim conflict resolution must survive the top-k rewrite
    from test_engine_parity_vol import claim_vol, pv, pvc, vol_config

    nodes = [node("n0"), node("n1")]
    pods = [
        pod("first", priority=10, volumes=[claim_vol("solo")]),
        pod("second", priority=1, volumes=[claim_vol("solo")]),
    ]
    kw = dict(
        pvcs=[pvc("solo", modes=("ReadWriteOncePod",), volume_name="pv-s")],
        pvs=[pv("pv-s")],
    )
    enc = encode_cluster(nodes, pods, vol_config(), policy=EXACT, **kw)
    got = _placements(GangScheduler(enc, match_width=1))
    assert got[("default", "first")] != ""
    assert got[("default", "second")] == ""


def test_hybrid_inner_loop_matches_pure_static():
    # static outer scan + while-loop matching (the chip-latency hybrid):
    # equal inner depth => identical placements to the all-scan program
    rng = np.random.default_rng(23)
    nodes = [node(f"n{i}", cpu=str(2 + int(rng.integers(3)))) for i in range(6)]
    pods = [
        pod(f"p{i}", cpu=f"{int(rng.integers(200, 800))}m") for i in range(30)
    ]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    pure = GangScheduler(enc, chunk=8, loop="static", inner_iters=12)
    hybrid = GangScheduler(
        enc, chunk=8, loop="static", inner_iters=12, inner_loop="dynamic"
    )
    assert hybrid.inner_loop == "dynamic" and hybrid.loop == "static"
    assert _placements(pure) == _placements(hybrid)
    # and the preemption phase still composes
    nodes2 = [node(f"m{i}", cpu="2", pods="8") for i in range(4)]
    pods2 = [
        pod(f"low-{i}", cpu="1500m", priority=1, node_name=f"m{i}")
        for i in range(4)
    ] + [pod(f"high-{i}", cpu="1200m", priority=100) for i in range(3)]
    cfg2 = _preempt_cfg()
    hyb2 = GangScheduler(
        encode_cluster(nodes2, pods2, cfg2, policy=EXACT),
        loop="static", inner_loop="dynamic",
    )
    seq2 = BatchedScheduler(
        encode_cluster(nodes2, pods2, cfg2, policy=EXACT), record=False
    )
    assert _placements(hyb2) == _placements(seq2)


def test_compact_eval_is_bit_identical():
    # pending-compaction is a pure execution-cost optimization: the same
    # cluster through compact and non-compact programs (both loop modes)
    # must produce identical assignments
    rng = np.random.default_rng(17)
    nodes = [node(f"n{i}", cpu=str(2 + int(rng.integers(3)))) for i in range(6)]
    pods = [
        pod(f"p{i}", cpu=f"{int(rng.integers(200, 800))}m") for i in range(30)
    ]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    for loop in ("dynamic", "static"):
        on = GangScheduler(enc, chunk=8, loop=loop, compact=True)
        off = GangScheduler(enc, chunk=8, loop=loop, compact=False)
        assert _placements(on) == _placements(off), loop
        np.testing.assert_array_equal(
            np.asarray(on._final_state.assignment),
            np.asarray(off._final_state.assignment),
        )


def test_compact_eval_with_preemption_phase():
    # compaction + the preempt phase: the phase hands back a state whose
    # pending set shrank mid-pass — placements must match the sequential
    # engine on the all-pods-need-eviction shape regardless of compact
    nodes = [node(f"n{i}", cpu="2", pods="8") for i in range(4)]
    pods = [
        pod(f"low-{i}", cpu="1500m", priority=1, node_name=f"n{i}")
        for i in range(4)
    ] + [pod(f"high-{i}", cpu="1200m", priority=100) for i in range(3)]
    cfg = _preempt_cfg()
    gang = GangScheduler(
        encode_cluster(nodes, pods, cfg, policy=EXACT), compact=True
    )
    seq = BatchedScheduler(
        encode_cluster(nodes, pods, cfg, policy=EXACT), record=False
    )
    assert _placements(gang) == _placements(seq)


def test_static_budget_auto_resumes():
    """A small static budget is a per-pass quantum, not a cap: run()
    auto-resumes exhausted passes of the same compiled program until the
    fixpoint, so starved budgets can no longer silently strand pods
    (the structural fix for ADVICE r3's under-budgeting trap)."""
    # 12 pods all pinned to one node: needs 12 committing rounds
    nodes = [node("n0", cpu="16", pods="110", labels={"k": "v"})]
    pods = [pod(f"p{i}", node_selector={"k": "v"}) for i in range(12)]
    cfg = restricted_config(
        filters=("NodeUnschedulable", "NodeName", "NodeAffinity", "NodeResourcesFit"),
    )
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    gang = GangScheduler(enc, loop="static", static_rounds=5)
    _, rounds = gang.run()
    assert all(v != "" for v in gang.placements().values())
    # resume really happened: committed rounds exceed one pass's budget
    assert int(np.asarray(rounds)) >= 12
    # the default budget (ceil(P/N)+4 per pass) also completes
    gang2 = GangScheduler(encode_cluster(nodes, pods, cfg, policy=EXACT),
                          loop="static")
    gang2.run()
    assert all(v != "" for v in gang2.placements().values())
    # an infeasible remainder must NOT trigger endless resumes: one
    # no-commit pass settles it
    pods2 = pods + [pod("misfit", node_selector={"k": "nope"})]
    gang3 = GangScheduler(
        encode_cluster(nodes, pods2, cfg, policy=EXACT),
        loop="static", static_rounds=6,
    )
    _, r3 = gang3.run()
    got = gang3.placements()
    assert got[("default", "misfit")] == ""
    assert sum(1 for v in got.values() if v) == 12
    assert int(np.asarray(r3)) == 12  # committed rounds only, finite


class TestEvalWindow:
    """eval_window: queue-prefix-bounded rounds (the chip lever for the
    eval-bound round wall — see GangScheduler.__init__). Placements are
    a valid greedy order; completeness and the window-offset sweep's
    fixpoint soundness are the load-bearing guarantees."""

    def _cfg(self):
        return restricted_config(
            filters=(
                "NodeUnschedulable", "NodeName", "NodeAffinity",
                "NodeResourcesFit",
            ),
        )

    def test_binding_window_places_all(self):
        # chunk=2 < P so the window actually binds each round
        nodes = [node(f"n{i}", cpu="8", pods="110") for i in range(3)]
        pods = [pod(f"p{i}", cpu="1") for i in range(18)]
        cfg = self._cfg()
        enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
        for loop in ("static", "dynamic"):
            gang = GangScheduler(
                enc, loop=loop, chunk=2, eval_window=2, rel_serialize=False
            )
            gang.run()
            assert all(v != "" for v in gang.placements().values()), loop

    def test_wide_window_matches_unwindowed(self):
        # W >= P: the window never binds, placements must be identical
        nodes = [node(f"n{i}", cpu="4", pods="110") for i in range(4)]
        pods = [pod(f"p{i}", cpu="1") for i in range(12)]
        cfg = self._cfg()
        enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
        wide = GangScheduler(enc, eval_window=64, chunk=4)
        plain = GangScheduler(enc, chunk=4)
        assert _placements(wide) == _placements(plain)

    def test_blocked_window_prefix_sweeps_to_feasible_pods(self):
        """First-in-queue pods are infeasible everywhere (no preemption
        in the config): windows over them commit nothing, so the carried
        offset must advance to deeper windows until the feasible pods
        place — without the offset sweep the loop would exit (dynamic)
        or burn its budget (static) with feasible pods stranded."""
        nodes = [node("n0", cpu="8", pods="110"), node("n1", cpu="8", pods="110")]
        # higher priority -> first in the PrioritySort queue
        blocked = [
            pod(f"big{i}", cpu="100", priority=100) for i in range(4)
        ]
        ok = [pod(f"ok{i}", cpu="1", priority=1) for i in range(8)]
        cfg = self._cfg()
        enc = encode_cluster(nodes, blocked + ok, cfg, policy=EXACT)
        for loop in ("static", "dynamic"):
            gang = GangScheduler(
                enc, loop=loop, chunk=2, eval_window=2, rel_serialize=False
            )
            _, rounds = gang.run()
            got = gang.placements()
            assert all(
                got[("default", f"ok{i}")] != "" for i in range(8)
            ), (loop, got)
            assert all(
                got[("default", f"big{i}")] == "" for i in range(4)
            ), (loop, got)
            # finite: no-commit window hops + committing rounds settle
            # well under the naive P-round ceiling
            assert int(np.asarray(rounds)) <= 24, loop

    def test_window_independent_of_compact(self):
        """A binding window routes rounds through its own row-subset
        pipeline, so it composes with compact=False (what vmapped
        sweeps use — vmapped cond can't skip anything anyway)."""
        nodes = [node(f"n{i}", cpu="8", pods="110") for i in range(3)]
        pods = [pod(f"p{i}", cpu="1") for i in range(18)]
        enc = encode_cluster(nodes, pods, self._cfg(), policy=EXACT)
        gang = GangScheduler(
            enc, compact=False, chunk=2, eval_window=2, rel_serialize=False
        )
        gang.run()
        assert all(v != "" for v in gang.placements().values())
        with pytest.raises(ValueError, match="eval_window"):
            GangScheduler(enc, eval_window=0)

    def test_explicit_budget_below_sweep_width_rejected(self):
        """An explicit static budget is a documented per-pass latency
        cap — silently raising it for the window sweep would break that
        contract, and honoring it would void the completeness proof, so
        the combination is rejected (code-review r5)."""
        nodes = [node("n0", cpu="8", pods="110")]
        pods = [pod(f"p{i}", cpu="1") for i in range(16)]
        enc = encode_cluster(nodes, pods, self._cfg(), policy=EXACT)
        with pytest.raises(ValueError, match="full eval_window sweep"):
            GangScheduler(
                enc, loop="static", chunk=2, eval_window=2, max_rounds=4
            )

    def test_explicit_dynamic_budget_below_sweep_width_rejected(self):
        """ADVICE r5 residue: the dynamic commit budget resets the
        window offset on every commit, so a cap below ceil(P/WP) can
        spend itself on the earliest windows and end the pass with later
        windows never evaluated — rejected loudly (mirroring static)
        instead of silently stranding feasible pods."""
        nodes = [node("n0", cpu="8", pods="110")]
        pods = [pod(f"p{i}", cpu="1") for i in range(16)]
        enc = encode_cluster(nodes, pods, self._cfg(), policy=EXACT)
        with pytest.raises(
            ValueError, match="dynamic per-pass commit budget"
        ):
            GangScheduler(
                enc, loop="dynamic", chunk=2, eval_window=2, max_rounds=4
            )
        # at exactly the sweep width the combination is legal
        GangScheduler(
            enc, loop="dynamic", chunk=2, eval_window=2, max_rounds=8
        )

    def test_dynamic_window_budget_scales_with_sweep_width(self):
        """Code-review r5 repro: on ONE schedulable node with a
        permanently infeasible window prefix, every commit is preceded
        by a no-commit sweep over the blocked windows (several rounds
        per pod). The default dynamic max_rounds must scale by the
        sweep width — at P+1 the while_loop exits early and silently
        strands feasible pods; there is no dynamic-mode auto-resume to
        catch it."""
        nodes = [node("n0", cpu="32", pods="110")]
        blocked = [pod(f"big{i}", cpu="100", priority=100) for i in range(2)]
        ok = [pod(f"ok{i}", cpu="1", priority=1) for i in range(8)]
        cfg = self._cfg()
        enc = encode_cluster(nodes, blocked + ok, cfg, policy=EXACT)
        gang = GangScheduler(
            enc, loop="dynamic", chunk=2, eval_window=2, rel_serialize=False
        )
        gang.run()
        got = gang.placements()
        assert all(got[("default", f"ok{i}")] != "" for i in range(8)), got
        assert all(got[("default", f"big{i}")] == "" for i in range(2)), got

    def test_explicit_dynamic_cap_counts_commit_rounds(self):
        """ADVICE r5: an explicit dynamic `max_rounds` below the window
        sweep's total round cost used to exhaust the while_loop
        mid-sweep and silently strand feasible pods (no-commit sweep
        rounds burned the cap). The cap is now denominated in COMMIT
        rounds — the unit it caps unwindowed, where every counted round
        commits — so a cap covering the commits completes regardless of
        how many sweep rounds the blocked prefix costs."""
        nodes = [node("n0", cpu="32", pods="110")]
        blocked = [pod(f"big{i}", cpu="100", priority=100) for i in range(2)]
        ok = [pod(f"ok{i}", cpu="1", priority=1) for i in range(8)]
        cfg = self._cfg()
        enc = encode_cluster(nodes, blocked + ok, cfg, policy=EXACT)
        # one node -> one commit per round: 8 commits needed, each
        # preceded by a no-commit hop over the infeasible prefix window,
        # so TOTAL rounds far exceed the cap of 12 — commit-counting is
        # what lets this complete
        gang = GangScheduler(
            enc, loop="dynamic", chunk=2, eval_window=2,
            rel_serialize=False, max_rounds=12,
        )
        _, rounds = gang.run()
        got = gang.placements()
        assert all(got[("default", f"ok{i}")] != "" for i in range(8)), got
        assert all(got[("default", f"big{i}")] == "" for i in range(2)), got
        assert int(np.asarray(rounds)) > 12  # sweep rounds ran uncapped
        # the cap still binds on commits: 7 < 8 feasible pods strands
        # the tail deterministically (the documented hard-cap role)
        capped = GangScheduler(
            enc, loop="dynamic", chunk=2, eval_window=2,
            rel_serialize=False, max_rounds=7,
        )
        capped.run()
        placed = sum(1 for v in capped.placements().values() if v)
        assert placed == 7

    def test_static_budget_covers_full_window_sweep(self):
        """Code-review r5 repro #2: an infeasible queue prefix spanning
        more windows than the static budget. The budget clamp
        (static_rounds >= ceil(P/WP)) keeps the auto-resume rule's
        'zero-commit pass means infeasible remainder' proof valid; an
        unclamped budget ends a pass mid-sweep with zero commits and
        the driver strands the feasible tail."""
        nodes = [node(f"n{i}", cpu="8", pods="110") for i in range(8)]
        blocked = [
            pod(f"big{i}", cpu="100", priority=100) for i in range(14)
        ]
        ok = [pod(f"ok{i}", cpu="1", priority=1) for i in range(2)]
        cfg = self._cfg()
        enc = encode_cluster(nodes, blocked + ok, cfg, policy=EXACT)
        gang = GangScheduler(
            enc, loop="static", chunk=2, eval_window=2, rel_serialize=False
        )
        assert gang.static_rounds >= 8  # the clamp engaged
        gang.run()
        got = gang.placements()
        assert all(got[("default", f"ok{i}")] != "" for i in range(2)), got
        assert all(got[("default", f"big{i}")] == "" for i in range(14))

    def test_windowed_static_sweep_with_blocked_prefix(self):
        """The GangSweep form of the same trap: every variant's static
        pass must survive an infeasible prefix wider than the naive
        budget (the per-variant-array resume rule breaks on any
        zero-commit pass, so the clamp must hold under vmap too)."""
        import numpy as np

        from kube_scheduler_simulator_tpu.parallel import GangSweep
        from kube_scheduler_simulator_tpu.parallel.sweep import weights_for

        nodes = [node(f"n{i}", cpu="8", pods="110") for i in range(8)]
        blocked = [
            pod(f"big{i}", cpu="100", priority=100) for i in range(14)
        ]
        ok = [pod(f"ok{i}", cpu="1", priority=1) for i in range(2)]
        cfg = self._cfg()
        enc = encode_cluster(nodes, blocked + ok, cfg, policy=EXACT)
        sweep = GangSweep(enc, chunk=2, loop="static", eval_window=2)
        w = np.stack([weights_for(enc, {}), weights_for(enc, {"NodeResourcesFit": 3})])
        assignments, _ = sweep.run(w)
        for d in sweep.placements(assignments):
            assert all(d[("default", f"ok{i}")] != "" for i in range(2)), d
