"""Gang (fixpoint) scheduler: correctness, determinism, divergence policy.

Gang mode (engine/gang.py) trades the sequential engine's bit-parity for
round-parallel throughput. Its contract (documented in the module):

  * one pod per node commits per round, earliest queue position wins;
  * committed placements are always feasible against the state they were
    evaluated on, and node-local constraints (resources, ports) can never
    be violated by same-round peers;
  * unschedulable pods are retried next round (the event-driven re-queue
    analogue), so affinity chains resolve across rounds;
  * no-contention workloads place identically to the sequential engine.
"""

import numpy as np
import pytest

from kube_scheduler_simulator_tpu.engine import (
    EXACT,
    BatchedScheduler,
    encode_cluster,
)
from kube_scheduler_simulator_tpu.engine.gang import GangScheduler
from kube_scheduler_simulator_tpu.sched.config import SchedulerConfiguration

from helpers import node, pod
from test_engine_parity import restricted_config


def _placements(sched):
    sched.run()
    return sched.placements()


def test_no_contention_matches_sequential():
    # each pod nodeSelector-pinned to its own node: one round, and the
    # placements must equal the sequential engine's exactly
    nodes = [node(f"n{i}", labels={"k": f"v{i}"}) for i in range(6)]
    pods = [pod(f"p{i}", node_selector={"k": f"v{i}"}) for i in range(6)]
    cfg = restricted_config(
        filters=("NodeUnschedulable", "NodeName", "NodeAffinity", "NodeResourcesFit"),
    )
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    gang = GangScheduler(enc)
    seq = BatchedScheduler(encode_cluster(nodes, pods, cfg, policy=EXACT), record=False)
    assert _placements(gang) == _placements(seq)
    assert int(np.asarray(gang._rounds)) == 2  # 1 committing + 1 empty


def test_contended_node_priority_order_and_capacity():
    # 4 pods all fit only n0 (n1 unschedulable); n0 holds exactly 2.
    # Queue order (PrioritySort) must win the contention rounds.
    nodes = [node("n0", cpu="2"), node("n1", cpu="8", unschedulable=True)]
    pods = [
        pod("lo1", cpu="1", priority=1),
        pod("hi", cpu="1", priority=10),
        pod("lo2", cpu="1", priority=1),
        pod("lo3", cpu="1", priority=1),
    ]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    gang = GangScheduler(enc)
    got = _placements(gang)
    assert got[("default", "hi")] == "n0"
    # exactly one of the priority-1 pods (the earliest in queue order,
    # which is input order among equals) fits next
    assert got[("default", "lo1")] == "n0"
    assert got[("default", "lo2")] == ""
    assert got[("default", "lo3")] == ""
    # matches the sequential engine bit-for-bit on this workload
    seq = BatchedScheduler(encode_cluster(nodes, pods, cfg, policy=EXACT), record=False)
    assert got == _placements(seq)


def test_random_cluster_contended_invariants():
    # moderately contended random cluster: under contention gang is a
    # deterministic greedy fixpoint, not sequential-identical (gang.py
    # divergence policy) — but it must (a) never violate capacity,
    # (b) schedule at least as many pods as the sequential pass (losers
    # are retried), (c) be deterministic.
    rng = np.random.default_rng(3)
    nodes = [node(f"n{i}", cpu=str(2 + int(rng.integers(3)))) for i in range(8)]
    pods = [
        pod(f"p{i}", cpu=f"{int(rng.integers(200, 900))}m",
            priority=int(rng.integers(3)))
        for i in range(40)
    ]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    gang = GangScheduler(enc, chunk=16)
    seq = BatchedScheduler(encode_cluster(nodes, pods, cfg, policy=EXACT), record=False)
    g, s = _placements(gang), _placements(seq)
    assert sum(1 for v in g.values() if v) >= sum(1 for v in s.values() if v)
    assert g == _placements(GangScheduler(enc, chunk=16))
    # capacity safety, independently recomputed
    used = {}
    for (ns, name), nn in g.items():
        if nn:
            p = next(pp for pp in pods if pp["metadata"]["name"] == name)
            req = p["spec"]["containers"][0]["resources"]["requests"]["cpu"]
            used[nn] = used.get(nn, 0) + int(req[:-1])
    for n_, total in used.items():
        alloc = next(nn for nn in nodes if nn["metadata"]["name"] == n_)
        assert total <= int(alloc["status"]["allocatable"]["cpu"]) * 1000


def test_determinism():
    rng = np.random.default_rng(7)
    nodes = [node(f"n{i}") for i in range(5)]
    pods = [pod(f"p{i}", cpu=f"{int(rng.integers(100, 500))}m") for i in range(20)]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    a = _placements(GangScheduler(enc))
    b = _placements(GangScheduler(enc))
    assert a == b


def test_affinity_chain_resolves_across_rounds():
    # backend requires affinity to frontend, but frontend sits LATER in
    # the queue (lower priority listed first in input order? — no:
    # equal priority, input order backend-first). Sequential: backend
    # fails (peer not bound yet). Gang: backend schedules in round 2 —
    # the documented retry divergence.
    nodes = [node(f"n{i}", labels={"zone": "z"}) for i in range(2)]
    aff = {
        "podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {
                    "labelSelector": {"matchLabels": {"app": "frontend"}},
                    "topologyKey": "zone",
                }
            ]
        }
    }
    pods = [
        pod("backend", affinity=aff),
        pod("frontend", labels={"app": "frontend"}),
    ]
    cfg = restricted_config(
        filters=("NodeUnschedulable", "NodeResourcesFit", "InterPodAffinity"),
        prefilters=("NodeResourcesFit", "InterPodAffinity"),
    )
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    gang = GangScheduler(enc)
    got = _placements(gang)
    assert got[("default", "frontend")] != ""
    assert got[("default", "backend")] != ""  # retried after peer bound
    seq = BatchedScheduler(encode_cluster(nodes, pods, cfg, policy=EXACT), record=False)
    assert _placements(seq)[("default", "backend")] == ""  # sequential can't


def test_infeasible_pods_terminate_quickly():
    nodes = [node("n0", cpu="1")]
    pods = [pod(f"p{i}", cpu="4") for i in range(10)]  # none fit
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    gang = GangScheduler(enc)
    state, rounds = gang.run()
    assert int(np.asarray(rounds)) == 1  # one empty round, then fixpoint
    assert all(v == "" for v in gang.placements().values())


def test_weight_sweep_vmap_matches_per_variant_runs():
    import jax
    import jax.numpy as jnp

    nodes = [node(f"n{i}", cpu=str(2 + i % 3)) for i in range(6)]
    pods = [pod(f"p{i}", cpu=f"{300 + 40 * (i % 5)}m") for i in range(18)]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    gang = GangScheduler(enc, chunk=8)
    order, _ = gang.order_arrays()
    wbase = np.asarray(gang.weights)
    variants = jnp.asarray(np.stack([wbase, wbase * 3, wbase + 7]), wbase.dtype)
    vrun = jax.jit(jax.vmap(gang.run_fn, in_axes=(None, None, None, 0)))
    vstate, vrounds = vrun(enc.arrays, enc.state0, order, variants)
    for i in range(3):
        state_i, _ = jax.jit(gang.run_fn)(
            enc.arrays, enc.state0, order, variants[i]
        )
        np.testing.assert_array_equal(
            np.asarray(vstate.assignment[i]), np.asarray(state_i.assignment)
        )


def test_rwop_claim_single_winner_per_round():
    # cluster-global constraint: two pods share a ReadWriteOncePod claim
    # and could win DIFFERENT nodes in the same round — the per-claim
    # conflict resolution must let exactly one commit (the earlier in
    # queue order), and the next round must reject the other
    from test_engine_parity_vol import claim_vol, pv, pvc, vol_config

    nodes = [node("n0"), node("n1")]
    pods = [
        pod("first", priority=10, volumes=[claim_vol("solo")]),
        pod("second", priority=1, volumes=[claim_vol("solo")]),
    ]
    kw = dict(
        pvcs=[pvc("solo", modes=("ReadWriteOncePod",), volume_name="pv-s")],
        pvs=[pv("pv-s")],
    )
    cfg = vol_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT, **kw)
    gang = GangScheduler(enc)
    got = _placements(gang)
    assert got[("default", "first")] != ""
    assert got[("default", "second")] == ""
    # matches the sequential engine on this workload
    seq = BatchedScheduler(
        encode_cluster(nodes, pods, cfg, policy=EXACT, **kw), record=False
    )
    assert got == _placements(seq)


def test_static_loop_matches_dynamic():
    # the counted-loop (scan-only) variant must place identically to the
    # while_loop variant — no-op rounds past the fixpoint change nothing
    rng = np.random.default_rng(11)
    nodes = [node(f"n{i}", cpu=str(2 + int(rng.integers(3)))) for i in range(6)]
    pods = [
        pod(f"p{i}", cpu=f"{int(rng.integers(200, 800))}m") for i in range(30)
    ]
    cfg = restricted_config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    # equal inner depth => provably identical placements (gang.py note)
    dyn = GangScheduler(enc, chunk=16, inner_iters=12)
    stat = GangScheduler(enc, chunk=16, loop="static", inner_iters=12)
    assert _placements(dyn) == _placements(stat)
    # rounds reported = rounds that committed something
    assert int(np.asarray(stat._rounds)) == int(np.asarray(dyn._rounds)) - 1


def test_static_loop_rwop_claims():
    from test_engine_parity_vol import claim_vol, pv, pvc, vol_config

    nodes = [node("n0"), node("n1")]
    pods = [
        pod("first", priority=10, volumes=[claim_vol("solo")]),
        pod("second", priority=1, volumes=[claim_vol("solo")]),
    ]
    kw = dict(
        pvcs=[pvc("solo", modes=("ReadWriteOncePod",), volume_name="pv-s")],
        pvs=[pv("pv-s")],
    )
    enc = encode_cluster(nodes, pods, vol_config(), policy=EXACT, **kw)
    got = _placements(GangScheduler(enc, loop="static"))
    assert got[("default", "first")] != ""
    assert got[("default", "second")] == ""


def test_full_default_config_accepted_postfilter_skipped():
    from kube_scheduler_simulator_tpu.engine.engine import supported_config

    nodes = [node(f"n{i}") for i in range(3)]
    pods = [pod(f"p{i}") for i in range(5)]
    enc = encode_cluster(nodes, pods, supported_config(), policy=EXACT)
    gang = GangScheduler(enc)
    assert gang.skipped_postfilter == ["DefaultPreemption"]
    got = _placements(gang)
    assert all(v != "" for v in got.values())
